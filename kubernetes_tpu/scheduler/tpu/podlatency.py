"""Pod latency ledger: per-pod end-to-end latency decomposition.

The wave flight recorder answers "where did wave k spend its time"; this
ledger answers "where did *pod p* spend its 4.55 seconds". Every pod gets
an entry stamped at each lifecycle edge — watch arrival (informer
deliver), queue admission, wave admission, kernel verdict, bind dispatch,
bind commit, and (when a kubelet is in the loop) status ack — so e2e
latency decomposes into exact per-segment durations instead of one
opaque SLI number.

Like the flight recorder, all recording is HOST-SIDE ONLY (OBS01): stamps
are perf_counter reads behind a lock, nothing runs inside jitted code,
no rng is consumed, and no scheduling decision reads the ledger — the
bit-compat goldens hold with the ledger on or off. Per-pod cost is one
dict write per edge; quantile gauges update once per wave, not per pod.

Edge semantics: `watch_arrival`/`queue_admission` are first-wins (a
requeue after backoff must not erase when the pod really arrived), the
later edges are last-wins — a pod that fails binding and retries reports
the *successful* attempt's decomposition, with the retry time absorbed
into its queue_wait segment. `status_ack` lands after completion, onto
the retained entry.

Every metric series this module emits is declared in LEDGER_SERIES and
registered in scheduler/metrics.py; kubesched-lint rule OBS02
cross-parses the two files to keep them in sync (the FI01 pattern).
"""

from __future__ import annotations

import collections
import math
import threading
import time

# Series this ledger emits. OBS02 checks (a) every name here is registered
# in scheduler/metrics.py and (b) every _series() call site uses a literal
# name from this tuple. Keep it a literal tuple of string constants.
LEDGER_SERIES = (
    "scheduler_pod_e2e_latency_seconds",
    "scheduler_pod_e2e_latency_quantile_seconds",
)

# lifecycle edges, in pipeline order
EDGES = (
    "watch_arrival",    # informer delivered the ADDED event
    "queue_admission",  # pod entered the scheduling queue
    "wave_admission",   # pod popped into a batched wave (or host cycle)
    "kernel_verdict",   # device kernel / host algorithm picked a node
    "gang_wait_start",  # gang member entered the Permit wait (gang pods only)
    "gang_wait_end",    # gang quorum allowed the member (or wait rejected)
    "bind_dispatch",    # bind call handed to the dispatcher
    "bind_commit",      # bind durably applied to the store
    "status_ack",       # kubelet reported the pod Running (when present)
)

# segment name -> (from_edge, to_edge); e2e spans the whole pipeline
SEGMENTS = (
    ("informer", "watch_arrival", "queue_admission"),
    ("queue_wait", "queue_admission", "wave_admission"),
    ("kernel", "wave_admission", "kernel_verdict"),
    # gang pods only: time parked at Permit until quorum (subset of the
    # bind_dispatch segment, which keeps its kernel_verdict anchor)
    ("gang_wait", "gang_wait_start", "gang_wait_end"),
    ("bind_dispatch", "kernel_verdict", "bind_dispatch"),
    ("bind_commit", "bind_dispatch", "bind_commit"),
    ("status_ack", "bind_commit", "status_ack"),
    ("e2e", "watch_arrival", "bind_commit"),
)
SEGMENT_NAMES = tuple(s[0] for s in SEGMENTS)

_FIRST_WINS = ("watch_arrival", "queue_admission")

DEFAULT_CAPACITY = 256   # completed entries retained for the zpage/dump
DEFAULT_OPEN_CAP = 8192  # open entries before oldest-first shedding
DEFAULT_WINDOW = 8192    # per-segment quantile sample window


class StreamingQuantile:
    """Exact quantiles over a bounded streaming window.

    Samples accumulate up to `window`; on overflow the oldest half is
    dropped (deterministic — no sampling, no rng), so quantiles are exact
    over the retained window. `quantile(q)` uses the inverted-CDF
    definition (`sorted[ceil(q*n) - 1]`), matching
    `numpy.percentile(..., method="inverted_cdf")` — the golden test pins
    this equivalence on fixed seeds.
    """

    __slots__ = ("window", "_samples", "_sorted", "total_n")

    def __init__(self, window: int = DEFAULT_WINDOW):
        self.window = max(int(window), 2)
        self._samples: list[float] = []
        self._sorted: list[float] | None = None
        self.total_n = 0  # lifetime count, survives window compression

    def add(self, value: float) -> None:
        self._samples.append(value)
        self._sorted = None
        self.total_n += 1
        if len(self._samples) > self.window:
            del self._samples[: self.window // 2]

    def n(self) -> int:
        return len(self._samples)

    def quantile(self, q: float) -> float | None:
        """Inverted-CDF quantile over the retained window; None if empty."""
        if not self._samples:
            return None
        if self._sorted is None:
            self._sorted = sorted(self._samples)
        n = len(self._sorted)
        idx = max(math.ceil(q * n) - 1, 0)
        return self._sorted[min(idx, n - 1)]


class PodLedgerEntry:
    """One pod's lifecycle stamps (perf_counter seconds) and, once
    completed, its per-segment decomposition."""

    __slots__ = ("key", "stamps", "wave_id", "arrived_at", "segments")

    def __init__(self, key: str):
        self.key = key
        self.stamps: dict[str, float] = {}
        self.wave_id: int | None = None  # exemplar link -> wave/<id> span
        self.arrived_at = time.time()    # wall clock, for correlation
        self.segments: dict[str, float] = {}

    def to_dict(self) -> dict:
        d = {
            "pod": self.key,
            "arrived_at": self.arrived_at,
            "segments": {k: round(v, 6) for k, v in self.segments.items()},
        }
        if self.wave_id is not None:
            d["wave_id"] = self.wave_id
            d["span"] = f"wave/{self.wave_id}"  # trace exemplar link
        return d


class PodLatencyLedger:
    """Per-pod lifecycle stamps -> exact segment decomposition + quantiles.

    Owned by the FlightRecorder (one per scheduler); stamped from the
    informer callback, the wave loop, and the binding path. `enabled`
    exists for the bit-compat golden — production keeps it on.
    """

    def __init__(self, metrics=None, capacity: int = DEFAULT_CAPACITY,
                 open_cap: int = DEFAULT_OPEN_CAP,
                 window: int = DEFAULT_WINDOW):
        self.enabled = True
        self.metrics = metrics
        self.capacity = capacity
        self.open_cap = open_cap
        self._lock = threading.Lock()
        self._open: dict[str, PodLedgerEntry] = {}
        # completed ring + by-key view of it (for late status acks)
        self._completed: collections.deque[PodLedgerEntry] = collections.deque()
        self._recent: dict[str, PodLedgerEntry] = {}
        self._estimators = {
            name: StreamingQuantile(window) for name in SEGMENT_NAMES
        }
        self.completed_total = 0
        self.dropped_open = 0  # open entries shed at open_cap

    # -- emission (every name literal, declared in LEDGER_SERIES: OBS02) ----

    def _series(self, name: str):
        m = self.metrics
        registry = getattr(m, "registry", None) if m is not None else None
        return registry.get(name) if registry is not None else None

    # -- stamping ------------------------------------------------------------

    def stamp(self, key: str, edge: str, wave_id: int | None = None) -> None:
        """Record that `key` crossed `edge` now. Cheap and decision-free."""
        if not self.enabled:
            return
        now = time.perf_counter()
        with self._lock:
            entry = self._open.get(key)
            if entry is None:
                if edge == "status_ack":
                    self._late_status_ack(key, now)
                    return
                entry = self._open[key] = PodLedgerEntry(key)
                if len(self._open) > self.open_cap:
                    oldest = next(iter(self._open))
                    del self._open[oldest]
                    self.dropped_open += 1
            if edge in _FIRST_WINS:
                entry.stamps.setdefault(edge, now)
            else:
                entry.stamps[edge] = now
            if wave_id is not None:
                entry.wave_id = wave_id

    def _late_status_ack(self, key: str, now: float) -> None:
        """Kubelet ack arriving after bind_commit completed the entry
        (the common case) — stamp the retained entry. Lock held."""
        entry = self._recent.get(key)
        if entry is None or "status_ack" in entry.stamps:
            return
        entry.stamps["status_ack"] = now
        commit = entry.stamps.get("bind_commit")
        if commit is None:
            return
        dt = max(now - commit, 0.0)
        entry.segments["status_ack"] = dt
        self._estimators["status_ack"].add(dt)
        hist = self._series("scheduler_pod_e2e_latency_seconds")
        if hist is not None:
            hist.observe(dt, "status_ack")

    def complete(self, key: str) -> PodLedgerEntry | None:
        """Close the pod's entry at bind commit: compute segments, feed
        the quantile estimators, land the histogram, retain for the zpage."""
        if not self.enabled:
            return None
        with self._lock:
            entry = self._open.pop(key, None)
            if entry is None:
                return None
            stamps = entry.stamps
            for name, frm, to in SEGMENTS:
                if frm in stamps and to in stamps:
                    entry.segments[name] = max(stamps[to] - stamps[frm], 0.0)
            for name, value in entry.segments.items():
                self._estimators[name].add(value)
            self.completed_total += 1
            self._completed.append(entry)
            self._recent[entry.key] = entry
            while len(self._completed) > self.capacity:
                old = self._completed.popleft()
                if self._recent.get(old.key) is old:
                    del self._recent[old.key]
            segments = dict(entry.segments)
        hist = self._series("scheduler_pod_e2e_latency_seconds")
        if hist is not None:
            for name, value in segments.items():
                hist.observe(value, name)
        return entry

    def forget(self, key: str) -> None:
        """Pod left the system unscheduled (deleted) — drop its open entry
        so churn of never-scheduled pods doesn't leak state."""
        with self._lock:
            self._open.pop(key, None)

    # -- gauges (once per wave, from FlightRecorder.end_wave) ----------------

    def update_gauges(self) -> None:
        gauge = self._series("scheduler_pod_e2e_latency_quantile_seconds")
        if gauge is None:
            return
        for name, p50, p99 in self._quantile_rows():
            gauge.set(p50, name, "p50")
            gauge.set(p99, name, "p99")

    def _quantile_rows(self) -> list[tuple[str, float, float]]:
        with self._lock:
            out = []
            for name in SEGMENT_NAMES:
                est = self._estimators[name]
                if est.n():
                    out.append((name, est.quantile(0.50), est.quantile(0.99)))
            return out

    # -- queries / snapshots -------------------------------------------------

    def segment_quantiles(self) -> dict:
        """{segment: {p50, p99, n}} over each estimator's retained window."""
        with self._lock:
            out = {}
            for name in SEGMENT_NAMES:
                est = self._estimators[name]
                if est.n():
                    out[name] = {
                        "p50": round(est.quantile(0.50), 6),
                        "p99": round(est.quantile(0.99), 6),
                        "n": est.total_n,
                    }
            return out

    def summary(self) -> dict:
        with self._lock:
            open_entries = len(self._open)
        return {
            "pods_completed": self.completed_total,
            "open_entries": open_entries,
            "dropped_open": self.dropped_open,
            "segments": self.segment_quantiles(),
        }

    def snapshot(self, last: int | None = None,
                 slowest: int | None = None) -> dict:
        """The /debug/podlatency zpage payload: summary + recent entries
        + the slowest retained entries by e2e."""
        with self._lock:
            completed = list(self._completed)
        out = {"summary": self.summary()}
        if last:
            out["last"] = [e.to_dict() for e in completed[-last:]]
        if slowest:
            ranked = sorted(completed,
                            key=lambda e: e.segments.get("e2e", 0.0),
                            reverse=True)
            out["slowest"] = [e.to_dict() for e in ranked[:slowest]]
        return out
