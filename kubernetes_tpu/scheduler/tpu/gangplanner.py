"""Gang-wave planner: whole-PodGroup admission onto the device gang kernel.

The host pod-group cycle (schedule_one.py schedule_pod_group) reproduces
the reference's scheduleOnePodGroup: enumerate topology placements, dry-run
the whole gang once per placement in a narrowed snapshot, score the fitting
domains, then run the default algorithm under the winner. Every dry run is
a sequence of single-pod kernel dispatches plus a full snapshot plane
rebuild per placement — the slow path for exactly the workload this
scheduler exists for (PAPER.md: GenericWorkload gangs + KEP-5732 packing).

This module is the admission gate and host-side half of the fast path: it
decides whether a popped gang is fully device-placeable, replicates the
host's placement enumeration (the SAME PlacementGenerate plugin calls, so
domain set, order, requiredDomain pin and error statuses can never
diverge), and hands the resolved GangPlan to TPUBackend.run_gang — one
program that scans the gang over every domain mask at once.

Fallback contract: the device path handles ONLY the success case. Every
odd case — no feasible domain in Required mode, tie-word overflow, plugin
error status, hybrid/host-compose members, nominated pods, open breaker,
sharded mesh, too many domains — returns None with the rng and snapshot
untouched, and the full host `_pod_group_algorithm` runs as if the device
attempt never happened. That is what makes gang-on device placement
bit-compatible: the host path IS the semantics; the device path is an
equal-output shortcut for the common case.

GANG01 (analysis/gang_seam.py): the gang admission/placement state — the
GangPlan fields and the WaveRecord gang_* outcome fields — is writable
only in this module and in backend.py; everything else observes.
"""

from __future__ import annotations

from ...utils.logging import get_logger
from ..cache.snapshot import Placement
from ..framework.cycle_state import CycleState

_log = get_logger("gangplanner")

# program-shape guards: a gang spanning more domains than this (pow2-padded
# mask rows) or more members than this rides the host cycle — huge domain
# fans are rare and the masked vmap's memory grows with D * the scan state
MAX_GANG_DOMAINS = 32
MAX_GANG_MEMBERS = 128


class GangPlan:
    """One PodGroup's resolved device placement plan.

    gang_placements holds the host PlacementGenerate output in plugin
    order — rows [0, gang_n_constrained) are topology domains, and when
    gang_has_fallback the final row is the unconstrained parent placement
    (Preferred topology / plugin-less gangs). These attributes are the
    GANG01-protected group admission state."""

    __slots__ = ("gang_placements", "gang_n_constrained",
                 "gang_has_fallback", "gang_required")

    def __init__(self, placements, n_constrained, has_fallback, required):
        self.gang_placements = placements
        self.gang_n_constrained = n_constrained
        self.gang_has_fallback = has_fallback
        self.gang_required = required


def _member_device_eligible(algo, pod) -> bool:
    """Is this member's decision FULLY modeled by the gang kernel?

    Anything needing a host stage — volume claims, DRA, declared features,
    extenders (the hybrid path), nominated-pod simulation, a nominee fast
    path — sends the whole group to the host cycle: all-or-nothing applies
    to the placement algorithm too, a gang must not split across tiers."""
    if pod.status.nominated_node_name:
        return False
    if algo._has_relevant_nominations(pod):
        return False
    if algo._needs_host_compose(pod):
        return False
    return True


def plan_gang(sched, fw, qpis) -> GangPlan | None:
    """Replicate _pod_group_algorithm's placement enumeration exactly.

    Runs the same run_placement_generate_plugins call on a scratch cycle
    state (the plugins are pure reads of store/cache), applies the same
    `narrowed = placements != [parent]` single-placement-still-constrains
    rule, and derives Required mode from the same topology_mode probe.
    A plugin error status returns None — the host cycle re-runs the
    plugins and surfaces the identical error outcome."""
    pods = [q.pod for q in qpis]
    parent = Placement(
        "all", [ni.name for ni in sched.snapshot.list_nodes()]
    )
    placements = None
    narrowed = False
    required = False
    if fw.placement_generate_plugins:
        pstate = CycleState()
        placements, st = fw.run_placement_generate_plugins(
            pstate, pods, parent
        )
        if not st.is_success and not st.is_skip:
            return None  # host cycle reproduces the error status
        narrowed = placements != [parent]
        for p in fw.placement_generate_plugins:
            mode = getattr(p, "topology_mode", lambda _p: None)(pods)
            required = required or mode == "Required"
    if placements is not None and narrowed:
        constrained = list(placements)
        if required:
            # Required topology: no unconstrained fallback row — a gang no
            # domain holds is unschedulable (host status reproduced on
            # the fallback path)
            return GangPlan(constrained, len(constrained), False, True)
        return GangPlan(constrained + [parent], len(constrained), True,
                        False)
    # no placement plugins / skipped / not narrowed: the host runs the
    # default algorithm on the whole snapshot — one unconstrained row
    return GangPlan([parent], 0, True, required)


def try_gang_wave(sched, fw, algo, gk: str, qpis: list):
    """Attempt whole-gang device placement; returns hosts aligned with
    `qpis` on success, else None (the host cycle takes the group).

    Every None path leaves the rng, snapshot and cache untouched and
    counts the members on the "host" side of the gang routing metric; the
    backend counts the "device" side on success."""
    from .backend import TPUSchedulingAlgorithm

    if not isinstance(algo, TPUSchedulingAlgorithm):
        return None
    backend = algo.backend
    recorder = backend.recorder

    def host_path():
        recorder.count_gang_pods("host", len(qpis))
        return None

    if not qpis or sched.snapshot.num_nodes() == 0:
        return host_path()
    if backend._ctx.n_shards != 1:
        # mesh seam: domain masks aren't sharded over the node axis yet
        return host_path()
    if algo.breaker.device_blocked():
        return host_path()
    if len(qpis) > MAX_GANG_MEMBERS:
        return host_path()
    if not all(_member_device_eligible(algo, q.pod) for q in qpis):
        return host_path()
    plan = plan_gang(sched, fw, qpis)
    if plan is None or len(plan.gang_placements) > MAX_GANG_DOMAINS:
        return host_path()
    try:
        res = backend.run_gang(
            [q.pod for q in qpis], sched.snapshot, plan.gang_placements,
            plan.gang_n_constrained, plan.gang_has_fallback, algo.rng,
        )
    except Exception as e:  # noqa: BLE001 — degrade, never break the cycle
        _log.error("gang wave failed; host cycle takes the group",
                   group=gk, members=len(qpis), error=str(e))
        algo.fallback_count += len(qpis)
        return host_path()
    if res is None:
        algo.fallback_count += len(qpis)
        return host_path()
    hosts, _win_d, _rec = res
    algo.kernel_count += len(qpis)
    return hosts
