"""Adaptive wave sizing for the streaming wave pipeline.

The wave loop's capacity knob used to be one constant (`Profile.wave_size`
/ the `schedule_wave(max_pods)` cap): great under sustained backlog, but a
light trickle then waits for nothing (a 512-slot program to place 3 pods)
and a burst gets no headroom beyond the constant. The controller sizes the
NEXT wave from the scheduling queue's observed depth instead — Kant's
(arxiv 2510.01256) load-adaptive batching applied to the pods×nodes
kernel: small waves under light arrival (latency), large waves under
backlog (throughput).

Determinism contract: the PRIMARY signal is queue depth — a pure function
of store/informer state, so the trace bench's virtual-time rows stay
bit-identical across runs (`trace_bench.DETERMINISTIC_KEYS`). The
wall-clock latency guard (AIMD: halve the size ceiling when observed
per-wave latency blows the budget, recover one pow2 step per good wave)
is OPT-IN via `KUBE_TPU_WAVE_LATENCY_S` precisely because wall time is
not deterministic; it ships disabled for every bench row.

Sizes are pow2-bucketed (floor `KUBE_TPU_WAVE_MIN_PODS`, default 8) so the
controller never fans out XLA program shapes beyond the buckets the wave
padding in `schedule_wave` already compiles. The circuit breaker's
HALF_OPEN probe sizing (`schedule_one.PROBE_WAVE_PODS`) stays authoritative:
the pop loop's probe break caps a recovering device's wave regardless of
what the controller asked for — the controller sizes load, the breaker
sizes risk.

Env knobs:
- KUBE_TPU_WAVE_MIN_PODS  (default 8): pow2 floor for any wave
- KUBE_TPU_WAVE_MAX_PODS  (default 0 = use the caller's cap): hard ceiling
- KUBE_TPU_WAVE_LATENCY_S (default unset = guard off): per-wave latency
  budget for the AIMD guard
"""

from __future__ import annotations

import os


def _next_pow2(n: int, floor: int = 1) -> int:
    p = max(1, floor)
    while p < n:
        p <<= 1
    return p


class WaveSizeController:
    """Sizes the next batched wave from queue depth (+ optional latency).

    One instance is owned by the ScheduleOneLoop and consulted at the top
    of every `schedule_wave` call; `observe()` feeds it completed waves'
    durations (a no-op unless the latency guard is armed).
    """

    def __init__(self, min_pods: int | None = None,
                 max_pods: int | None = None,
                 latency_budget_s: float | None = None):
        env = os.environ.get
        self.min_pods = _next_pow2(int(
            env("KUBE_TPU_WAVE_MIN_PODS", "8")) if min_pods is None
            else min_pods)
        self.max_pods = int(
            env("KUBE_TPU_WAVE_MAX_PODS", "0")) if max_pods is None \
            else max_pods
        if latency_budget_s is None:
            raw = env("KUBE_TPU_WAVE_LATENCY_S", "")
            latency_budget_s = float(raw) if raw else None
        self.latency_budget_s = latency_budget_s or None
        # AIMD ceiling driven by the latency guard; None = wide open
        self._soft_max: int | None = None
        # decision trail for bench/debug dumps (bounded)
        self.sized_waves = 0
        # capacity-gate signal for the stall profiler: True when the last
        # next_size() wanted more slots than the caller's cap allowed —
        # the ticked trace regime's per-tick gate. Deterministic (queue
        # depth in, bool out); the profiler only reads it.
        self.last_clipped = False
        self.capped_waves = 0

    def next_size(self, backlog: int, cap: int) -> int:
        """Target pod count for the next wave.

        `backlog` is the queue's active-pod depth (deterministic);
        `cap` is the caller's legacy max_pods and stays a hard ceiling —
        existing callers that ask for 512-pod waves under a dumped backlog
        still get exactly 512."""
        ceiling = cap
        if self.max_pods > 0:
            ceiling = min(ceiling, self.max_pods)
        if self._soft_max is not None:
            ceiling = min(ceiling, self._soft_max)
        # +1: the pod about to be popped may not be counted as active yet
        target = _next_pow2(backlog + 1, self.min_pods)
        self.sized_waves += 1
        self.last_clipped = target > ceiling
        if self.last_clipped:
            self.capped_waves += 1
        return max(1, min(target, ceiling))

    def observe(self, wave_duration_s: float) -> None:
        """AIMD latency guard (opt-in): a wave over budget halves the size
        ceiling; a wave under budget recovers one pow2 step."""
        budget = self.latency_budget_s
        if budget is None:
            return
        if wave_duration_s > budget:
            base = self._soft_max if self._soft_max is not None else \
                max(self.max_pods, self.min_pods * 4)
            self._soft_max = max(self.min_pods, base // 2)
        elif self._soft_max is not None:
            doubled = self._soft_max * 2
            limit = self.max_pods if self.max_pods > 0 else doubled
            self._soft_max = doubled if doubled < limit else None

    def snapshot(self) -> dict:
        return {
            "min_pods": self.min_pods,
            "max_pods": self.max_pods,
            "latency_budget_s": self.latency_budget_s,
            "soft_max": self._soft_max,
            "sized_waves": self.sized_waves,
            "capped_waves": self.capped_waves,
        }
