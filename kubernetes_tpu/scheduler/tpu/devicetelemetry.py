"""Device telemetry: transfer ledger, compile tracker, memory watermark.

The flight recorder answers "where did wave k spend its time" and the pod
ledger answers "where did pod p spend its seconds"; this module answers
the device-side questions those two cannot see: how many bytes crossed
the host<->device boundary (and for which plane), how often XLA had to
compile a fresh program (and for which shape), and how many bytes of
plane buffers are resident on the device right now.

Three instruments, one owner (the FlightRecorder, like the pod ledger):

- **Transfer ledger** — every host->device upload and device->host fetch
  in scheduler/tpu/backend.py routes through the accounted seam
  (`accounted_put` / `accounted_fetch`, or the accounting-only
  `account_upload` for bytes the jit call moves implicitly). Each call
  names a plane from TRANSFER_PLANES; bytes accumulate per plane and
  per direction, and per wave onto `WaveRecord.upload_bytes` /
  `fetch_bytes` / `*_by_plane`. kubesched-lint rule OBS03
  (analysis/transfer_seam.py) cross-parses backend.py to keep every
  `device_put` behind this seam and every plane name declared here.
- **Compile tracker** — `compile_span(kernel, signature)` wraps each
  jitted entry point. The first time a (kernel, signature) pair is seen
  the call is a jit cache miss (jax caches on static args + array
  avals, which the signature mirrors), so its wall time is the
  compile+run cost: it is counted, labelled with a compact shape label,
  and recorded as a `compile/<kernel>` phase on the wave record.
- **Memory watermark** — `note_resident(group, nbytes)` tracks the
  bytes of each device-resident buffer group (base planes, affinity
  tables, carry overlay, signature table); live bytes are the sum, the
  watermark is the running max, and jax `memory_stats()` (when jax is
  already imported — this module never imports it) is emitted alongside
  as a cross-check.

Everything here is HOST-SIDE ONLY (OBS01): accounting happens around
device calls, never inside jitted code, consumes no rng, and no
scheduling decision reads the telemetry — the bit-compat goldens hold
with telemetry on or off. `accounted_put` preserves values and dtypes
exactly (it calls the same `device_put` the backend would), so routing
a transfer through the seam cannot change a binding.

Every metric series this module emits is declared in LEDGER_SERIES and
registered in scheduler/metrics.py; kubesched-lint rule OBS02
cross-parses the two files to keep them in sync (the FI01 pattern).
"""

from __future__ import annotations

import contextlib
import hashlib
import sys
import threading
import time

import numpy as np

# Series this telemetry emits. OBS02 checks (a) every name here is
# registered in scheduler/metrics.py and (b) every _series() call site
# uses a literal name from this tuple. Keep it a literal tuple.
LEDGER_SERIES = (
    "scheduler_tpu_transfer_bytes_total",
    "scheduler_tpu_compiles_total",
    "scheduler_tpu_compiled_shapes",
    "scheduler_tpu_device_memory_bytes",
)

# Named planes a seam call may attribute transfer bytes to. OBS03
# cross-parses this tuple against every seam call site in the tree:
# the plane argument must be a string literal naming one of these.
# Keep it a literal tuple of string constants.
TRANSFER_PLANES = (
    "node_planes",      # full base-mirror upload of every node plane
    "carry_scatter",    # legacy name for the base-mirror row scatter
    "delta_rows",       # O(churn) gathered rows of the delta scatter
    "delta_idx",        # pow2-padded row-index vector of the delta scatter
    "affinity_tables",  # interned (anti-)affinity signature tables
    "ipa_term_key",     # global IPA term-key table refresh
    "features",         # the wave's stacked pod features + tie words
    "gang_masks",       # gang wave's [D, Nb] topology-domain mask stack
    "results",          # packed winners/cursor fetch at collect
    "scores",           # per-node score/fail rows (single-pod, sig export)
)

# Device-resident buffer groups for the memory watermark.
RESIDENT_GROUPS = ("planes", "tables", "carry", "sig_table")

UPLOAD = "upload"
FETCH = "fetch"


def tree_nbytes(tree) -> int:
    """Total nbytes of an array, or of every value of a dict of arrays."""
    if tree is None:
        return 0
    if isinstance(tree, dict):
        return sum(tree_nbytes(v) for v in tree.values())
    return int(getattr(tree, "nbytes", 0) or 0)


def _shape_label(signature) -> str:
    """Deterministic compact fallback label for a compile signature.

    Call sites pass an explicit structural label (e.g. "pad32/g8");
    this digest is only the fallback, and it must be stable across
    processes (str hashing is salted, hashlib is not) so bench
    artifacts compare across runs.
    """
    digest = hashlib.md5(repr(signature).encode()).hexdigest()[:10]
    return f"sig-{digest}"


class DeviceTelemetry:
    """Accounted transfer seam + compile tracker + memory watermark.

    Owned by the FlightRecorder (one per scheduler); called from the
    backend around its device seams. `enabled` exists for the
    bit-compat golden — production keeps it on. When disabled the seam
    still performs the underlying put/fetch (the backend depends on the
    return value) and only the accounting is skipped.
    """

    def __init__(self, metrics=None):
        self.enabled = True
        self.metrics = metrics
        self._lock = threading.Lock()
        # direction -> {plane: cumulative bytes}
        self._transfers: dict[str, dict[str, int]] = {UPLOAD: {}, FETCH: {}}
        self._totals: dict[str, int] = {UPLOAD: 0, FETCH: 0}
        # compile tracker: first-seen (kernel, signature) == jit cache miss
        self._compiled: set = set()
        self._compiles: dict[str, int] = {}
        self._compile_seconds: dict[str, float] = {}
        self._shapes: dict[str, set[str]] = {}
        # memory watermark: group -> currently resident bytes
        self._resident: dict[str, int] = {}
        self._watermark = 0
        # warm-restart baseline: compile count at the end of the warmup
        # phase — compile_count_since_warm() is the "compile-free warm
        # restart" assertion's zero
        self._warm_compile_base = 0

    # -- emission (every name literal, declared in LEDGER_SERIES: OBS02) ----

    def _series(self, name: str):
        m = self.metrics
        registry = getattr(m, "registry", None) if m is not None else None
        return registry.get(name) if registry is not None else None

    # -- transfer ledger -----------------------------------------------------

    def accounted_put(self, plane: str, tree, put, record=None):
        """Host->device upload through the accounted seam.

        `put` is the device placement function (a context's `put(value,
        name=None)` seam, or bare jax.device_put for scalars/arrays); it
        is applied per leaf — for a dict the leaf's key rides along as
        `name` so a sharded context can look up the plane's node axis —
        and the returned mirror has exactly the values, dtypes and
        structure a direct put would produce: the seam is bit-compatible
        by construction. Bytes are attributed to `plane` (and to
        `record` when the upload belongs to a wave).
        """
        if isinstance(tree, dict):
            out = {k: put(v, k) for k, v in tree.items()}
        else:
            out = put(tree)
        self._account(UPLOAD, plane, tree_nbytes(tree), record)
        return out

    def accounted_fetch(self, plane: str, value, record=None):
        """Device->host fetch through the accounted seam (np.asarray)."""
        host = np.asarray(value)
        self._account(FETCH, plane, int(host.nbytes), record)
        return host

    def account_upload(self, plane: str, nbytes: int, record=None) -> None:
        """Accounting-only upload entry, for bytes a jit call transfers
        implicitly (the wave's feature arrays cross with the dispatch)."""
        self._account(UPLOAD, plane, nbytes, record)

    def account_fetch(self, plane: str, nbytes: int, record=None) -> None:
        """Accounting-only fetch entry (value already on host)."""
        self._account(FETCH, plane, nbytes, record)

    def _account(self, direction: str, plane: str, nbytes, record) -> None:
        if not self.enabled:
            return
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._lock:
            by_plane = self._transfers[direction]
            by_plane[plane] = by_plane.get(plane, 0) + nbytes
            self._totals[direction] += nbytes
        if record is not None:
            if direction == UPLOAD:
                record.upload_bytes += nbytes
                record.upload_by_plane[plane] = (
                    record.upload_by_plane.get(plane, 0) + nbytes)
            else:
                record.fetch_bytes += nbytes
                record.fetch_by_plane[plane] = (
                    record.fetch_by_plane.get(plane, 0) + nbytes)
            self.stamp_watermark(record)
        counter = self._series("scheduler_tpu_transfer_bytes_total")
        if counter is not None:
            counter.inc(direction, plane, by=float(nbytes))

    # -- compile tracker -----------------------------------------------------

    @contextlib.contextmanager
    def compile_span(self, kernel: str, signature, label: str | None = None,
                     record=None):
        """Wrap a jitted entry point; first-seen signature == cache miss.

        jax's jit cache keys on static args + array avals; `signature`
        is the host-side mirror of that key, so the first call with a
        fresh signature pays tracing + XLA compilation and its wall time
        is recorded as the `compile/<kernel>` phase. Later calls with a
        seen signature yield with zero overhead beyond a set lookup.
        """
        if not self.enabled:
            yield
            return
        key = (kernel, signature)
        with self._lock:
            seen = key in self._compiled
        if seen:
            yield
            return
        start = time.perf_counter()
        try:
            yield
        finally:
            elapsed = time.perf_counter() - start
            shape = label if label is not None else _shape_label(signature)
            with self._lock:
                first = key not in self._compiled
                if first:
                    self._compiled.add(key)
                    self._compiles[kernel] = self._compiles.get(kernel, 0) + 1
                    self._compile_seconds[kernel] = (
                        self._compile_seconds.get(kernel, 0.0) + elapsed)
                    self._shapes.setdefault(kernel, set()).add(shape)
            if first:
                if record is not None:
                    phase = f"compile/{kernel}"
                    record.phases[phase] = (
                        record.phases.get(phase, 0.0) + elapsed)
                counter = self._series("scheduler_tpu_compiles_total")
                if counter is not None:
                    counter.inc(kernel, shape)

    def compile_count(self, kernel: str | None = None) -> int:
        with self._lock:
            if kernel is not None:
                return self._compiles.get(kernel, 0)
            return sum(self._compiles.values())

    def compiled_shapes(self, kernel: str) -> list[str]:
        with self._lock:
            return sorted(self._shapes.get(kernel, ()))

    def mark_warm(self) -> None:
        """Snapshot the compile count as the warm baseline (called once,
        at the end of the backend warmup phase)."""
        with self._lock:
            self._warm_compile_base = sum(self._compiles.values())

    def compile_count_since_warm(self) -> int:
        """Compiles paid AFTER warmup — a warm restart re-entering service
        must keep this at 0 (the bench's warm_compile_count column)."""
        with self._lock:
            return sum(self._compiles.values()) - self._warm_compile_base

    # -- memory watermark ----------------------------------------------------

    def note_resident(self, group: str, nbytes: int, record=None) -> None:
        """Record that buffer `group` now holds `nbytes` on the device
        (0 == freed). Live bytes are the sum across groups; the
        watermark is the running max of the live total."""
        if not self.enabled:
            return
        nbytes = max(int(nbytes), 0)
        with self._lock:
            self._resident[group] = nbytes
            live = sum(self._resident.values())
            if live > self._watermark:
                self._watermark = live
        if record is not None:
            self.stamp_watermark(record)

    def stamp_watermark(self, record) -> None:
        """Fold the current live total into the wave's high-water mark."""
        if not self.enabled or record is None:
            return
        with self._lock:
            live = sum(self._resident.values())
        if live > record.mem_watermark_bytes:
            record.mem_watermark_bytes = live

    def _jax_memory_bytes(self) -> int | None:
        """Device bytes_in_use per jax, as a cross-check on the ledger.

        Reads sys.modules only — this module must never import jax
        (the flight-recorder CLI demo runs without it)."""
        jax = sys.modules.get("jax")
        if jax is None:
            return None
        try:
            total, found = 0, False
            for dev in jax.local_devices():
                stats = getattr(dev, "memory_stats", None)
                stats = stats() if callable(stats) else None
                if stats and "bytes_in_use" in stats:
                    total += int(stats["bytes_in_use"])
                    found = True
            return total if found else None
        except Exception:
            return None

    # -- gauges (once per wave, from FlightRecorder.end_wave) ----------------

    def update_gauges(self) -> None:
        mem = self._series("scheduler_tpu_device_memory_bytes")
        shapes = self._series("scheduler_tpu_compiled_shapes")
        if mem is None and shapes is None:
            return
        with self._lock:
            live = sum(self._resident.values())
            shape_counts = {k: len(v) for k, v in self._shapes.items()}
        if mem is not None:
            mem.set(float(live), "ledger")
            jax_bytes = self._jax_memory_bytes()
            if jax_bytes is not None:
                mem.set(float(jax_bytes), "jax")
        if shapes is not None:
            for kernel, count in shape_counts.items():
                shapes.set(float(count), kernel)

    # -- queries / snapshots -------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            return {
                "upload_bytes_total": self._totals[UPLOAD],
                "fetch_bytes_total": self._totals[FETCH],
                "compiles_total": sum(self._compiles.values()),
                "distinct_shapes": {k: len(v)
                                    for k, v in sorted(self._shapes.items())},
                "mem_live_bytes": sum(self._resident.values()),
                "mem_watermark_bytes": self._watermark,
            }

    def snapshot(self) -> dict:
        """The /debug/devicetelemetry zpage payload (also embedded in
        the flight-recorder dump and SIGUSR1 log line)."""
        with self._lock:
            out = {
                "transfers": {
                    UPLOAD: {
                        "total_bytes": self._totals[UPLOAD],
                        "by_plane": dict(sorted(
                            self._transfers[UPLOAD].items())),
                    },
                    FETCH: {
                        "total_bytes": self._totals[FETCH],
                        "by_plane": dict(sorted(
                            self._transfers[FETCH].items())),
                    },
                },
                "compiles": {
                    "total": sum(self._compiles.values()),
                    "by_kernel": dict(sorted(self._compiles.items())),
                    "seconds_by_kernel": {
                        k: round(v, 6)
                        for k, v in sorted(self._compile_seconds.items())},
                    "distinct_shapes": {
                        k: sorted(v)
                        for k, v in sorted(self._shapes.items())},
                },
                "memory": {
                    "resident_bytes": dict(sorted(self._resident.items())),
                    "live_bytes": sum(self._resident.values()),
                    "watermark_bytes": self._watermark,
                },
            }
        jax_bytes = self._jax_memory_bytes()
        if jax_bytes is not None:
            out["memory"]["jax_bytes_in_use"] = jax_bytes
        return out

    def bench_columns(self, waves: int) -> dict:
        """The three device columns bench.py/bench_suite.py report and
        the regression gate compares (lower is better for all three)."""
        with self._lock:
            upload = self._totals[UPLOAD]
            compiles = sum(self._compiles.values())
            watermark = self._watermark
        return {
            "upload_bytes_per_wave": int(round(upload / waves)) if waves else 0,
            "compile_count": compiles,
            "mem_watermark_bytes": watermark,
        }
