"""Wave flight recorder: tracing, metrics, and post-mortem telemetry for
the batched TPU scheduling pipeline.

Every batched wave is self-describing: the loop and backend time their
phases through this recorder (which doubles each stopwatch as a child span
on the shared `utils.tracing.Tracer`), and each wave leaves a structured
`WaveRecord` in a bounded ring buffer — pod/clone counts, dedup tier,
pad/occupancy, carry invalidations, fallback reason, per-phase durations —
queryable after the fact via `python -m
kubernetes_tpu.scheduler.tpu.flightrecorder` or the SIGUSR1 dump hook
(the `cache/debugger.py` pattern).

A slow-wave watchdog arms a timer per open wave; if the wave is still in
flight past the deadline it captures a `utils.pprof.take_profile` sample
of all threads and attaches it to the flight record — the post-mortem for
"why was wave 1723 slow" ships with the wave.

All recording is HOST-SIDE ONLY: phases close after device results are
collected, nothing here runs inside jitted code (mechanically enforced by
kubesched-lint rule OBS01), so the seeded tie-break stream and the golden
bit-compat contract are byte-identical with the recorder on or off.
With no tracer exporter installed the span side costs one attribute
lookup per phase (the no-op tracer fast path); the ring buffer append is
a dict build + deque append per wave, not per pod.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from ...utils import faultinject
from ...utils.envknob import float_env, int_env
from ...utils.tracing import Tracer
from .devicetelemetry import DeviceTelemetry
from .podlatency import PodLatencyLedger
from .stallprofiler import StallProfiler

# loop-level pipeline phases (the phase_profile bench.py reports)
LOOP_PHASES = ("snapshot", "kernel", "finish", "bind", "pump", "events",
               "pop", "harness", "drain")
# backend wave-path phases (the wave_profile bench.py reports)
WAVE_PHASES = ("sync", "features", "tie", "dispatch", "upload", "wait",
               "dedup")
# launch-side host-prep phases: with the pipeline on, these run while the
# PREDECESSOR wave executes on device — the overlap the streaming-waves
# pipeline exists to create (pipeline_overlap_ratio = hidden prep / prep)
PREP_PHASES = ("sync", "features", "upload", "dedup", "tie", "dispatch")

# watchdog defaults; env knobs so production runs can tune without code
DEFAULT_CAPACITY = int_env("KUBE_TPU_FLIGHT_CAPACITY", 256)
# None/0 = watchdog off (the default: CPU-fallback bench waves legitimately
# run long, and profile capture is not free)
DEFAULT_SLOW_WAVE_S = float_env("KUBE_TPU_SLOW_WAVE_S", None)
DEFAULT_PROFILE_S = float_env("KUBE_TPU_SLOW_WAVE_PROFILE_S", 0.25)


@dataclass
class WaveRecord:
    """One batched wave's flight record (see README "Observability")."""

    wave_id: int
    started_at: float  # wall clock, for post-mortem correlation
    pods: int = 0
    pad: int = 0  # padded program slots (pow2 bucket)
    signatures: int = 0  # distinct feature signatures (0 = dedup off)
    clones: int = 0  # pods that rode the cheap carry-replay tier
    distinct_signature_ratio: float | None = None
    dedup_tier: str = "off"  # "dedup" | "off"
    occupancy: float = 0.0  # pods / pad
    carry_invalidations: int = 0  # invalidations during this wave's flight
    cache_exports: int = 0  # signature hints exported to the BatchCache
    # cross-wave signature reuse (device-resident score cache): signatures
    # of this wave replayed from / missing in / evicted from the previous
    # chained wave's resident table
    xwave_hits: int = 0
    xwave_misses: int = 0
    xwave_evictions: int = 0
    fallback_reason: str | None = None  # resync/fallback diagnosis, if any
    # gang waves (README "Gang waves"): PodGroups admitted to this wave,
    # their member counts, members that fell back to the host gang cycle,
    # and the per-group outcome ("device:<domain>" | "fallback:<reason>")
    gang_groups: int = 0
    gang_pods: int = 0
    gang_fallback_pods: int = 0
    gang_outcome: str | None = None
    injected_faults: int = 0  # chaos faults fired during this wave's flight
    retries: int = 0  # dispatcher retry attempts during this wave's flight
    # host prep seconds that ran while a predecessor wave was in flight on
    # device (the pipelined overlap), and the per-wave ratio of prep hidden
    overlap_s: float = 0.0
    pipeline_overlap_ratio: float = 0.0
    # device transfer ledger (devicetelemetry.py): bytes this wave moved
    # across the host<->device boundary, attributed per TRANSFER_PLANES name
    upload_bytes: int = 0
    fetch_bytes: int = 0
    upload_by_plane: dict = field(default_factory=dict)
    fetch_by_plane: dict = field(default_factory=dict)
    # per-wave high-water mark of device-resident plane-buffer bytes
    mem_watermark_bytes: int = 0
    phases: dict = field(default_factory=dict)  # phase -> seconds
    duration_s: float = 0.0
    # stall attribution (stallprofiler.py — the ONLY writer of these
    # fields, enforced by kubesched-lint OBS04): wall-clock decomposition
    # into named stall reasons, its coverage of duration_s, and the
    # largest contributor
    stall_by_reason: dict = field(default_factory=dict)
    stall_coverage: float = 0.0
    stall_dominant: str | None = None
    profile: str | None = None  # watchdog pprof capture, when triggered
    # internal bookkeeping (not serialized)
    _t0: float = 0.0
    _inv_base: int = 0
    _fault_base: int = 0
    _retry_base: int = 0
    # stall-profiler scratch (written only in stallprofiler.py: OBS04)
    _stall_acc: dict = field(default_factory=dict)
    _stall_mark: str | None = None
    _stall_done: bool = False

    def to_dict(self) -> dict:
        d = {
            "wave_id": self.wave_id,
            "started_at": self.started_at,
            "duration_s": round(self.duration_s, 6),
            "pods": self.pods,
            "pad": self.pad,
            "occupancy": round(self.occupancy, 4),
            "signatures": self.signatures,
            "clones": self.clones,
            "distinct_signature_ratio": self.distinct_signature_ratio,
            "dedup_tier": self.dedup_tier,
            "carry_invalidations": self.carry_invalidations,
            "cache_exports": self.cache_exports,
            "xwave_hits": self.xwave_hits,
            "xwave_misses": self.xwave_misses,
            "xwave_evictions": self.xwave_evictions,
            "fallback_reason": self.fallback_reason,
            "gang_groups": self.gang_groups,
            "gang_pods": self.gang_pods,
            "gang_fallback_pods": self.gang_fallback_pods,
            "gang_outcome": self.gang_outcome,
            "injected_faults": self.injected_faults,
            "retries": self.retries,
            "overlap_s": round(self.overlap_s, 6),
            "pipeline_overlap_ratio": round(self.pipeline_overlap_ratio, 4),
            "upload_bytes": self.upload_bytes,
            "fetch_bytes": self.fetch_bytes,
            "upload_by_plane": dict(self.upload_by_plane),
            "fetch_by_plane": dict(self.fetch_by_plane),
            "mem_watermark_bytes": self.mem_watermark_bytes,
            "phases": {k: round(v, 6) for k, v in self.phases.items()},
            "stall_by_reason": {k: round(v, 6)
                                for k, v in self.stall_by_reason.items()},
            "stall_coverage": round(self.stall_coverage, 4),
            "stall_dominant": self.stall_dominant,
        }
        if self.profile is not None:
            d["profile"] = self.profile
        return d


class FlightRecorder:
    """Shared phase stopwatches + per-wave ring buffer + watchdog.

    One instance is shared by the ScheduleOneLoop, every TPUBackend, and
    the bench/harness: `phase_totals` IS the loop's phase_profile dict and
    `wave_totals` IS the backend's perf dict (same objects), so every
    consumer reads recorder-sourced numbers."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, tracer=None,
                 metrics=None,
                 slow_wave_deadline_s: float | None = DEFAULT_SLOW_WAVE_S,
                 profile_seconds: float = DEFAULT_PROFILE_S):
        self.tracer = tracer or Tracer("flight-recorder")  # no-op by default
        self.metrics = metrics
        # per-pod e2e latency decomposition (README "Observability")
        self.pod_ledger = PodLatencyLedger(metrics=metrics)
        # device-side accounting: transfer ledger, compile tracker,
        # memory watermark (README "Device telemetry")
        self.device_telemetry = DeviceTelemetry(metrics=metrics)
        # streaming-wave stall attribution: per-wave wall-clock decomposed
        # into overlap + named stall reasons (README "Streaming waves")
        self.stall_profiler = StallProfiler(metrics=metrics)
        self.slow_wave_deadline_s = slow_wave_deadline_s or None
        self.profile_seconds = profile_seconds
        # cumulative phase stopwatches (the dicts bench.py diffs)
        self.phase_totals: dict = {k: 0.0 for k in LOOP_PHASES}
        self.phase_totals["waves"] = 0
        self.wave_totals: dict = {k: 0.0 for k in WAVE_PHASES}
        self._records: "collections.deque[WaveRecord]" = collections.deque(
            maxlen=capacity
        )
        self._lock = threading.Lock()
        self._wave_seq = 0
        self.invalidations = 0  # cumulative carry invalidations
        self.retries_total = 0  # cumulative dispatcher retry attempts
        # gang routing totals: path ("device" | "host") -> member count
        self.gang_pod_totals: dict = {}
        # streaming-wave pipeline accounting: cumulative launch-side host
        # prep seconds, and how many of them ran under an in-flight
        # predecessor (see note_pipeline); wave-size histogram by pad
        self.prep_s_total = 0.0
        self.overlap_s_total = 0.0
        self.wave_sizes: dict[int, int] = {}
        self.slow_wave_captures = 0
        self._watchdogs: dict[int, threading.Timer] = {}
        # circuit-breaker transition history (old, new, reason), bounded
        self.breaker_events: "collections.deque[tuple]" = collections.deque(
            maxlen=64
        )
        # watch-partition detections (kind, repaired, latency_s), bounded
        self.partition_events: "collections.deque[tuple]" = collections.deque(
            maxlen=64
        )
        # crash-restart reconcile outcomes (kind, count), bounded — one
        # entry per recovery kind per reconcile pass, not per pod
        self.restart_events: "collections.deque[tuple]" = collections.deque(
            maxlen=64
        )
        # fleet shard ownership/failover transitions: ("ownership", owned,
        # fleet_size) on acquire/release, ("failover", shard, latency_s)
        # on a dead peer's shard adoption — bounded
        self.fleet_events: "collections.deque[tuple]" = collections.deque(
            maxlen=64
        )

    # -- phase stopwatches (span-backed) --------------------------------------

    @contextmanager
    def phase(self, name: str, record: WaveRecord | None = None, **attrs):
        """Time a loop-level phase; emits a `phase/<name>` child span and
        accumulates into phase_totals (and the wave record, when given)."""
        t0 = time.perf_counter()
        try:
            with self.tracer.span(f"phase/{name}", **attrs):
                yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.phase_totals[name] = self.phase_totals.get(name, 0.0) + dt
                if record is not None:
                    record.phases[name] = record.phases.get(name, 0.0) + dt

    @contextmanager
    def wave_phase(self, name: str, record: WaveRecord | None = None):
        """Time a backend wave-path phase (sync/features/.../wait)."""
        t0 = time.perf_counter()
        try:
            with self.tracer.span(f"wave_phase/{name}"):
                yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.wave_totals[name] = self.wave_totals.get(name, 0.0) + dt
                if record is not None:
                    record.phases[name] = record.phases.get(name, 0.0) + dt

    def count_wave(self) -> None:
        """One wave launched (the phase_profile["waves"] counter)."""
        with self._lock:
            self.phase_totals["waves"] += 1

    # -- per-wave records -----------------------------------------------------

    def begin_wave(self, pods: int, pad: int = 0) -> WaveRecord:
        """Open a flight record at wave launch; arms the slow-wave watchdog
        when a deadline is configured."""
        with self._lock:
            self._wave_seq += 1
            rec = WaveRecord(wave_id=self._wave_seq, started_at=time.time(),
                             pods=pods, pad=pad or pods)
            rec._t0 = time.perf_counter()
            rec._inv_base = self.invalidations
            rec._fault_base = faultinject.fired_total()
            rec._retry_base = self.retries_total
        if self.slow_wave_deadline_s:
            t = threading.Timer(self.slow_wave_deadline_s,
                                self._capture_slow_wave, args=(rec,))
            t.daemon = True
            with self._lock:
                self._watchdogs[rec.wave_id] = t
            t.start()
        return rec

    def note_launch(self, rec: WaveRecord, signatures: int = 0,
                    dedup: bool = False) -> None:
        """Attach launch-side wave composition (dedup grouping outcome)."""
        rec.signatures = signatures
        rec.dedup_tier = "dedup" if dedup else "off"
        if dedup and rec.pods:
            rec.clones = rec.pods - signatures
            rec.distinct_signature_ratio = round(signatures / rec.pods, 4)

    def note_pipeline(self, rec: WaveRecord, overlapped: bool) -> None:
        """Attach launch-side pipeline accounting: `overlapped` is True
        when a predecessor wave was in flight on device while this wave's
        host prep (the PREP_PHASES stopwatches) ran — i.e. the prep was
        hidden under the predecessor's `wait`. Called by the backend at the
        end of launch_batched, before collect; pure bookkeeping, never in
        jitted code."""
        prep = sum(rec.phases.get(p, 0.0) for p in PREP_PHASES)
        rec.overlap_s = prep if overlapped else 0.0
        rec.pipeline_overlap_ratio = 1.0 if (overlapped and prep) else 0.0
        with self._lock:
            self.prep_s_total += prep
            self.overlap_s_total += rec.overlap_s

    def note_cross_wave(self, rec: WaveRecord, hits: int, misses: int,
                        evictions: int) -> None:
        """Attach the launch-side cross-wave cache outcome: how many of
        this wave's signatures replayed a previous chained wave's resident
        score row (hits) vs paid a fresh full pass (misses), and how many
        resident rows fell out of the single-generation table."""
        rec.xwave_hits = hits
        rec.xwave_misses = misses
        rec.xwave_evictions = evictions

    @contextmanager
    def fallback_attribution(self, framework, record: WaveRecord | None = None):
        """Per-plugin phase attribution for host-fallback scoring: while
        active, every plugin call the framework times lands in
        `fallback/<plugin>` phase buckets (phase_totals + the wave record)
        UNSAMPLED, so a fallback regression is attributable to the plugin
        that caused it instead of vanishing into one "finish" span. Host-
        side only — the observer fires around interpreter-level plugin
        calls, never inside jitted code."""
        if framework is None:
            yield
            return
        prev = getattr(framework, "plugin_observer", None)

        def observe(point: str, plugin: str, dt: float) -> None:
            key = f"fallback/{plugin}"
            with self._lock:
                self.phase_totals[key] = self.phase_totals.get(key, 0.0) + dt
                if record is not None:
                    record.phases[key] = record.phases.get(key, 0.0) + dt

        framework.plugin_observer = observe
        try:
            yield
        finally:
            framework.plugin_observer = prev

    def carry_invalidated(self) -> None:
        """The device carry was dropped (resync/divergence/external event);
        open records count the invalidations that happened in their window."""
        with self._lock:
            self.invalidations += 1

    def note_retries(self, n: int) -> None:
        """The dispatcher absorbed n retry attempts (called from worker
        threads); open wave records count retries in their window."""
        with self._lock:
            self.retries_total += n

    def count_gang_pods(self, path: str, n: int) -> None:
        """Count gang members routed down `path` ("device" = admitted to a
        gang wave, "host" = fell back to the per-pod host gang cycle). The
        ONE emission point for scheduler_tpu_gang_pods_total — the wave
        record's gang_fallback_pods field is set by the backend separately
        so a record never double-lands the counter."""
        if n <= 0:
            return
        with self._lock:
            self.gang_pod_totals[path] = self.gang_pod_totals.get(path, 0) + n
        m = self.metrics
        if m is not None and hasattr(m, "gang_pods"):
            m.gang_pods(path, n)

    def breaker_transition(self, old: str, new: str, reason: str) -> None:
        """Record a TPU circuit-breaker state transition and land it on the
        metrics registry (state gauge + transition counter)."""
        with self._lock:
            self.breaker_events.append((old, new, reason))
        m = self.metrics
        if m is not None and hasattr(m, "breaker_transition"):
            m.breaker_transition(old, new)

    def partition_detected(self, kind: str, repaired: int,
                           latency_s: float) -> None:
        """An informer detected (and just repaired) a watch-stream
        partition; lands the detection counter + repair-latency histogram
        on the metrics registry. Wired as the InformerFactory's partition
        observer."""
        with self._lock:
            self.partition_events.append((kind, repaired, latency_s))
        m = self.metrics
        if m is not None and hasattr(m, "partition_detected"):
            m.partition_detected(kind, latency_s)

    def restart_recovery(self, kind: str, n: int = 1) -> None:
        """A startup reconcile resolved n pieces of mid-flight crash state
        of `kind` (adopted/forgotten/requeued/gang_adopt/gang_release/
        permit_cleared); lands the restart-recovery counter on the metrics
        registry. Wired as Scheduler.reconcile's outcome sink."""
        if n <= 0:
            return
        with self._lock:
            self.restart_events.append((kind, n))
        m = self.metrics
        if m is not None and hasattr(m, "restart_recovery"):
            m.restart_recovery(kind, n)

    def shard_ownership(self, owned: int, fleet_size: int) -> None:
        """This fleet member's shard count changed (lease acquired or
        lost); lands the ownership gauges on the metrics registry."""
        with self._lock:
            self.fleet_events.append(("ownership", owned, fleet_size))
        m = self.metrics
        if m is not None and hasattr(m, "fleet_ownership"):
            m.fleet_ownership(owned, fleet_size)

    def shard_failover(self, shard: int, latency_s: float) -> None:
        """A dead peer's shard adopted (lease expiry -> takeover latency);
        lands the failover counter + latency histogram."""
        with self._lock:
            self.fleet_events.append(("failover", shard, latency_s))
        m = self.metrics
        if m is not None and hasattr(m, "fleet_failover"):
            m.fleet_failover(shard, latency_s)

    def end_wave(self, rec: WaveRecord,
                 fallback_reason: str | None = None) -> WaveRecord:
        """Finalize and ring-buffer a record; disarms the watchdog, attaches
        any captured profile, and lands the wave's metrics series."""
        timer = None
        with self._lock:
            timer = self._watchdogs.pop(rec.wave_id, None)
        if timer is not None:
            timer.cancel()
        rec.duration_s = time.perf_counter() - rec._t0
        rec.occupancy = round(rec.pods / rec.pad, 4) if rec.pad else 0.0
        if fallback_reason is not None:
            rec.fallback_reason = fallback_reason
        with self._lock:
            rec.carry_invalidations = self.invalidations - rec._inv_base
            rec.injected_faults = faultinject.fired_total() - rec._fault_base
            rec.retries = self.retries_total - rec._retry_base
            self.wave_sizes[rec.pad] = self.wave_sizes.get(rec.pad, 0) + 1
            self._records.append(rec)
        # stall attribution closes with the record: duration/phases are
        # final here, and the decomposition must land before the metrics
        # pass reads stall_by_reason
        self.stall_profiler.finalize(rec)
        m = self.metrics
        if m is not None:
            if hasattr(m, "wave_completed"):
                m.wave_completed(rec)
            if hasattr(m, "update_sli_quantiles"):
                m.update_sli_quantiles()
        # ledger/telemetry gauges refresh once per wave, not per pod
        self.pod_ledger.update_gauges()
        self.device_telemetry.update_gauges()
        return rec

    def _capture_slow_wave(self, rec: WaveRecord) -> None:
        """Watchdog fire: the wave blew its deadline and is still open —
        sample every thread's stack so the record explains where the time
        went. Runs on the timer thread; purely observational."""
        from ...utils.pprof import take_profile

        try:
            profile = take_profile(seconds=self.profile_seconds)
        except Exception as e:  # noqa: BLE001 - diagnostics are best-effort
            profile = f"profile capture failed: {type(e).__name__}: {e}"
        rec.profile = (
            f"slow wave {rec.wave_id}: exceeded "
            f"{self.slow_wave_deadline_s}s deadline\n{profile}"
        )
        with self._lock:
            self.slow_wave_captures += 1
        if self.metrics is not None and hasattr(self.metrics,
                                                "slow_wave_captured"):
            self.metrics.slow_wave_captured()

    # -- queries / snapshots --------------------------------------------------

    def records(self, last: int | None = None) -> list[WaveRecord]:
        with self._lock:
            recs = list(self._records)
        return recs[-last:] if last else recs

    def phase_snapshot(self) -> dict:
        with self._lock:
            return dict(self.phase_totals)

    def wave_snapshot(self) -> dict:
        with self._lock:
            return dict(self.wave_totals)

    def wave_size_histogram(self) -> dict:
        """Completed-wave count per pow2 pad bucket (the adaptive wave-size
        controller's observable output), keyed by stringified pad size."""
        with self._lock:
            return {str(k): v for k, v in sorted(self.wave_sizes.items())}

    def pipeline_overlap_ratio(self) -> float | None:
        """Fraction of cumulative launch-side host prep that ran under an
        in-flight predecessor wave. None until any prep has been timed."""
        with self._lock:
            if not self.prep_s_total:
                return None
            return round(self.overlap_s_total / self.prep_s_total, 4)

    def summary(self) -> dict:
        recs = self.records()
        durations = sorted(r.duration_s for r in recs)
        return {
            "waves_recorded": len(recs),
            "waves_total": self.phase_snapshot().get("waves", 0),
            "slow_wave_captures": self.slow_wave_captures,
            "carry_invalidations": self.invalidations,
            "retries_total": self.retries_total,
            "breaker_transitions": len(self.breaker_events),
            "partitions_detected": len(self.partition_events),
            "fallbacks": sum(1 for r in recs if r.fallback_reason),
            "wave_p50_s": (round(durations[len(durations) // 2], 4)
                           if durations else None),
            "wave_max_s": round(durations[-1], 4) if durations else None,
            "pipeline_overlap_ratio": self.pipeline_overlap_ratio(),
            "wave_size_hist": self.wave_size_histogram(),
            "stalls": self.stall_profiler.summary(),
        }

    # -- dump hook (cache/debugger.py pattern) --------------------------------

    def dump(self, last: int | None = None) -> str:
        """JSON post-mortem dump: summary + the ring buffer's records."""
        return json.dumps({
            "summary": self.summary(),
            "phase_totals": {
                k: (v if k == "waves" else round(v, 6))
                for k, v in self.phase_snapshot().items()
            },
            "wave_totals": {k: round(v, 6)
                            for k, v in self.wave_snapshot().items()},
            "pod_latency": self.pod_ledger.snapshot(slowest=8),
            "device_telemetry": self.device_telemetry.snapshot(),
            "stalls": self.stall_profiler.snapshot(last=8),
            "records": [r.to_dict() for r in self.records(last)],
        }, indent=2)

    def install(self, signum=None):
        """Install a signal handler dumping flight records to the log
        (SIGUSR1 by default; the cache debugger owns SIGUSR2). Returns the
        previous handler. Raises ValueError off the main thread."""
        import logging
        import signal as _signal

        if signum is None:
            signum = _signal.SIGUSR1
        log = logging.getLogger("kubernetes_tpu.flightrecorder")

        def handler(_sig, _frame):
            log.warning("flight-recorder dump:\n%s", self.dump())

        return _signal.signal(signum, handler)


# -- CLI: post-mortem reader / smoke ------------------------------------------


def format_postmortem(records: list[dict]) -> str:
    """Human-readable wave table from to_dict()-shaped records."""
    if not records:
        return "(no flight records)"
    cols = ("wave", "pods", "pad", "occ", "sigs", "tier", "inval",
            "fallback", "ms", "slowest phases")
    rows = []
    for r in records:
        phases = sorted(r.get("phases", {}).items(), key=lambda kv: -kv[1])
        top = " ".join(f"{k}={v * 1000:.1f}ms" for k, v in phases[:3])
        if r.get("profile"):
            top += "  [profile captured]"
        rows.append((
            str(r["wave_id"]), str(r["pods"]), str(r["pad"]),
            f"{r.get('occupancy', 0):.2f}", str(r.get("signatures", 0)),
            r.get("dedup_tier", "off"),
            str(r.get("carry_invalidations", 0)),
            (r.get("fallback_reason") or "-")[:32],
            f"{r.get('duration_s', 0) * 1000:.1f}", top,
        ))
    widths = [max(len(c), *(len(row[i]) for row in rows))
              for i, c in enumerate(cols)]
    out = ["  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    for row in rows:
        out.append("  ".join(c.ljust(w) for c, w in zip(row, widths)))
    return "\n".join(out)


def _demo() -> FlightRecorder:
    """Synthetic multi-wave run exercising the full recorder surface
    (no device, no jax import) — the `make obs` smoke."""
    rec = FlightRecorder(capacity=8, slow_wave_deadline_s=0.05,
                         profile_seconds=0.05)
    tel = rec.device_telemetry
    for i in range(10):
        wr = rec.begin_wave(pods=30 + i, pad=32)
        with rec.wave_phase("sync", wr):
            pass
        # device telemetry, driven exactly as the backend drives it:
        # accounted transfers per plane, a compile span per jit signature
        # (only wave 0's is a cache miss), resident-buffer bytes
        tel.account_upload("features", 4096, wr)
        tel.account_upload("carry_scatter", 1024, wr)
        tel.note_resident("planes", 1 << 20, wr)
        with tel.compile_span("batched_assign", ("demo", 32),
                              label="pad32", record=wr):
            pass
        with rec.wave_phase("dispatch", wr):
            pass
        tel.accounted_fetch("results", list(range(8)), wr)
        rec.note_launch(wr, signatures=3, dedup=True)
        rec.note_cross_wave(wr, hits=(3 if i else 0),
                            misses=(0 if i else 3), evictions=0)
        # wave 0 launches into an idle device; every later wave's prep
        # overlaps the (synthetic) in-flight predecessor
        rec.note_pipeline(wr, overlapped=bool(i))
        # stall attribution, driven exactly as the loop drives it: gap
        # marks at the seams (queue ran dry, per-tick cap, forced drain)
        if i == 2:
            rec.stall_profiler.mark_gap(wr, "queue_empty")
        elif i == 5:
            rec.stall_profiler.mark_gap(wr, "capacity_gate")
        elif i == 7:
            rec.stall_profiler.mark_gap(wr, "flush")
        with rec.phase("kernel", wr):
            if i == 4:
                time.sleep(0.12)  # trip the watchdog once
        rec.count_wave()
        rec.end_wave(wr, fallback_reason=(
            "tie-break draw overflow" if i == 7 else None
        ))
    return rec


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.scheduler.tpu.flightrecorder",
        description="Wave flight-recorder post-mortem reader",
    )
    parser.add_argument("dump", nargs="?",
                        help="JSON dump file (from FlightRecorder.dump() / "
                             "the SIGUSR1 hook); '-' reads stdin")
    parser.add_argument("--last", type=int, default=None,
                        help="show only the last N waves")
    parser.add_argument("--demo", action="store_true",
                        help="run a synthetic multi-wave smoke and print its "
                             "post-mortem (no device needed)")
    parser.add_argument("--schema", action="store_true",
                        help="print the flight-record field schema")
    args = parser.parse_args(argv)

    if args.schema:
        for f in WaveRecord.__dataclass_fields__:
            if not f.startswith("_"):
                print(f)
        return 0
    if args.demo:
        rec = _demo()
        payload = json.loads(rec.dump(last=args.last))
        # smoke-assert the device-telemetry block's presence and schema
        # (the `make obs` contract for the SIGUSR1/zpage payload)
        telemetry = payload.get("device_telemetry")
        if not isinstance(telemetry, dict):
            print("FAIL: dump payload is missing 'device_telemetry'")
            return 1
        missing = [k for k in ("transfers", "compiles", "memory")
                   if k not in telemetry]
        records = payload.get("records", [])
        bad_records = [r["wave_id"] for r in records
                       if "upload_bytes" not in r
                       or "mem_watermark_bytes" not in r
                       or sum(r.get("upload_by_plane", {}).values())
                       != r["upload_bytes"]]
        if missing or bad_records:
            print(f"FAIL: device telemetry schema: missing={missing} "
                  f"bad_records={bad_records}")
            return 1
        if telemetry["transfers"]["upload"]["total_bytes"] <= 0 \
                or telemetry["compiles"]["total"] != 1 \
                or telemetry["memory"]["watermark_bytes"] <= 0:
            print("FAIL: device telemetry totals: "
                  + json.dumps(telemetry, indent=2))
            return 1
        # stall-attribution block: every wave decomposed, coverage holds
        stalls = payload.get("stalls", {}).get("summary")
        if not isinstance(stalls, dict):
            print("FAIL: dump payload is missing 'stalls'")
            return 1
        uncovered = [r["wave_id"] for r in records
                     if "stall_by_reason" not in r
                     or r.get("stall_coverage", 0.0) < 0.95]
        if uncovered or stalls.get("waves_profiled", 0) <= 0 \
                or (stalls.get("coverage_min") or 0.0) < 0.95:
            print(f"FAIL: stall attribution: uncovered={uncovered} "
                  f"summary={json.dumps(stalls)}")
            return 1
    elif args.dump:
        import sys

        raw = (sys.stdin.read() if args.dump == "-"
               else open(args.dump).read())
        payload = json.loads(raw)
        if args.last:
            payload["records"] = payload.get("records", [])[-args.last:]
    else:
        parser.print_usage()
        return 2
    print(format_postmortem(payload.get("records", [])))
    summary = payload.get("summary", {})
    print("\nsummary: " + ", ".join(f"{k}={v}" for k, v in summary.items()))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
