"""Circuit breaker for the TPU device path: trip to host fallback, probe back.

The degradation ladder already has two rungs — the batched device wave, and
the per-pod host path (`TPUSchedulingAlgorithm.schedule_pod`'s
`super().schedule_pod` tier, where every `FallbackNeeded` lands). What it
lacked was memory: a flaking device made EVERY wave pay the launch/collect
round trip before falling back. The breaker adds the standard three states:

- CLOSED: waves go to the device; consecutive *device* failures count up
  (benign fallbacks — non-kernelizable pods, overflow — do not count).
- OPEN: after `threshold` consecutive failures, waves bypass the device
  entirely and route per-pod through the host tier until `cooldown_s`
  elapses (clock-injectable for tests).
- HALF_OPEN: after cooldown, up to `probes` waves are let through as
  probes; `probes` consecutive successes close the breaker, any failure
  re-opens it and restarts the cooldown.

Env knobs: KUBE_TPU_BREAKER_THRESHOLD (default 3),
KUBE_TPU_BREAKER_COOLDOWN_S (default 1.0), KUBE_TPU_BREAKER_PROBES
(default 2). Transitions fan out through `on_transition` (flight recorder
+ metrics); the breaker itself never imports either — it is a pure state
machine, safe to construct anywhere.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from ...utils.envknob import float_env, int_env

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# numeric encoding for the state gauge (metrics.py mirrors this map —
# kept inline there so importing metrics never drags the tpu package)
STATE_VALUES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Three-state breaker over an opaque 'device wave' operation."""

    def __init__(
        self,
        threshold: int | None = None,
        cooldown_s: float | None = None,
        probes: int | None = None,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Callable[[str, str, str], None] | None = None,
    ):
        self.threshold = (threshold if threshold is not None
                          else int_env("KUBE_TPU_BREAKER_THRESHOLD", 3))
        self.cooldown_s = (cooldown_s if cooldown_s is not None
                           else float_env("KUBE_TPU_BREAKER_COOLDOWN_S", 1.0))
        self.probes = (probes if probes is not None
                       else int_env("KUBE_TPU_BREAKER_PROBES", 2))
        self._clock = clock
        self._on_transition = on_transition
        self._mu = threading.Lock()
        self.state = CLOSED
        self.consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_successes = 0
        self._probes_inflight = 0
        self.trip_count = 0
        self.recovery_count = 0
        self.transitions: list[tuple[str, str, str]] = []  # bounded below

    # -- decisions ---------------------------------------------------------

    def allow_device_wave(self) -> bool:
        """May the next wave go to the device? OPEN flips to HALF_OPEN once
        the cooldown elapses; HALF_OPEN admits at most `probes` concurrent
        probe waves."""
        fire: tuple[str, str, str] | None = None
        with self._mu:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if self._clock() - self._opened_at < self.cooldown_s:
                    return False
                fire = self._transition_locked(HALF_OPEN, "cooldown elapsed")
                self._probe_successes = 0
                self._probes_inflight = 0
            # HALF_OPEN: meter the probes
            if self._probes_inflight >= self.probes:
                allowed = False
            else:
                self._probes_inflight += 1
                allowed = True
        if fire is not None:
            self._fan_out(fire)
        return allowed

    def device_blocked(self) -> bool:
        """Pure read for the per-pod path: True only while OPEN and still
        cooling — never mutates state, so it is safe in schedule_pod."""
        with self._mu:
            return (
                self.state == OPEN
                and self._clock() - self._opened_at < self.cooldown_s
            )

    def probing(self) -> bool:
        """Pure read: True while HALF_OPEN — the wave popper uses this to
        cap probe waves at a small size (a recovering device gets a taster,
        not a full wave). Never mutates state."""
        with self._mu:
            return self.state == HALF_OPEN

    # -- outcomes ----------------------------------------------------------

    def record_success(self) -> None:
        fire: tuple[str, str, str] | None = None
        with self._mu:
            self.consecutive_failures = 0
            if self.state == HALF_OPEN:
                self._probe_successes += 1
                self._probes_inflight = max(0, self._probes_inflight - 1)
                if self._probe_successes >= self.probes:
                    fire = self._transition_locked(
                        CLOSED, f"{self.probes} probe waves succeeded")
                    self.recovery_count += 1
        if fire is not None:
            self._fan_out(fire)

    def record_failure(self, reason: str = "device wave failed") -> None:
        fire: tuple[str, str, str] | None = None
        with self._mu:
            self.consecutive_failures += 1
            if self.state == HALF_OPEN:
                # one failed probe re-opens immediately and restarts cooldown
                fire = self._transition_locked(OPEN, f"probe failed: {reason}")
                self._opened_at = self._clock()
                self.trip_count += 1
            elif (self.state == CLOSED
                  and self.consecutive_failures >= self.threshold):
                fire = self._transition_locked(
                    OPEN,
                    f"{self.consecutive_failures} consecutive failures "
                    f"({reason})",
                )
                self._opened_at = self._clock()
                self.trip_count += 1
        if fire is not None:
            self._fan_out(fire)

    def record_benign(self) -> None:
        """A device wave ended without a device verdict (non-kernelizable
        fallback, overflow, poisoned carry): releases a HALF_OPEN probe
        slot without counting toward success or failure — a probe that
        never reached the device proves nothing either way."""
        with self._mu:
            if self.state == HALF_OPEN:
                self._probes_inflight = max(0, self._probes_inflight - 1)

    # -- plumbing ----------------------------------------------------------

    def _transition_locked(
        self, new_state: str, reason: str
    ) -> tuple[str, str, str]:
        old = self.state
        self.state = new_state
        entry = (old, new_state, reason)
        self.transitions.append(entry)
        if len(self.transitions) > 256:
            del self.transitions[:128]
        return entry

    def _fan_out(self, entry: tuple[str, str, str]) -> None:
        # outside _mu: the sink writes flight-recorder/metrics state under
        # its own locks
        if self._on_transition is not None:
            self._on_transition(*entry)

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "trip_count": self.trip_count,
                "recovery_count": self.recovery_count,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "probes": self.probes,
            }
