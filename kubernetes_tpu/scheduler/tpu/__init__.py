from .backend import TPUBackend, TPUSchedulingAlgorithm

__all__ = ["TPUBackend", "TPUSchedulingAlgorithm"]
