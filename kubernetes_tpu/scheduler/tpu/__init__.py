"""TPU scheduling backend package.

Lazy re-exports (PEP 562): `python -m kubernetes_tpu.scheduler.tpu.
flightrecorder` and other telemetry-only importers must not pay the
backend's jax import (or require a device) just to read flight records.
"""

__all__ = ["TPUBackend", "TPUSchedulingAlgorithm"]


def __getattr__(name):
    if name in __all__:
        from . import backend

        return getattr(backend, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
