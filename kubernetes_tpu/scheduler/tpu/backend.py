"""The TPU scheduling backend: dense-kernel findNodesThatFitPod + prioritizeNodes.

This is the north-star component (BASELINE.json): a `backend=tpu` profile
whose scheduling algorithm runs the fused pods×nodes feasibility-and-score
kernel (ops/kernels.py) instead of the per-node host plugin fan-out
(the reference's Parallelizer.Until at schedule_one.go:844 and
runtime/framework.go:1320). The framework's extension-point state machine —
Reserve/Permit/Bind, queueing, preemption — is untouched; only the two hot
loops move onto the device.

Bit-compatibility contract (SURVEY.md §7): with percentageOfNodesToScore=100
the host path evaluates every node, the rotating start index is a no-op, and
selection reduces to (max total score, seeded-rng tie-break over winners in
snapshot node order) — which is exactly what this backend computes, so TPU
and host decisions are identical. Golden tests enforce it.

Fallback: pods using features the kernel doesn't model yet (exotic
match_fields, hostIP-specific ports, term-slot overflow), claim/extender
pods, and preemption aftermath (nominated pods) run the host path via
super() — mirroring how the reference composes host + extender paths in one
cycle. Inter-pod (anti)affinity — both incoming-pod terms and existing-pod
terms — runs fully in-kernel (the dense topologyToMatchedTermCount of
interpodaffinity/filtering.go:91-185, scoring.go:81-257).
"""

from __future__ import annotations

import functools

import numpy as np

import jax

from ...api.resource import ResourceNames
from ...api.types import Pod
from ...ops import (
    FallbackNeeded,
    KernelConfig,
    PlaneBuilder,
    PodFeatureExtractor,
    batched_assign,
    fit_and_score,
    stack_features,
)
from ...ops.kernels import FILTER_NAMES
from ..framework.interface import (
    Diagnosis,
    FitError,
    ScheduleResult,
    Status,
)
from ..schedule_one import SchedulingAlgorithm

@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rows_jit(dev: dict, rows: dict, idx):
    """Row-scatter every plane in one program (one dispatch, donated
    buffers): dev[k][idx] = rows[k] for all planes simultaneously."""
    return {k: dev[k].at[idx].set(rows[k]) for k in dev}


# Reconstructed host-path messages + codes per filter mask row.
_ROW_STATUS = {
    "NodeUnschedulable": ("unresolvable", "node(s) were unschedulable"),
    "NodeName": ("unresolvable", "node didn't match the requested node name"),
    "NodeAffinity": ("unresolvable", "node(s) didn't match Pod's node affinity/selector"),
    "NodePorts": ("unschedulable", "node(s) didn't have free ports for the requested pod ports"),
}


class TPUBackend:
    """Planes + features + device-state bookkeeping for one cluster."""

    def __init__(self, names: ResourceNames, plugin_args: dict | None = None,
                 system_default_spread: bool = True):
        import jax

        args = (plugin_args or {}).get("NodeResourcesFit", {})
        ipa_args = (plugin_args or {}).get("InterPodAffinity", {})
        self.ipa_ignore_preferred_existing = bool(
            ipa_args.get("ignorePreferredTermsOfExistingPods", False)
        )
        self.names = names
        self.builder = PlaneBuilder(names)
        self.extractor = PodFeatureExtractor(
            names, self.builder.vocabs, system_default_spread=system_default_spread
        )
        self.strategy = args.get("strategy", "LeastAllocated")
        resources = args.get("resources") or {"cpu": 1, "memory": 1}
        self.fit_resources = tuple(
            (names.index_of(r), w) for r, w in sorted(resources.items(),
                                                      key=lambda kv: names.index_of(kv[0]))
        )
        shape = args.get("shape")
        # deep-tuple: KernelConfig is a static jit arg and must be hashable
        # even when the config came in as JSON/YAML lists
        self.rtc_shape = (
            tuple(sorted(tuple(p) for p in shape)) if shape else ((0, 0), (100, 100))
        )
        self._device_planes: dict | None = None
        self._device_version = -1
        self._device_buckets: tuple | None = None
        self._pending_dirty: set[int] | None = set()  # None = full re-put
        self._device_tables: dict | None = None
        self._tables_src: dict | None = None
        self._jax = jax

    # -- config / planes -----------------------------------------------------

    def kernel_config(self, planes, feats=None) -> KernelConfig:
        """feats (one dict or a stacked batch) tightens n_hard/n_soft (and
        the IPA slot counts) so the kernel only traces the constraint slots
        this pod wave actually uses — inactive slots cost segment reductions
        per scan step otherwise."""
        mc = self.extractor.MAX_CONSTRAINTS
        n_hard = n_soft = mc
        n_ipa_aff = n_ipa_anti = self.extractor.MAX_IPA_TERMS
        n_ipa_pref = self.extractor.MAX_IPA_PREF
        if feats is not None:
            n_hard = int(np.asarray(feats["hard_active"]).sum(axis=-1).max())
            n_soft = int(np.asarray(feats["soft_active"]).sum(axis=-1).max())
            n_ipa_aff = int((np.asarray(feats["ipa_aff_t"]) >= 0).sum(axis=-1).max())
            n_ipa_anti = int((np.asarray(feats["ipa_anti_t"]) >= 0).sum(axis=-1).max())
            n_ipa_pref = int((np.asarray(feats["ipa_pref_t"]) >= 0).sum(axis=-1).max())
        # existing-direction statics: true when the planes already carry
        # anti/preferred terms OR the wave itself does (a placed wave pod
        # joins the carried planes mid-scan)
        wave_anti = bool(feats is not None
                         and np.asarray(feats["ipa_anti_add"]).any())
        wave_pref = bool(feats is not None
                         and np.asarray(feats["ipa_pref_add"]).any())
        existing_anti = bool(planes.ipa_anti[: planes.n].any()) or wave_anti
        existing_pref = bool(planes.ipa_pref[: planes.n].any()) or wave_pref
        return KernelConfig(
            strategy=self.strategy,
            fit_resources=self.fit_resources,
            rtc_shape=self.rtc_shape,
            topo_domains=self.builder.topo_domains(planes),
            max_constraints=mc,
            n_hard=n_hard,
            n_soft=n_soft,
            ipa_existing_anti=existing_anti,
            ipa_existing_pref=existing_pref,
            n_ipa_aff=n_ipa_aff,
            n_ipa_anti=n_ipa_anti,
            n_ipa_pref=n_ipa_pref,
            max_ipa_terms=self.extractor.MAX_IPA_TERMS,
            max_ipa_pref=self.extractor.MAX_IPA_PREF,
            ipa_ignore_preferred_existing=self.ipa_ignore_preferred_existing,
        )

    def sync(self, snapshot):
        """Refresh host planes from the snapshot (O(changed) by generation),
        accumulating dirty rows for the device delta-upload."""
        planes = self.builder.sync(snapshot)
        if self._pending_dirty is not None:
            dirty = self.builder.dirty_rows
            if dirty is None:
                self._pending_dirty = None  # full rebuild happened
            else:
                self._pending_dirty.update(dirty)
        return planes

    def device_inputs(self, planes) -> dict:
        """Node planes + affinity signature tables, mirrored to device HBM.

        Call AFTER feature extraction — features intern affinity signatures.
        Unchanged planes cost nothing (version check); when only some node
        rows changed since the last upload (the steady state: each wave's
        binds dirty ≤ wave_size rows) the update is a per-plane row scatter
        instead of a full host→device re-put of every [Nb, ...] array.
        """
        full = (
            self._device_planes is None
            or self._pending_dirty is None
            or self._device_buckets != planes.bucket_sizes
        )
        if full:
            self._device_planes = {
                k: self._jax.device_put(a) for k, a in planes.as_dict().items()
            }
        elif self._device_version != planes.version and self._pending_dirty:
            # pad the dirty index list to a pow2 bucket (repeat the first
            # index — duplicate scatter writes of identical rows are benign)
            # so XLA sees a bounded set of scatter shapes, not one per wave
            from ...ops.vocab import next_pow2

            rows = sorted(self._pending_dirty)
            pad = next_pow2(len(rows), 8) - len(rows)
            idx = np.array(rows + [rows[0]] * pad, np.int32)
            host = planes.as_dict()
            dev = self._device_planes
            # one fused jitted scatter for every plane: eager per-plane
            # .at[].set() dispatches (and first-compiles) one tiny program
            # per plane per idx-bucket — a dozen device round-trip latencies
            # per wave on a tunneled chip. ipa_term_key is a global table;
            # its changes force a full rebuild elsewhere.
            scatter_in = {k: v for k, v in dev.items() if k != "ipa_term_key"}
            rows_host = {k: host[k][idx] for k in scatter_in}
            updated = _scatter_rows_jit(scatter_in, rows_host, idx)
            updated["ipa_term_key"] = dev["ipa_term_key"]
            self._device_planes = updated
        self._device_version = planes.version
        self._device_buckets = planes.bucket_sizes
        self._pending_dirty = set()
        tables = self.extractor.affinity_tables(planes)
        if self._tables_src is not tables:
            self._device_tables = {
                k: self._jax.device_put(a) for k, a in tables.items()
            }
            self._tables_src = tables
        return {**self._device_planes, **self._device_tables}

    # -- single-pod kernel cycle ---------------------------------------------

    def run(self, pod: Pod, snapshot):
        """One pod against the whole cluster; returns kernel outputs (numpy)
        plus the planes used. Raises FallbackNeeded when not kernelizable."""
        self.extractor.register(pod)
        planes = self.sync(snapshot)
        f = self.extractor.features(pod, planes)
        dev = self.device_inputs(planes)
        cfg = self.kernel_config(planes, f)
        out = fit_and_score(cfg, dev, f)
        return planes, {
            "fails": np.asarray(out["fails"]),
            "feasible": np.asarray(out["feasible"]),
            "insufficient": np.asarray(out["insufficient"]),
            "too_many_pods": np.asarray(out["too_many_pods"]),
            "total": np.asarray(out["total"]),
        }

    def run_batched(self, pods: list[Pod], snapshot, rng=None,
                    pad_to: int = 0):
        """Greedy batched assignment of a pod wave in one device program.

        With rng (the scheduling algorithm's seeded random.Random) the wave's
        tie-breaks are bit-identical to the host path scheduling the same
        pods sequentially: the rng's future getrandbits(32) stream is cloned
        into the kernel, and the live rng is advanced by exactly the words
        the kernel consumed.

        Returns (node names per pod or None, planes). The caller applies the
        same assumes host-side so cache and device state stay coherent."""
        from ...ops.kernels import MAX_TIE_DRAWS

        from ...ops import pad_features

        for pod in pods:
            self.extractor.register(pod)
        planes = self.sync(snapshot)
        feats = stack_features(
            [self.extractor.features_cached(p, planes) for p in pods]
        )
        if pad_to > len(pods):
            # one static batch shape per configured wave size → one compile
            feats = pad_features(feats, pad_to)
        n_slots = max(pad_to, len(pods))
        dev = self.device_inputs(planes)
        cfg = self.kernel_config(planes, feats)
        tie_words = rng_state = None
        if rng is not None:
            # vectorized stream cloning: transplant the MT19937 state into
            # numpy (uint32 full-range randint maps 1:1 onto genrand words)
            # instead of len(pods)*16 interpreter-level getrandbits calls
            rng_state = rng.getstate()
            _version, mt, _gauss = rng_state
            rs = np.random.RandomState()
            rs.set_state(("MT19937", np.array(mt[:624], dtype=np.uint32), mt[624]))
            n_words = n_slots * MAX_TIE_DRAWS + MAX_TIE_DRAWS
            tie_words = rs.randint(0, 2**32, size=n_words,
                                   dtype=np.uint64).astype(np.uint32)
        _winners_dev, info = batched_assign(cfg, dev, feats, tie_words)
        # ONE device→host transfer for everything the host needs: winners ++
        # [tie_consumed, tie_overflow] (separate np.asarray calls each pay
        # the tunnel's full round-trip latency)
        packed = np.asarray(info["packed"])
        winners, consumed, overflow = (
            packed[: len(pods)], int(packed[-2]), bool(packed[-1])
        )
        if rng is not None:
            if overflow:
                # a step exhausted its draw words (p < 2^-16 per tied step):
                # results past that step are desynced from the host stream —
                # discard the wave, untouched rng, host path decides
                raise FallbackNeeded("tie-break draw overflow")
            if consumed:
                # advance the live rng by exactly `consumed` words via the
                # same state transplant (no Python-loop catch-up)
                version, mt, gauss = rng_state
                rs2 = np.random.RandomState()
                rs2.set_state(("MT19937", np.array(mt[:624], dtype=np.uint32),
                               mt[624]))
                rs2.randint(0, 2**32, size=consumed, dtype=np.uint64)
                s = rs2.get_state()
                rng.setstate((version,
                              tuple(int(x) for x in s[1]) + (int(s[2]),), gauss))
        return [planes.node_names[w] if w >= 0 else None for w in winners], planes

    # -- diagnosis reconstruction ---------------------------------------------

    def build_diagnosis(self, pod: Pod, planes, out) -> Diagnosis:
        """Reconstruct per-node first-failure statuses exactly as the host
        filter chain would have produced them (first rejecting plugin wins,
        runtime RunFilterPlugins)."""
        diagnosis = Diagnosis()
        v = self.builder.vocabs
        fails = out["fails"]
        c_max = self.extractor.MAX_CONSTRAINTS
        # interleave PTS rows the way the host plugin checks per constraint:
        # missing-key then skew, constraint by constraint
        order: list[tuple[str, int]] = [(nm, i) for i, nm in enumerate(FILTER_NAMES)]
        for c in range(c_max):
            order.append((f"pts_missing:{c}", len(FILTER_NAMES) + c))
            order.append((f"pts_skew:{c}", len(FILTER_NAMES) + c_max + c))
        # InterPodAffinity rows follow PTS (registry filter order); within
        # the plugin the host checks existing-anti, then incoming-anti, then
        # incoming-affinity (filtering.go:352-412)
        base = len(FILTER_NAMES) + 2 * c_max
        order.append(("ipa_existing_anti", base))
        order.append(("ipa_anti", base + 1))
        order.append(("ipa_aff", base + 2))
        hard_keys = self._hard_constraint_keys(pod)
        # tolerance per taint-vocab entry, for host-identical taint messages
        from ...api.types import Taint

        tol = [
            any(tl.tolerates(Taint(*v.taints.key(j))) for tl in pod.spec.tolerations)
            for j in range(len(v.taints))
        ]
        for i in range(planes.n):
            if out["feasible"][i]:
                continue
            st = None
            for name, row in order:
                if not fails[row, i]:
                    continue
                st = self._row_to_status(name, i, planes, out, hard_keys, tol)
                break
            if st is not None:
                diagnosis.node_to_status.set(planes.node_names[i], st)
                diagnosis.unschedulable_plugins.add(st.plugin)
        return diagnosis

    def _hard_constraint_keys(self, pod: Pod) -> list[str]:
        from ..plugins.pod_topology_spread import PodTopologySpread

        pts = PodTopologySpread(system_defaulting=self.extractor.system_default_spread)
        return [c.topology_key for c in pts._constraints_for(pod, "DoNotSchedule")]

    def _row_to_status(self, name: str, i: int, planes, out, hard_keys, tol) -> Status:
        v = self.builder.vocabs
        if name == "TaintToleration":
            # the first *intolerable* taint, matching the host filter's
            # first-rejection message (basics.py TaintToleration.filter)
            msg = "node(s) had untolerated taint"
            for tid in planes.taints[i]:
                if tid >= 0 and not tol[int(tid)]:
                    key, val, _eff = v.taints.key(int(tid))
                    msg = f"node(s) had untolerated taint {{{key}: {val}}}"
                    break
            return Status.unresolvable(msg, plugin="TaintToleration")
        if name == "NodeResourcesFit":
            reasons = []
            if out["too_many_pods"][i]:
                reasons.append("Too many pods")
            for r in range(out["insufficient"].shape[0]):
                if out["insufficient"][r, i]:
                    rname = (self.names.names[r] if r < self.names.width else f"res{r}")
                    reasons.append(f"Insufficient {rname}")
            return Status.unschedulable(*reasons, plugin="NodeResourcesFit")
        if name.startswith("pts_missing:"):
            c = int(name.split(":")[1])
            key = hard_keys[c] if c < len(hard_keys) else "?"
            return Status.unresolvable(
                f"node(s) didn't have required label {key}", plugin="PodTopologySpread"
            )
        if name.startswith("pts_skew:"):
            return Status.unschedulable(
                "node(s) didn't match pod topology spread constraints",
                plugin="PodTopologySpread",
            )
        if name == "ipa_existing_anti":
            return Status.unschedulable(
                "node(s) had pods with anti-affinity rules rejecting the pod",
                plugin="InterPodAffinity",
            )
        if name == "ipa_anti":
            return Status.unschedulable(
                "node(s) didn't satisfy pod anti-affinity rules",
                plugin="InterPodAffinity",
            )
        if name == "ipa_aff":
            return Status.unschedulable(
                "node(s) didn't satisfy pod affinity rules",
                plugin="InterPodAffinity",
            )
        kind, msg = _ROW_STATUS[name]
        ctor = Status.unresolvable if kind == "unresolvable" else Status.unschedulable
        return ctor(msg, plugin=name)


class TPUSchedulingAlgorithm(SchedulingAlgorithm):
    """schedulePod with the dense kernel on the hot path.

    Inherits select_host (seeded-rng tie-break) and the host path for
    fallback, so decisions match the host algorithm bit-for-bit at
    percentageOfNodesToScore=100."""

    def __init__(self, framework, backend: TPUBackend, rng=None, nominator=None):
        super().__init__(framework, percentage_of_nodes_to_score=100,
                         rng=rng, nominator=nominator)
        self.backend = backend
        self.fallback_count = 0
        self.kernel_count = 0

    def schedule_pod(self, state, pod: Pod, snapshot) -> ScheduleResult:
        if snapshot.num_nodes() == 0:
            raise FitError(pod, 0, Diagnosis())
        if self._must_fall_back(pod):
            self.fallback_count += 1
            return super().schedule_pod(state, pod, snapshot)
        try:
            planes, out = self.backend.run(pod, snapshot)
        except FallbackNeeded:
            self.fallback_count += 1
            return super().schedule_pod(state, pod, snapshot)
        self.kernel_count += 1

        feasible_idx = np.flatnonzero(out["feasible"][: planes.n])
        if feasible_idx.size == 0:
            # Populate CycleState via the host PreFilter chain before raising:
            # DefaultPreemption's victim dry-run re-runs Filter plugins against
            # this state (preemption.go SelectVictimsOnNode), and e.g.
            # PodTopologySpread.filter is a no-op without its prefilter state —
            # skipping this would let preemption nominate skew-violating nodes.
            self.fw.run_pre_filter_plugins(state, pod, snapshot.list_nodes())
            diagnosis = self.backend.build_diagnosis(pod, planes, out)
            raise FitError(pod, snapshot.num_nodes(), diagnosis)
        if feasible_idx.size == 1:
            evaluated = planes.n  # every node was evaluated by the kernel
            return ScheduleResult(
                suggested_host=planes.node_names[int(feasible_idx[0])],
                evaluated_nodes=evaluated,
                feasible_nodes=1,
            )
        totals = out["total"][feasible_idx]
        best = totals.max()
        winners = feasible_idx[totals == best]
        if winners.size > 1:
            win = int(winners[self.rng.randrange(winners.size)])
        else:
            win = int(winners[0])
        return ScheduleResult(
            suggested_host=planes.node_names[win],
            evaluated_nodes=planes.n,
            feasible_nodes=int(feasible_idx.size),
        )

    def _must_fall_back(self, pod: Pod) -> bool:
        # long-tail volume plugins (VolumeBinding/Zone/Restrictions/Limits)
        # run host-side only — a claim-backed pod needs the full host chain
        from ...api.storage import pod_claim_names

        if pod_claim_names(pod) or pod.spec.resource_claims:
            return True
        # configured HTTP extenders veto/score out-of-process — host path only
        if self.extenders and any(e.is_interested(pod) for e in self.extenders):
            return True
        # preemption aftermath: nominated pods must be simulated onto nodes
        # during filtering (schedule_one.go:1190) — host path handles it
        if pod.status.nominated_node_name:
            return True
        if self.nominator is not None and getattr(
            self.nominator, "has_nominated_pods", lambda: False
        )():
            return True
        return False
