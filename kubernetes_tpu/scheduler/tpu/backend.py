"""The TPU scheduling backend: dense-kernel findNodesThatFitPod + prioritizeNodes.

This is the north-star component (BASELINE.json): a `backend=tpu` profile
whose scheduling algorithm runs the fused pods×nodes feasibility-and-score
kernel (ops/kernels.py) instead of the per-node host plugin fan-out
(the reference's Parallelizer.Until at schedule_one.go:844 and
runtime/framework.go:1320). The framework's extension-point state machine —
Reserve/Permit/Bind, queueing, preemption — is untouched; only the two hot
loops move onto the device.

Bit-compatibility contract (SURVEY.md §7): with percentageOfNodesToScore=100
the host path evaluates every node, the rotating start index is a no-op, and
selection reduces to (max total score, seeded-rng tie-break over winners in
snapshot node order) — which is exactly what this backend computes, so TPU
and host decisions are identical. Golden tests enforce it.

Fallback: pods using features the kernel doesn't model yet (exotic
match_fields, hostIP-specific ports, term-slot overflow), claim/extender
pods, and preemption aftermath (nominated pods) run the host path via
super() — mirroring how the reference composes host + extender paths in one
cycle. Inter-pod (anti)affinity — both incoming-pod terms and existing-pod
terms — runs fully in-kernel (the dense topologyToMatchedTermCount of
interpodaffinity/filtering.go:91-185, scoring.go:81-257).
"""

from __future__ import annotations

import functools

import numpy as np

import jax

from ...api.resource import ResourceNames
from ...api.types import Pod
from ...ops import (
    DeviceFlakeError,
    FallbackNeeded,
    KernelConfig,
    PlaneBuilder,
    PodFeatureExtractor,
    stack_features,
)
from ...ops.kernels import FILTER_NAMES, dedup_fast_capable
from ...parallel.mesh import context_from_env
from ...utils import faultinject
from ..framework.interface import (
    Diagnosis,
    FitError,
    NodeToStatus,
    ScheduleResult,
    Status,
)
from ..schedule_one import SchedulingAlgorithm, num_feasible_nodes_to_find
from .devicetelemetry import tree_nbytes
from .flightrecorder import FlightRecorder

@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rows_jit(dev: dict, rows: dict, idx):
    """Row-scatter every plane in one program (one dispatch, donated
    buffers): dev[k][idx] = rows[k] for all planes simultaneously."""
    return {k: dev[k].at[idx].set(rows[k]) for k in dev}


def _bucket_label(bucket_sizes) -> str:
    """Compact shape label for the compile tracker's metrics series."""
    return "nb" + "x".join(str(b) for b in bucket_sizes)


def _wave_label(bucket_sizes, pad: int, uniq) -> str:
    g = len(uniq) if uniq is not None else 0
    return f"pad{pad}/g{g}/{_bucket_label(bucket_sizes)}"


def _mt_stream(rng_state) -> np.random.RandomState:
    """numpy RandomState sharing the MT19937 position of a CPython
    random.Random state — uint32 full-range randint maps 1:1 onto genrand
    words, so the two generators walk the same word stream."""
    _version, mt, _gauss = rng_state
    rs = np.random.RandomState()
    rs.set_state(("MT19937", np.array(mt[:624], dtype=np.uint32), mt[624]))
    return rs


def clone_tie_words(rng, n_words: int) -> np.ndarray:
    """The rng's next n_words getrandbits(32) outputs, without advancing it."""
    rs = _mt_stream(rng.getstate())
    # host-side MT19937 stream cloning, never traced: randint needs uint64
    # to cover the closed [0, 2^32) range; the kernel only ever sees the
    # down-cast uint32 words
    return rs.randint(0, 2**32, size=n_words,
                      dtype=np.uint64).astype(np.uint32)  # kubesched-lint: disable=JIT04


def advance_rng(rng, n_words: int) -> None:
    """Advance a live random.Random by exactly n_words getrandbits(32)
    draws via the same state transplant (no Python-loop catch-up)."""
    if not n_words:
        return
    version, _mt, gauss = rng.getstate()
    rs = _mt_stream(rng.getstate())
    # same host-only uint64 as clone_tie_words: state transplant, not math
    rs.randint(0, 2**32, size=n_words, dtype=np.uint64)  # kubesched-lint: disable=JIT04
    s = rs.get_state()
    rng.setstate((version, tuple(int(x) for x in s[1]) + (int(s[2]),), gauss))


# Plugins the dense kernel fully models. In the HYBRID path these are
# skipped host-side (their work already happened on device) while the
# long-tail plugins (VolumeBinding/Zone/Restrictions/Limits,
# DynamicResources, NodeDeclaredFeatures) run on the kernel-pruned node
# set — the "framework composes host + device plugins in one cycle" design
# (SURVEY §7), mirroring how the reference composes in-tree plugins with
# out-of-process extenders.
KERNEL_FILTER_PLUGINS = frozenset({
    "NodeUnschedulable", "NodeName", "TaintToleration", "NodeAffinity",
    "NodePorts", "NodeResourcesFit", "PodTopologySpread", "InterPodAffinity",
})
KERNEL_SCORE_PLUGINS = frozenset({
    "NodeResourcesFit", "NodeResourcesBalancedAllocation", "TaintToleration",
    "NodeAffinity", "PodTopologySpread", "InterPodAffinity", "ImageLocality",
})

# Reconstructed host-path messages + codes per filter mask row.
_ROW_STATUS = {
    "NodeUnschedulable": ("unresolvable", "node(s) were unschedulable"),
    "NodeName": ("unresolvable", "node didn't match the requested node name"),
    "NodeAffinity": ("unresolvable", "node(s) didn't match Pod's node affinity/selector"),
    "NodePorts": ("unschedulable", "node(s) didn't have free ports for the requested pod ports"),
}


class NeedResync(Exception):
    """A pipelined launch cannot proceed on the device-resident carry (an
    external change touched node rows the carry doesn't account for, or the
    plane buckets changed shape): the caller must drain the pipeline, after
    which the next launch re-uploads from host truth."""


def group_feature_rows(packed: np.ndarray):
    """Group byte-identical packed feature rows (the wave-side analogue of
    Framework.sign_pod / signers.go): returns (sig_ids [P] int32, uniq_idx
    [G] int32 first-occurrence slots), group ids in first-appearance order.

    Byte equality of the packed rows — not the plugin signature string — is
    the grouping ground truth: two rows that agree byte-for-byte are the
    same kernel input by construction, so a buggy/missing signer fragment
    can never make dedup unsound (it only costs hit rate)."""
    ids = np.empty(packed.shape[0], np.int32)
    groups: dict[bytes, int] = {}
    uniq: list[int] = []
    for i in range(packed.shape[0]):
        gid = groups.setdefault(packed[i].tobytes(), len(uniq))
        if gid == len(uniq):
            uniq.append(i)
        ids[i] = gid
    return ids, np.asarray(uniq, np.int32)


class SignatureScoreCache:
    """Host bookkeeping for the device-resident cross-wave score rows.

    The kernel's fast tier materializes a per-signature score-row table
    (sig_table) that stays on device; this cache keeps the matching
    signature-bytes → slot map plus a shape/config key so the NEXT chained
    wave can hand the table back (batched_assign carry_map/sig_table) and
    replay signatures it has already scored. The device arrays themselves
    never round-trip through the host — only the dict of handles does.

    Validity contract: the table's rows are score rows AGAINST THE CARRY
    PLANES as of the end of the wave that produced it. They are only
    handed back when the next launch chains on that same carry (the
    launch-time NeedResync checks prove no external change slipped in);
    any carry invalidation — resync, poison, overflow, breaker trip —
    clears this cache too (TPUBackend.invalidate_carry)."""

    def __init__(self):
        self.slots: dict[bytes, int] = {}   # signature bytes → table slot
        self.table: dict | None = None      # device arrays from sig_table
        self.key: tuple | None = None       # (cfg, bucket_sizes, G_pad)
        self.hits = 0                        # cumulative, for stats
        self.misses = 0
        self.evictions = 0

    def clear(self) -> None:
        self.slots = {}
        self.table = None
        self.key = None

    def lookup(self, key, sig_bytes, g_pad: int):
        """carry_map [g_pad] for this wave's signatures against the cached
        table, or None when the cache is cold / keyed differently (a config
        or shape change would hand the kernel mis-shaped rows). Slot gid of
        the new wave replays from cached slot carry_map[gid]; -1 = miss."""
        if self.table is None or key != self.key:
            return None
        m = np.full(g_pad, -1, np.int32)
        for gid, b in enumerate(sig_bytes):
            m[gid] = self.slots.get(b, -1)
        return m

    def store(self, key, table, sig_bytes) -> tuple[int, int, int]:
        """Adopt a just-launched wave's table as the new resident
        generation; returns (hits, misses, evictions) of this wave's
        signatures against the PREVIOUS generation. Bounded by
        construction: the table holds exactly one generation (one wave's
        G_pad slots) — signatures absent from the new wave are evicted."""
        warm = self.table is not None and key == self.key
        hit = sum(1 for b in sig_bytes if b in self.slots) if warm else 0
        miss = len(sig_bytes) - hit
        evict = max(0, len(self.slots) - hit) if warm else len(self.slots)
        self.slots = {}
        for gid, b in enumerate(sig_bytes):
            self.slots.setdefault(b, gid)  # first-appearance wins
        self.table = table
        self.key = key
        self.hits += hit
        self.misses += miss
        self.evictions += evict
        return hit, miss, evict


class InflightWave:
    """A launched-but-uncollected batched wave: device handles only."""

    __slots__ = ("pods", "qpis", "planes", "info", "pad", "cursor_base_host",
                 "frame_shift", "poisoned", "sig_ids", "record")

    def __init__(self, pods, planes, info, pad, frame_shift, sig_ids=None):
        self.pods = pods
        self.record = None  # flight record riding along, closed after bind
        # per-slot signature group ids when the wave ran deduplicated (host
        # export maps kernel sig_scores rows back to pods through these)
        self.sig_ids = sig_ids
        self.qpis = None  # set by the scheduling loop
        self.planes = planes
        self.info = info  # kernel outputs, all still on device
        self.pad = pad
        # absolute tie-stream position where this wave's draws started, in
        # this wave's word-frame; device-known at launch (cursor_init), host-
        # known once the predecessor is collected
        self.cursor_base_host: int | None = None
        # words the live rng advanced between the predecessor's launch and
        # this launch (collects in between) — converts the predecessor's
        # final cursor into this wave's frame
        self.frame_shift = frame_shift
        self.poisoned = False

    def mark_poisoned(self) -> None:
        """Sanctioned poison hook for the scheduling loop: this wave's
        results must be discarded at collect — host state diverged from
        what its kernel assumed. In-flight-wave state is only writable
        from backend.py (kubesched-lint PIPE01); callers poison through
        this method instead of assigning the flag."""
        self.poisoned = True


class TPUBackend:
    """Planes + features + device-state bookkeeping for one cluster."""

    def __init__(self, names: ResourceNames, plugin_args: dict | None = None,
                 system_default_spread: bool = True, recorder=None,
                 context=None):
        import jax

        # execution-context seam (parallel/mesh.py): LocalContext on one
        # device, MeshContext over a node-sharded mesh — selected here once
        # (KUBE_TPU_MESH_DEVICES) and never changed, so every resident
        # device handle (base mirror, carry overlay, sig_table) shares one
        # placement for the backend's lifetime
        self._ctx = context if context is not None else context_from_env()
        args = (plugin_args or {}).get("NodeResourcesFit", {})
        ipa_args = (plugin_args or {}).get("InterPodAffinity", {})
        self.ipa_ignore_preferred_existing = bool(
            ipa_args.get("ignorePreferredTermsOfExistingPods", False)
        )
        self.names = names
        self.builder = PlaneBuilder(names)
        self.extractor = PodFeatureExtractor(
            names, self.builder.vocabs, system_default_spread=system_default_spread
        )
        self.strategy = args.get("strategy", "LeastAllocated")
        resources = args.get("resources") or {"cpu": 1, "memory": 1}
        self.fit_resources = tuple(
            (names.index_of(r), w) for r, w in sorted(resources.items(),
                                                      key=lambda kv: names.index_of(kv[0]))
        )
        shape = args.get("shape")
        # deep-tuple: KernelConfig is a static jit arg and must be hashable
        # even when the config came in as JSON/YAML lists
        self.rtc_shape = (
            tuple(sorted(tuple(p) for p in shape)) if shape else ((0, 0), (100, 100))
        )
        # Double-buffered device planes (streaming waves): buffer ONE is
        # the base host-truth mirror (`_device_planes`, written only by
        # device_inputs' put/scatter), buffer TWO is the carry overlay
        # (`_carry`, written only by the kernel's own outputs). A chained
        # launch reads {**base, **overlay} with zero upload; when the
        # overlay dies, the base owes exactly the rows in
        # `_mirror_dirty ∪ _pending_dirty` — repaid by one O(churn) row
        # scatter, not an O(cluster) re-put, so a resync no longer stalls
        # the pipeline behind a full plane upload.
        self._device_planes: dict | None = None
        self._device_version = -1
        self._device_buckets: tuple | None = None
        self._pending_dirty: set[int] | None = set()  # None = full re-put
        # rows whose BASE plane values are stale because the carry overlay
        # holds their truth (our own collected binds); base-buffer debt
        self._mirror_dirty: set[int] = set()
        self._device_tables: dict | None = None
        self._tables_src: dict | None = None
        self._uploaded_term_key: np.ndarray | None = None  # host-side copy
        self._jax = jax
        # pipelined-wave carry: the last launched kernel's output planes
        # (device arrays) feed the next launch directly, so back-to-back
        # waves chain on-device while the host processes results one wave
        # behind (the TPU-native form of the reference's scheduling/binding
        # overlap, schedule_one.go:146)
        self._carry: dict | None = None
        self._carry_rows: set[int] = set()  # rows placed since carry base
        self._carry_anti = False  # carry holds IPA anti/pref terms the host
        self._carry_pref = False  # planes may not show yet (binds in flight)
        self._carry_external = False  # an external event touched the planes
        self._inflight: InflightWave | None = None  # last launched wave
        self._advanced_since_launch = 0  # rng words collected since then
        # fine-grained wave-path timing (seconds), surfaced by the perf
        # harness next to the coarse phase profile: where does "kernel"
        # wall time actually go — host feature prep, dispatch, device wait?
        # The flight recorder owns the stopwatches; `perf` aliases its
        # wave_totals dict (same object) so existing consumers keep reading.
        self.recorder = recorder if recorder is not None else FlightRecorder()
        self.perf = self.recorder.wave_totals
        # accounted host<->device seam (transfer ledger + compile tracker +
        # memory watermark): every device_put/fetch below routes through it
        # (kubesched-lint OBS03), so per-plane byte attribution is exact
        self.telemetry = self.recorder.device_telemetry
        # signature-dedup wave scoring (ISSUE 2): group byte-identical
        # feature rows so the kernel scores each distinct signature once and
        # replays clones from the carry. Decisions are bit-identical either
        # way (golden-tested), so the switch exists for A/B and fallback.
        self.dedup_enabled = True
        # cumulative wave-composition counters for metrics/bench
        # (distinct_signature_ratio = signatures/pods; xwave_* count
        # cross-wave signature reuse — hits replay a previous chained
        # wave's resident score row without any fresh scoring pass)
        self.dedup_stats = {"pods": 0, "signatures": 0, "waves": 0,
                            "xwave_hits": 0, "xwave_misses": 0,
                            "xwave_evictions": 0}
        # cross-wave signature reuse (ISSUE 5): the kernel's resident
        # per-signature score rows survive wave boundaries while launches
        # chain on the device carry, so a repeat-heavy burst pays the full
        # scoring pass once per signature per BURST, not per wave. The
        # switch exists for A/B and golden tests; decisions are
        # bit-identical either way.
        self.cross_wave_enabled = True
        self.sig_cache = SignatureScoreCache()
        # (carry dict, allowed dirty rows) of the wave being processed RIGHT
        # NOW: single-pod re-runs inside that window must see state as of
        # THAT wave — the live carry already contains the uncollected
        # successor's placements, which come later in queue order
        self._rerun_carry: tuple[dict, set[int]] | None = None

    # -- config / planes -----------------------------------------------------

    def kernel_config(self, planes, feats=None) -> KernelConfig:
        """feats (one dict or a stacked batch) tightens n_hard/n_soft (and
        the IPA slot counts) so the kernel only traces the constraint slots
        this pod wave actually uses — inactive slots cost segment reductions
        per scan step otherwise."""
        mc = self.extractor.MAX_CONSTRAINTS
        n_hard = n_soft = mc
        n_ipa_aff = n_ipa_anti = self.extractor.MAX_IPA_TERMS
        n_ipa_pref = self.extractor.MAX_IPA_PREF
        if feats is not None:
            n_hard = int(np.asarray(feats["hard_active"]).sum(axis=-1).max())
            n_soft = int(np.asarray(feats["soft_active"]).sum(axis=-1).max())
            n_ipa_aff = int((np.asarray(feats["ipa_aff_t"]) >= 0).sum(axis=-1).max())
            n_ipa_anti = int((np.asarray(feats["ipa_anti_t"]) >= 0).sum(axis=-1).max())
            n_ipa_pref = int((np.asarray(feats["ipa_pref_t"]) >= 0).sum(axis=-1).max())
        # existing-direction statics: true when the planes already carry
        # anti/preferred terms OR the wave itself does (a placed wave pod
        # joins the carried planes mid-scan)
        wave_anti = bool(feats is not None
                         and np.asarray(feats["ipa_anti_add"]).any())
        wave_pref = bool(feats is not None
                         and np.asarray(feats["ipa_pref_add"]).any())
        # _carry_anti/_carry_pref: a pipelined wave may have placed the first
        # anti/preferred-term pod on the device carry before the host planes
        # reflect it — the statics must stay on
        existing_anti = (bool(planes.ipa_anti[: planes.n].any()) or wave_anti
                         or self._carry_anti)
        existing_pref = (bool(planes.ipa_pref[: planes.n].any()) or wave_pref
                         or self._carry_pref)
        return KernelConfig(
            strategy=self.strategy,
            fit_resources=self.fit_resources,
            rtc_shape=self.rtc_shape,
            topo_domains=self.builder.topo_domains(planes),
            max_constraints=mc,
            n_hard=n_hard,
            n_soft=n_soft,
            ipa_existing_anti=existing_anti,
            ipa_existing_pref=existing_pref,
            n_ipa_aff=n_ipa_aff,
            n_ipa_anti=n_ipa_anti,
            n_ipa_pref=n_ipa_pref,
            max_ipa_terms=self.extractor.MAX_IPA_TERMS,
            max_ipa_pref=self.extractor.MAX_IPA_PREF,
            ipa_ignore_preferred_existing=self.ipa_ignore_preferred_existing,
        )

    def sync(self, snapshot):
        """Refresh host planes from the snapshot (O(changed) by generation),
        accumulating dirty rows for the device delta-upload."""
        planes = self.builder.sync(snapshot)
        if self._pending_dirty is not None:
            dirty = self.builder.dirty_rows
            if dirty is None:
                self._pending_dirty = None  # full rebuild happened
            else:
                self._pending_dirty.update(dirty)
        return planes

    def device_inputs(self, planes, rec=None) -> dict:
        """Node planes + affinity signature tables, mirrored to device HBM.

        Call AFTER feature extraction — features intern affinity signatures.
        Unchanged planes cost nothing (version check); when only some node
        rows changed since the last upload (the steady state: each wave's
        binds dirty ≤ wave_size rows) the update is a per-plane row scatter
        instead of a full host→device re-put of every [Nb, ...] array.
        """
        full = (
            self._device_planes is None
            or self._pending_dirty is None
            or self._device_buckets != planes.bucket_sizes
            # a dirty set past half the cluster costs more to scatter
            # (gather + pow2-padded index) than to re-put wholesale
            or len(self._pending_dirty) > max(64, planes.n // 2)
        )
        if full:
            self._cold_start_upload(planes, rec)
        elif self._pending_dirty:
            # NOTE: no version guard — after invalidate_carry folds the
            # mirror debt into _pending_dirty, rows can be stale even when
            # planes.version hasn't moved since the last upload
            # pad the dirty index list to a pow2 bucket (repeat the first
            # index — duplicate scatter writes of identical rows are benign)
            # so XLA sees a bounded set of scatter shapes, not one per wave
            from ...ops.vocab import next_pow2

            rows = sorted(self._pending_dirty)
            pad = next_pow2(len(rows), 8) - len(rows)
            idx = np.array(rows + [rows[0]] * pad, np.int32)
            host = planes.as_dict()
            dev = self._device_planes
            # one fused jitted scatter for every plane: eager per-plane
            # .at[].set() dispatches (and first-compiles) one tiny program
            # per plane per idx-bucket — a dozen device round-trip latencies
            # per wave on a tunneled chip. ipa_term_key is GLOBAL (not
            # row-indexed): re-upload it whenever its content moved (a new
            # term interned mid-run dirties every row but not the shape —
            # a stale device copy maps the new term to key slot -1 and the
            # kernel rejects every node).
            scatter_in = {k: v for k, v in dev.items() if k != "ipa_term_key"}
            rows_host = {k: host[k][idx] for k in scatter_in}
            # explicit accounted put of the scattered rows (and index)
            # instead of letting the jit call transfer them implicitly:
            # same avals, same compiled program, exact byte attribution.
            # Replicated placement even under a mesh: the gathered rows'
            # leading axis is the dirty-row set, NOT the node axis — each
            # shard applies the scatter and keeps the rows that land in
            # its partition
            rows_dev = self.telemetry.accounted_put(
                "delta_rows", rows_host, put=self._ctx.put_replicated,
                record=rec)
            idx_dev = self.telemetry.accounted_put(
                "delta_idx", idx, put=self._ctx.put_replicated, record=rec)
            with self.telemetry.compile_span(
                    "scatter_rows", ("scatter", planes.bucket_sizes, len(idx)),
                    label=f"rows{len(idx)}", record=rec):
                updated = _scatter_rows_jit(scatter_in, rows_dev, idx_dev)
            updated["ipa_term_key"] = dev["ipa_term_key"]
            self._device_planes = updated
        self._fresh_term_key(planes, rec)
        self._device_version = planes.version
        self._device_buckets = planes.bucket_sizes
        self._pending_dirty = set()
        self._refresh_tables(planes, rec)
        self.telemetry.note_resident(
            "planes", tree_nbytes(self._device_planes), rec)
        return {**self._device_planes, **self._device_tables}

    def _cold_start_upload(self, planes, rec=None) -> None:
        """The ONE sanctioned full-plane re-put of the node planes
        (kubesched-lint SHARD01): cold start, bucket reshape, lost row
        tracking (builder full rebuild), or a dirty set so large a
        wholesale put beats the scatter. Every other base-mirror repair
        is an O(churn) delta row scatter through device_inputs — a burst
        at 100k nodes must never come through here in steady state (the
        bench's upload-flatness criterion pins this)."""
        self._device_planes = self.telemetry.accounted_put(
            "node_planes", planes.as_dict(), put=self._ctx.put,
            record=rec)
        self._uploaded_term_key = planes.ipa_term_key.copy()
        self._mirror_dirty = set()

    def _fresh_term_key(self, planes, rec=None) -> None:
        """Re-upload the GLOBAL ipa_term_key table when its HOST content
        moved (a new term interned mid-run): the comparison is host-side
        only (last-uploaded copy), so the steady state costs no device
        traffic. Called from every device-input assembly point — the
        scatter path skips this table, and the carry path bypasses
        device_inputs entirely."""
        host_tk = planes.ipa_term_key
        if (self._uploaded_term_key is not None
                and np.array_equal(self._uploaded_term_key, host_tk)):
            return
        if self._device_planes is not None:
            self._device_planes["ipa_term_key"] = self.telemetry.accounted_put(
                "ipa_term_key", host_tk, put=self._ctx.put, record=rec)
        self._uploaded_term_key = host_tk.copy()

    def _refresh_tables(self, planes, rec=None) -> None:
        tables = self.extractor.affinity_tables(planes)
        if self._tables_src is not tables:
            self._device_tables = self.telemetry.accounted_put(
                "affinity_tables", tables, put=self._ctx.put,
                record=rec)
            self._tables_src = tables
            self.telemetry.note_resident(
                "tables", tree_nbytes(self._device_tables), rec)

    def _carry_view(self, planes) -> dict:
        """Device inputs for a single-pod cycle while the wave pipeline's
        carry is live.

        During a wave's result-processing window (collect set _rerun_carry)
        re-runs read THAT wave's output planes — state as of that wave, not
        the uncollected successor's (whose pods come later in queue order).
        Host assumes of the same wave's successful pods dirty exactly the
        rows the wave's outputs already hold (identical int updates), so
        those rows are consumable; any other dirt disqualifies the view
        (e.g. a gang member's in-snapshot assume on the same row)."""
        if self._carry is not None:
            compatible = (
                not self._carry_external
                and self._device_buckets == planes.bucket_sizes
                and self._pending_dirty is not None
            )
            if compatible and self._rerun_carry is not None:
                carry, allowed = self._rerun_carry
                if not (self._pending_dirty - allowed):
                    # consumable dirt: the overlay holds those rows' truth;
                    # the BASE buffer now owes them (mirror debt)
                    self._mirror_dirty |= self._pending_dirty
                    self._pending_dirty = set()
                    self._device_version = planes.version
                    self._refresh_tables(planes)
                    self._fresh_term_key(planes)
                    return {**self._device_planes, **carry,
                            **self._device_tables}
            elif compatible and self._pending_dirty == set():
                self._device_version = planes.version
                self._refresh_tables(planes)
                self._fresh_term_key(planes)
                return {**self._device_planes, **self._carry,
                        **self._device_tables}
            self.invalidate_carry()
        return self.device_inputs(planes)

    # -- single-pod kernel cycle ---------------------------------------------

    def run(self, pod: Pod, snapshot):
        """One pod against the whole cluster; returns kernel outputs (numpy)
        plus the planes used. Raises FallbackNeeded when not kernelizable."""
        self.extractor.register(pod)
        planes = self.sync(snapshot)
        f = self.extractor.features(pod, planes)
        dev = self._carry_view(planes)
        cfg = self.kernel_config(planes, f)
        self.telemetry.account_upload("features", tree_nbytes(f))
        with self.telemetry.compile_span(
                "fit_and_score",
                (cfg, planes.bucket_sizes, self._ctx.n_shards),
                label=_bucket_label(planes.bucket_sizes)):
            out = self._ctx.fit_and_score(cfg, dev, f)
        return planes, {
            k: self.telemetry.accounted_fetch("scores", out[k])
            for k in ("fails", "feasible", "insufficient",
                      "too_many_pods", "total")
        }

    def run_batched(self, pods: list[Pod], snapshot, rng=None,
                    pad_to: int = 0):
        """Greedy batched assignment of a pod wave in one device program.

        With rng (the scheduling algorithm's seeded random.Random) the wave's
        tie-breaks are bit-identical to the host path scheduling the same
        pods sequentially: the rng's future getrandbits(32) stream is cloned
        into the kernel, and the live rng is advanced by exactly the words
        the kernel consumed.

        Returns (node names per pod or None, planes). The caller applies the
        same assumes host-side so cache and device state stay coherent."""
        from ...ops.kernels import MAX_TIE_DRAWS

        from ...ops import pad_features

        for pod in pods:
            self.extractor.register(pod)
        planes = self.sync(snapshot)
        feats = stack_features(
            [self.extractor.features_cached(p, planes) for p in pods]
        )
        if pad_to > len(pods):
            # one static batch shape per configured wave size → one compile
            feats = pad_features(feats, pad_to)
        n_slots = max(pad_to, len(pods))
        dev = self.device_inputs(planes)
        cfg = self.kernel_config(planes, feats)
        sig_ids, uniq, _ = self._group_wave(feats, len(pods))
        tie_words = None
        if rng is not None:
            # vectorized stream cloning instead of n_slots*16 interpreter-
            # level getrandbits calls
            tie_words = clone_tie_words(
                rng, n_slots * MAX_TIE_DRAWS + MAX_TIE_DRAWS
            )
        self.telemetry.account_upload(
            "features", tree_nbytes(feats) + tree_nbytes(tie_words))
        with self.telemetry.compile_span(
                "batched_assign",
                (cfg, planes.bucket_sizes, n_slots,
                 len(uniq) if uniq is not None else 0,
                 tie_words is not None, False, False, self._ctx.n_shards),
                label=_wave_label(planes.bucket_sizes, n_slots, uniq)):
            _winners_dev, info = self._ctx.batched_assign(
                cfg, dev, feats, tie_words, sig_ids=sig_ids, uniq_idx=uniq)
        # ONE device→host transfer for everything the host needs: winners ++
        # [tie_consumed, tie_overflow] (separate np.asarray calls each pay
        # the tunnel's full round-trip latency)
        packed = self.telemetry.accounted_fetch("results", info["packed"])
        winners, consumed, overflow = (
            packed[: len(pods)], int(packed[-2]), bool(packed[-1])
        )
        if rng is not None:
            if overflow:
                # a step exhausted its draw words (p < 2^-16 per tied step):
                # results past that step are desynced from the host stream —
                # discard the wave, untouched rng, host path decides
                raise FallbackNeeded("tie-break draw overflow")
            advance_rng(rng, consumed)
        return [planes.node_names[w] if w >= 0 else None for w in winners], planes

    def _group_wave(self, feats, n_real: int):
        """Signature-group a (possibly padded) stacked feature batch:
        returns (sig_ids [P_pad], uniq_idx [G_pad], sig_bytes [G]) for
        batched_assign, or (None, None, None) with dedup disabled. uniq_idx
        is padded to a pow2 bucket (floor 8, repeating the first group's
        slot) so the per-wave distinct count doesn't fan out XLA program
        shapes; sig_bytes holds the G real groups' packed-row bytes — the
        cross-wave cache key material."""
        if not self.dedup_enabled:
            return None, None, None
        from ...ops.planes import pack_features
        from ...ops.vocab import next_pow2

        packed_rows, _ = pack_features(feats)
        sig_ids, uniq = group_feature_rows(packed_rows)
        self.dedup_stats["pods"] += n_real
        self.dedup_stats["signatures"] += int(sig_ids[:n_real].max()) + 1
        self.dedup_stats["waves"] += 1
        sig_bytes = tuple(packed_rows[i].tobytes() for i in uniq)
        gp = next_pow2(len(uniq), floor=8)
        if gp > len(uniq):
            uniq = np.concatenate(
                [uniq, np.full(gp - len(uniq), uniq[0], np.int32)]
            )
        return sig_ids, uniq, sig_bytes

    # -- gang wave -------------------------------------------------------------

    def run_gang(self, pods: list[Pod], snapshot, placements,
                 n_constrained: int, has_fallback: bool, rng):
        """Whole-PodGroup device placement (README "Gang waves"): ONE
        program scans the gang over every topology-domain mask at once
        (ops.kernels.gang_assign) instead of the host cycle's per-domain
        dry runs — each of which pays a full sequence of single-pod kernel
        dispatches against a placement-narrowed snapshot rebuild.

        `placements` is the host PlacementGenerate output in plugin order:
        rows [0, n_constrained) are the topology domains, and when
        has_fallback row n_constrained is the unconstrained parent
        (Preferred topology / no placement plugins). Returns (hosts,
        win_row, record) on success — hosts aligned with `pods`, win_row
        an index into `placements` — or None when the gang must ride the
        host cycle (no feasible domain, tie overflow, non-kernel feature).
        The live rng advances by the winning domain's tie draws ONLY on
        success; every fallback leaves it untouched, so the host cycle
        re-derives bit-identical decisions from the same stream."""
        from ...ops import pad_features
        from ...ops.kernels import MAX_TIE_DRAWS, gang_assign
        from ...ops.planes import placement_masks
        from ...ops.vocab import next_pow2

        rec = self.recorder.begin_wave(pods=len(pods))
        rec.gang_groups = 1
        rec.gang_pods = len(pods)
        try:
            with self.recorder.wave_phase("sync", rec):
                for pod in pods:
                    self.extractor.register(pod)
                planes = self.sync(snapshot)
            with self.recorder.wave_phase("features", rec):
                feats = stack_features(
                    [self.extractor.features_cached(p, planes) for p in pods]
                )
        except FallbackNeeded as e:
            rec.gang_fallback_pods = len(pods)
            rec.gang_outcome = f"fallback:{e}"
            self.recorder.end_wave(rec, fallback_reason=str(e))
            return None
        pad_to = next_pow2(len(pods), floor=4)
        if pad_to > len(pods):
            feats = pad_features(feats, pad_to)
        # masks ride in host placement order; pad rows (pow2 program shape)
        # stay all-False and can never win (empty valid set places nobody)
        n_rows = next_pow2(len(placements), floor=2)
        masks = placement_masks(
            planes, [list(p.node_names) for p in placements], n_rows
        )
        dev = self._carry_view(planes)
        cfg = self.kernel_config(planes, feats)
        # one frame covers the WORST single domain (every domain replays
        # the stream from cursor 0, mirroring the host's dry-run restores)
        tie_words = clone_tie_words(
            rng, pad_to * MAX_TIE_DRAWS + MAX_TIE_DRAWS
        )
        self.telemetry.account_upload(
            "features", tree_nbytes(feats) + tree_nbytes(tie_words), rec)
        self.telemetry.account_upload("gang_masks", masks.nbytes, rec)
        with self.recorder.wave_phase("kernel", rec), \
                self.telemetry.compile_span(
                    "gang_assign",
                    (cfg, planes.bucket_sizes, pad_to, n_rows,
                     int(n_constrained), bool(has_fallback),
                     self._ctx.n_shards),
                    label=(f"gang{pad_to}/d{n_rows}/"
                           f"{_bucket_label(planes.bucket_sizes)}"),
                    record=rec):
            packed_dev = gang_assign(
                cfg, dev, feats, masks, tie_words,
                n_constrained=n_constrained, has_fallback=has_fallback
            )
        with self.recorder.wave_phase("wait", rec):
            packed = self.telemetry.accounted_fetch("results", packed_dev,
                                                    rec)
        d, p = n_rows, pad_to
        winners = packed[: d * p].reshape(d, p)
        consumed = packed[d * p: d * p + d]
        overflow = packed[d * p + d: d * p + 2 * d]
        placed = packed[d * p + 2 * d: d * p + 3 * d]
        win_d, ok = int(packed[-3]), bool(packed[-2])
        n_real = len(placements)
        if overflow[:n_real].any():
            # a truncated draw desynchronizes that domain's VERDICT, not
            # just its stream — the whole gang verdict is untrustworthy
            rec.gang_fallback_pods = len(pods)
            rec.gang_outcome = "fallback:tie-break draw overflow"
            self.recorder.end_wave(
                rec, fallback_reason="gang tie-break draw overflow")
            return None
        if not ok:
            # group-level nomination hint: the domain that placed the most
            # members is the near-miss — recorded on the wave record so
            # operators (and the host cycle's preemption) see WHERE the
            # gang almost fit; actual preemption stays host-side
            near = int(np.argmax(placed[:n_real])) if n_real else -1
            hint = ""
            if near >= 0:
                hint = (f" near={placements[near].name}"
                        f" placed={int(placed[near])}/{len(pods)}")
            rec.gang_fallback_pods = len(pods)
            rec.gang_outcome = "fallback:no-domain" + hint
            self.recorder.end_wave(
                rec, fallback_reason="gang: no feasible domain")
            return None
        hosts = [planes.node_names[int(w)]
                 for w in winners[win_d][: len(pods)]]
        advance_rng(rng, int(consumed[win_d]))
        rec.gang_outcome = f"device:{placements[win_d].name}"
        self.recorder.end_wave(rec)
        self.recorder.count_gang_pods("device", len(pods))
        return hosts, win_d, rec

    # -- pipelined wave launch/collect ----------------------------------------

    def invalidate_carry(self) -> None:
        """Drop the carry overlay (device buffer two); the BASE plane
        buffer stays valid except for the rows the overlay owned
        (`_mirror_dirty`) plus whatever was already pending — folded into
        `_pending_dirty` so the next device_inputs repairs the base with
        one O(churn) row scatter instead of an O(cluster) re-put. A full
        re-put is still owed when row tracking itself was lost
        (`_pending_dirty is None`: builder full rebuild / bucket reshape)."""
        if self._carry is not None:
            self.recorder.carry_invalidated()
        self._carry = None
        self._carry_rows = set()
        self._carry_anti = self._carry_pref = False
        self._carry_external = False
        self._rerun_carry = None
        if self._pending_dirty is not None:
            self._pending_dirty |= self._mirror_dirty
        self._mirror_dirty = set()
        # resident score rows are scores AGAINST the carry planes — they
        # die with it
        self.sig_cache.clear()
        self.telemetry.note_resident("carry", 0)
        self.telemetry.note_resident("sig_table", 0)

    def mark_external(self) -> None:
        """An event outside the wave pipeline's own writeback touched
        cluster state (node change, foreign pod add/update/delete, host-path
        assume/forget): the carry no longer mirrors host truth — the next
        launch drains the pipeline and re-uploads. Cheap no-op when no carry
        is live."""
        if self._carry is not None:
            self._carry_external = True

    def launch_batched(self, pods: list[Pod], snapshot, rng=None,
                       pad_to: int = 0) -> InflightWave:
        """Dispatch one wave's kernel WITHOUT waiting for results.

        The kernel's input planes are the previous launch's output planes
        (still on device — XLA sequences the dependency), so consecutive
        launches chain with no host round trip; the host processes wave i-1
        while the device runs wave i. The tie-break stream is cloned from
        the live rng into the in-flight frame; an uncollected predecessor's
        final cursor rides along as a device scalar (cursor_init).

        Raises NeedResync when the carry can't absorb host-side changes
        (external dirty rows / bucket reshape) — caller drains the pipeline
        and retries — and FallbackNeeded for non-kernelizable pods."""
        from ...ops import pad_features
        from ...ops.kernels import MAX_TIE_DRAWS

        try:
            # before any state is touched: an injected launch flake leaves
            # the carry, inflight frame, and rng exactly as they were
            faultinject.fire("tpu.launch")
        except faultinject.FaultInjected as e:
            raise DeviceFlakeError(f"injected launch fault: {e}") from e
        self._rerun_carry = None  # a new launch closes any re-run window
        rec = self.recorder.begin_wave(pods=len(pods))
        with self.recorder.wave_phase("sync", rec):
            for pod in pods:
                self.extractor.register(pod)
            planes = self.sync(snapshot)
        with self.recorder.wave_phase("features", rec):
            feats = stack_features(
                [self.extractor.features_cached(p, planes) for p in pods]
            )
            if pad_to > len(pods):
                feats = pad_features(feats, pad_to)
            pad = max(pad_to, len(pods))
        rec.pad = pad

        prev = self._inflight
        chained = False
        try:
            if prev is not None and self._carry is None:
                # a single-pod cycle (or divergence) dropped the carry while
                # a wave is still in flight: host planes lack that wave's
                # placements, so a host re-upload here would double-book nodes
                raise NeedResync("carry dropped while a wave is in flight")
            if self._carry is not None:
                if self._carry_external:
                    raise NeedResync("external event touched cluster state")
                if self._device_buckets != planes.bucket_sizes:
                    raise NeedResync("plane buckets changed under the carry")
                if self._pending_dirty is None:
                    raise NeedResync("full plane rebuild required")
                external = self._pending_dirty - self._carry_rows
                if external:
                    raise NeedResync(f"{len(external)} externally-dirtied rows")
                # remaining dirty rows are our own collected binds — the
                # carry overlay already holds their exact values (same int
                # updates), so no host-truth scatter now; the BASE buffer
                # owes those rows (mirror debt, repaid by one delta scatter
                # if the overlay dies)
                self._mirror_dirty |= self._pending_dirty
                self._pending_dirty = set()
                self._device_version = planes.version
                self._refresh_tables(planes)
                self._fresh_term_key(planes)
                dev = {**self._device_planes, **self._carry,
                       **self._device_tables}
                # the carry survived every resync check: this wave chains
                # on the exact planes the resident score rows were scored
                # against, so cross-wave replay is sound
                chained = True
            else:
                with self.recorder.wave_phase("upload", rec):
                    dev = self.device_inputs(planes, rec)
        except NeedResync as e:
            # caller drains and retries; this attempt's record closes here
            self.recorder.end_wave(rec, fallback_reason=f"resync: {e}")
            raise

        cfg = self.kernel_config(planes, feats)
        with self.recorder.wave_phase("dedup", rec):
            sig_ids, uniq, sig_bytes = self._group_wave(feats, len(pods))
        # cross-wave signature reuse: hand the previous chained wave's
        # resident score-row table back to the kernel with a slot map so
        # already-scored signatures skip the full pass entirely
        carry_map = sig_table = xw_key = None
        if sig_ids is not None and dedup_fast_capable(cfg):
            xw_key = (cfg, planes.bucket_sizes, len(uniq))
            if chained and self.cross_wave_enabled:
                carry_map = self.sig_cache.lookup(xw_key, sig_bytes,
                                                  len(uniq))
                if carry_map is not None:
                    sig_table = self.sig_cache.table
        self.recorder.note_launch(
            rec,
            signatures=(int(sig_ids[: len(pods)].max()) + 1
                        if sig_ids is not None else 0),
            dedup=sig_ids is not None,
        )
        tie_words = None
        # np.int32, not a python int: a weak-typed scalar would give the
        # first launch a different jit signature than chained ones (whose
        # cursor rides in as a device array) — one full recompile
        cursor_init: object = np.int32(0)
        frame_shift = self._advanced_since_launch
        with self.recorder.wave_phase("tie", rec):
            if rng is not None:
                # frame covers a full predecessor + this wave (static shape
                # per pad): the predecessor may consume up to pad*MAX words
                tie_words = clone_tie_words(rng, (2 * pad + 1) * MAX_TIE_DRAWS)
                if prev is not None:
                    # predecessor's final cursor, shifted into this frame
                    # inside the next kernel's trace — no host sync/eager op
                    cursor_init = prev.info["tie_consumed"]
        with self.recorder.wave_phase("dispatch", rec):
            # the wave's stacked features (+ tie words) cross to the device
            # implicitly with this jit call — accounting-only seam entry
            self.telemetry.account_upload(
                "features", tree_nbytes(feats) + tree_nbytes(tie_words), rec)
            with self.telemetry.compile_span(
                    "batched_assign",
                    (cfg, planes.bucket_sizes, pad,
                     len(uniq) if uniq is not None else 0,
                     tie_words is not None, carry_map is not None,
                     sig_table is not None, self._ctx.n_shards),
                    label=_wave_label(planes.bucket_sizes, pad, uniq),
                    record=rec):
                _winners_dev, info = self._ctx.batched_assign(
                    cfg, dev, feats, tie_words, cursor_init,
                    frame_shift if prev is not None else 0,
                    sig_ids=sig_ids, uniq_idx=uniq,
                    carry_map=carry_map, sig_table=sig_table,
                )
        if xw_key is not None and "sig_table" in info:
            if carry_map is None:
                # nothing was replayed (cold cache / fresh upload / reuse
                # off): this wave's table starts a fresh generation
                self.sig_cache.clear()
            xw_hit, xw_miss, xw_evict = self.sig_cache.store(
                xw_key, info["sig_table"], sig_bytes
            )
            self.dedup_stats["xwave_hits"] += xw_hit
            self.dedup_stats["xwave_misses"] += xw_miss
            self.dedup_stats["xwave_evictions"] += xw_evict
            self.recorder.note_cross_wave(rec, xw_hit, xw_miss, xw_evict)
        else:
            self.sig_cache.clear()
        # next launch chains on these outputs
        self._carry = {k: info[k] for k in
                       ("used", "nonzero_used", "sel_counts")}
        for k in ("ipa_counts", "ipa_anti", "ipa_pref"):
            if k in info:
                self._carry[k] = info[k]
        self._carry_anti = self._carry_anti or bool(feats["ipa_anti_add"].any())
        self._carry_pref = self._carry_pref or bool(feats["ipa_pref_add"].any())
        # the carry overlay and resident score table now hold device memory;
        # fold the new live total into the wave's high-water mark
        self.telemetry.note_resident("carry", tree_nbytes(self._carry))
        self.telemetry.note_resident(
            "sig_table", tree_nbytes(self.sig_cache.table))
        self.telemetry.stamp_watermark(rec)
        fl = InflightWave(pods, planes, info, pad, frame_shift,
                          sig_ids=sig_ids)
        fl.record = rec
        if prev is None:
            fl.cursor_base_host = 0
        self._inflight = fl
        self._advanced_since_launch = 0
        # pipeline overlap accounting: when a predecessor was still in
        # flight, every host prep phase above (sync/features/upload/dedup/
        # tie/dispatch) ran while the device executed it — hidden time
        self.recorder.note_pipeline(rec, overlapped=prev is not None)
        # stall profiler: the double-buffer handoff bit (chained launch
        # vs cold launch into an idle device) — host-side bookkeeping
        self.recorder.stall_profiler.note_handoff(rec,
                                                  chained=prev is not None)
        return fl

    def collect(self, fl: InflightWave, rng=None):
        """Block on a launched wave's packed result (one transfer), advance
        the live rng by exactly the words it consumed, and absorb its
        placements into the carry bookkeeping. Returns (hosts, planes).

        Raises FallbackNeeded on tie-draw overflow (results discarded, rng
        untouched, carry invalidated — the successor launch, if any, must be
        poisoned by the caller)."""
        rec = fl.record
        try:
            faultinject.fire("tpu.collect")
        except faultinject.FaultInjected as e:
            # same contract as overflow: results discarded, rng untouched,
            # carry invalidated; the caller must poison any successor wave
            if self._inflight is fl:
                self._inflight = None
            self.invalidate_carry()
            if rec is not None:
                self.recorder.end_wave(
                    rec, fallback_reason=f"injected: {e}")
            raise DeviceFlakeError(f"injected collect fault: {e}") from e
        with self.recorder.wave_phase("wait", rec):
            packed = self.telemetry.accounted_fetch(
                "results", fl.info["packed"], rec)
        winners = packed[: len(fl.pods)]
        final_abs, overflow = int(packed[-2]), bool(packed[-1])
        if self._inflight is fl:
            self._inflight = None
        if fl.poisoned:
            self.invalidate_carry()
            if rec is not None:
                self.recorder.end_wave(
                    rec, fallback_reason="poisoned: predecessor diverged")
            raise FallbackNeeded("predecessor wave diverged host-side")
        if rng is not None and overflow:
            self.invalidate_carry()
            if rec is not None:
                self.recorder.end_wave(
                    rec, fallback_reason="overflow: tie-break draw overflow")
            raise FallbackNeeded("tie-break draw overflow")
        if rng is not None:
            if fl.cursor_base_host is None:
                raise RuntimeError("wave collected before its predecessor")
            own = final_abs - fl.cursor_base_host
            # advance the LIVE rng (already past every previously collected
            # wave) by exactly this wave's consumption
            advance_rng(rng, own)
            self._advanced_since_launch += own
            succ = self._inflight
            if succ is not None and succ.cursor_base_host is None:
                # successor's draws start where ours ended, expressed in the
                # successor's (shifted) frame
                succ.cursor_base_host = final_abs - succ.frame_shift
        win_rows = {int(w) for w in winners if w >= 0}
        self._carry_rows.update(win_rows)
        # open this wave's re-run window: single-pod cycles during result
        # processing see THIS wave's output planes (see _carry_view)
        if self._carry is not None:
            carried = {k: fl.info[k] for k in self._carry if k in fl.info}
            self._rerun_carry = (carried, win_rows)
        hosts = [fl.planes.node_names[w] if w >= 0 else None for w in winners]
        return hosts, fl.planes

    # -- diagnosis reconstruction ---------------------------------------------

    def _diagnosis_row_order(self) -> list[tuple[str, int]]:
        c_max = self.extractor.MAX_CONSTRAINTS
        # interleave PTS rows the way the host plugin checks per constraint:
        # missing-key then skew, constraint by constraint
        order: list[tuple[str, int]] = [(nm, i) for i, nm in enumerate(FILTER_NAMES)]
        for c in range(c_max):
            order.append((f"pts_missing:{c}", len(FILTER_NAMES) + c))
            order.append((f"pts_skew:{c}", len(FILTER_NAMES) + c_max + c))
        # InterPodAffinity rows follow PTS (registry filter order); within
        # the plugin the host checks existing-anti, then incoming-anti, then
        # incoming-affinity (filtering.go:352-412)
        base = len(FILTER_NAMES) + 2 * c_max
        order.append(("ipa_existing_anti", base))
        order.append(("ipa_anti", base + 1))
        order.append(("ipa_aff", base + 2))
        return order

    def build_diagnosis(self, pod: Pod, planes, out) -> Diagnosis:
        """Per-node first-failure statuses exactly as the host filter chain
        would have produced them (first rejecting plugin wins, runtime
        RunFilterPlugins) — LAZILY: the first-failing row per node is one
        vectorized argmax; Status objects (message formatting, python) are
        materialized only for the nodes a consumer actually asks about.
        Preemption's candidate scan touches ~10% of nodes, so the eager
        O(N)-python walk this replaces dominated every FitError at scale."""
        diagnosis = Diagnosis()
        order = self._diagnosis_row_order()
        hard_keys = self._hard_constraint_keys(pod)
        # tolerance per taint-vocab entry, for host-identical taint messages
        from ...api.types import Taint

        v = self.builder.vocabs
        tol = [
            any(tl.tolerates(Taint(*v.taints.key(j))) for tl in pod.spec.tolerations)
            for j in range(len(v.taints))
        ]
        lazy = _LazyKernelStatuses(self, planes, out, order, hard_keys, tol)
        diagnosis.node_to_status = lazy
        diagnosis.unschedulable_plugins |= lazy.failing_plugins()
        return diagnosis

    def _hard_constraint_keys(self, pod: Pod) -> list[str]:
        from ..plugins.pod_topology_spread import PodTopologySpread

        pts = PodTopologySpread(system_defaulting=self.extractor.system_default_spread)
        return [c.topology_key for c in pts._constraints_for(pod, "DoNotSchedule")]

    def _row_to_status(self, name: str, i: int, planes, out, hard_keys, tol) -> Status:
        v = self.builder.vocabs
        if name == "TaintToleration":
            # the first *intolerable* taint, matching the host filter's
            # first-rejection message (basics.py TaintToleration.filter)
            msg = "node(s) had untolerated taint"
            for tid in planes.taints[i]:
                if tid >= 0 and not tol[int(tid)]:
                    key, val, _eff = v.taints.key(int(tid))
                    msg = f"node(s) had untolerated taint {{{key}: {val}}}"
                    break
            return Status.unresolvable(msg, plugin="TaintToleration")
        if name == "NodeResourcesFit":
            reasons = []
            if out["too_many_pods"][i]:
                reasons.append("Too many pods")
            for r in range(out["insufficient"].shape[0]):
                if out["insufficient"][r, i]:
                    rname = (self.names.names[r] if r < self.names.width else f"res{r}")
                    reasons.append(f"Insufficient {rname}")
            return Status.unschedulable(*reasons, plugin="NodeResourcesFit")
        if name.startswith("pts_missing:"):
            c = int(name.split(":")[1])
            key = hard_keys[c] if c < len(hard_keys) else "?"
            return Status.unresolvable(
                f"node(s) didn't have required label {key}", plugin="PodTopologySpread"
            )
        if name.startswith("pts_skew:"):
            return Status.unschedulable(
                "node(s) didn't match pod topology spread constraints",
                plugin="PodTopologySpread",
            )
        if name == "ipa_existing_anti":
            return Status.unschedulable(
                "node(s) had pods with anti-affinity rules rejecting the pod",
                plugin="InterPodAffinity",
            )
        if name == "ipa_anti":
            return Status.unschedulable(
                "node(s) didn't satisfy pod anti-affinity rules",
                plugin="InterPodAffinity",
            )
        if name == "ipa_aff":
            return Status.unschedulable(
                "node(s) didn't satisfy pod affinity rules",
                plugin="InterPodAffinity",
            )
        kind, msg = _ROW_STATUS[name]
        ctor = Status.unresolvable if kind == "unresolvable" else Status.unschedulable
        return ctor(msg, plugin=name)


class _LazyKernelStatuses(NodeToStatus):
    """NodeToStatus over the kernel's dense failure rows: one numpy argmax
    finds every node's first-failing row up front; Status objects
    materialize per node on get() (memoized). Host-stage overlays written
    via set() take precedence (they are more specific)."""

    def __init__(self, backend, planes, out, order, hard_keys, tol):
        super().__init__()
        import numpy as _np

        self._backend = backend
        self._planes = planes
        self._out = out
        self._hard_keys = hard_keys
        self._tol = tol
        self._memo: dict[int, Status] = {}
        self._unsched_names = None
        self._fit_names = None
        self._row_names = [name for name, _ in order]
        fails = _np.asarray(out["fails"])[:, : planes.n]
        ordered = fails[[row for _, row in order], :]
        self._first = _np.argmax(ordered, axis=0)
        # real (non-padding) infeasible nodes with a recorded failure row
        self._failed = (ordered.any(axis=0)
                        & ~_np.asarray(out["feasible"])[: planes.n])
        self._index = planes.node_index

    def failing_plugins(self) -> set:
        import numpy as _np

        out = set()
        for r in _np.unique(self._first[self._failed]):
            name = self._row_names[int(r)]
            if name.startswith("pts_"):
                out.add("PodTopologySpread")
            elif name.startswith("ipa_"):
                out.add("InterPodAffinity")
            else:
                out.add(name)
        return out

    def set(self, node_name: str, status: Status) -> None:
        super().set(node_name, status)
        self._unsched_names = None  # overlays invalidate the bulk caches
        self._fit_names = None

    def get(self, node_name: str) -> Status:
        st = self.node_to_status.get(node_name)
        if st is not None:
            return st
        i = self._index.get(node_name)
        if i is None or i >= len(self._first) or not self._failed[i]:
            return self.absent_nodes_status
        st = self._memo.get(i)
        if st is None:
            name = self._row_names[int(self._first[i])]
            st = self._memo[i] = self._backend._row_to_status(
                name, i, self._planes, self._out, self._hard_keys, self._tol
            )
        return st

    # row name -> Status code kind mirrored from _row_to_status
    _UNSCHEDULABLE_ROWS = ("NodePorts", "NodeResourcesFit", "pts_skew",
                           "ipa_existing_anti", "ipa_anti", "ipa_aff")

    def unschedulable_name_set(self) -> set:
        """Names whose status code is plain UNSCHEDULABLE (preemption's
        candidate precheck) — one vectorized pass instead of a Status
        materialization per node. Overlay entries take precedence."""
        cached = getattr(self, "_unsched_names", None)
        if cached is not None:
            return cached
        import numpy as _np

        rows = [r for r, name in enumerate(self._row_names)
                if name.split(":")[0] in self._UNSCHEDULABLE_ROWS]
        mask = self._failed & _np.isin(self._first, rows)
        names = {self._planes.node_names[i] for i in _np.nonzero(mask)[0]}
        from ..framework.interface import UNSCHEDULABLE as _U

        for n, st in self.node_to_status.items():
            if st.code == _U:
                names.add(n)
            else:
                names.discard(n)
        self._unsched_names = names
        return names

    def fit_verdict_names(self) -> set:
        """Names whose FIRST failing filter is NodeResourcesFit (the
        batched victims-search precondition)."""
        cached = getattr(self, "_fit_names", None)
        if cached is not None:
            return cached
        import numpy as _np

        fit_row = self._row_names.index("NodeResourcesFit")
        mask = self._failed & (self._first == fit_row)
        names = {self._planes.node_names[i] for i in _np.nonzero(mask)[0]}
        for n, st in self.node_to_status.items():
            if st.plugin == "NodeResourcesFit":
                names.add(n)
            else:
                names.discard(n)
        self._fit_names = names
        return names

    def aggregate_reasons(self) -> dict[str, int]:
        """Vectorized FitError aggregation: identical strings and counts to
        materializing every node's Status, without the O(N)-python walk."""
        import numpy as _np

        reasons: dict[str, int] = {}

        def bump(msg: str, n: int) -> None:
            if n:
                reasons[msg] = reasons.get(msg, 0) + int(n)

        first = self._first
        failed = self._failed
        for r, name in enumerate(self._row_names):
            mask = failed & (first == r)
            count = int(mask.sum())
            if not count:
                continue
            if name == "NodeResourcesFit":
                ins = _np.asarray(self._out["insufficient"]
                                  )[:, : len(mask)]
                bump("Too many pods", int(
                    (_np.asarray(self._out["too_many_pods"])[: len(mask)]
                     & mask).sum()))
                for col in range(ins.shape[0]):
                    n = int((ins[col] & mask).sum())
                    rname = (self._backend.names.names[col]
                             if col < self._backend.names.width
                             else f"res{col}")
                    bump(f"Insufficient {rname}", n)
            elif name == "TaintToleration":
                # per-node FIRST intolerable taint id, then count per id
                taints = _np.asarray(self._planes.taints)[: len(mask)]
                intol = _np.zeros_like(taints, dtype=bool)
                for j, ok in enumerate(self._tol):
                    if not ok:
                        intol |= taints == j
                has = intol.any(axis=1)
                firstcol = _np.argmax(intol, axis=1)
                tids = taints[_np.arange(len(mask)), firstcol]
                for tid in _np.unique(tids[mask & has]):
                    key, val, _eff = self._backend.builder.vocabs.taints.key(
                        int(tid))
                    bump(f"node(s) had untolerated taint {{{key}: {val}}}",
                         int((tids == tid)[mask & has].sum()))
                bump("node(s) had untolerated taint",
                     int((mask & ~has).sum()))
            else:
                st = None
                # constant-message rows: materialize ONE status for text
                idx = int(_np.argmax(mask))
                st = self._backend._row_to_status(
                    name, idx, self._planes, self._out, self._hard_keys,
                    self._tol)
                for rr in st.reasons:
                    bump(rr, count)
        # host-stage overlays (kernel-feasible nodes the long tail
        # rejected) are disjoint from the kernel-failed set
        for st in self.node_to_status.values():
            for rr in st.reasons:
                bump(rr, 1)
        return reasons


class TPUSchedulingAlgorithm(SchedulingAlgorithm):
    """schedulePod with the dense kernel on the hot path.

    Inherits select_host (seeded-rng tie-break) and the host path for
    fallback, so decisions match the host algorithm bit-for-bit at
    percentageOfNodesToScore=100."""

    def __init__(self, framework, backend: TPUBackend, rng=None,
                 nominator=None, host_tail_percentage: int = 0):
        super().__init__(framework, percentage_of_nodes_to_score=100,
                         rng=rng, nominator=nominator)
        from .circuitbreaker import CircuitBreaker

        self.backend = backend
        self.fallback_count = 0
        self.kernel_count = 0
        # degradation ladder rung 3: after N consecutive DEVICE failures
        # (DeviceFlakeError — benign fallbacks don't count) waves bypass
        # the device and ride the host tier until probe waves succeed
        self.breaker = CircuitBreaker(
            on_transition=self._on_breaker_transition)
        # pod key -> node-neutral PodVolumes assumed at wave admission
        self._wave_plans: dict[str, object] = {}
        # the dense kernel evaluates EVERY node for free, so the kernel
        # path stays at 100%; the HYBRID path's host long-tail stage is
        # where per-node work costs, and it follows the reference's own
        # adaptive sampling (numFeasibleNodesToFind + rotation + early
        # exit, schedule_one.go:775,862) at this percentage (0 = the
        # adaptive 50-nodes/125 formula; clusters under 100 nodes always
        # evaluate everything, so small-cluster decisions are unchanged)
        self.host_tail_percentage = host_tail_percentage

    def _on_breaker_transition(self, old: str, new: str, reason: str) -> None:
        from .circuitbreaker import OPEN

        if new == OPEN:
            # trip: per-pod host scheduling is about to mutate cluster
            # state outside the wave pipeline's writeback — the resident
            # cross-wave score rows can't be trusted past this point. The
            # carry's own NeedResync checks handle the planes; the
            # signature cache must be dropped explicitly (it would
            # otherwise look warm if the carry happens to survive).
            self.backend.sig_cache.clear()
        rec = getattr(self.backend, "recorder", None)
        if rec is not None:
            rec.breaker_transition(old, new, reason)

    def schedule_pod(self, state, pod: Pod, snapshot) -> ScheduleResult:
        if snapshot.num_nodes() == 0:
            raise FitError(pod, 0, Diagnosis())
        if self.breaker.device_blocked():
            # breaker OPEN and cooling: don't pay the device round trip —
            # route straight to the host tier (pure read, no state change)
            self.fallback_count += 1
            return super().schedule_pod(state, pod, snapshot)
        pre_filter_done = None
        if pod.status.nominated_node_name:
            # evaluateNominatedNode fast path (schedule_one.go:718): try
            # the nominee host-side (ONE node); when it no longer fits,
            # fall through to the normal kernel/hybrid cycle — exactly how
            # the host path continues its scan, but without paying a full
            # per-node host chain over the whole cluster
            res, pre_filter_done = self._evaluate_nominated(
                state, pod, snapshot
            )
            if res is not None:
                self.fallback_count += 1  # host-path decision
                return res
        hybrid = (self._needs_host_compose(pod)
                  or self._has_relevant_nominations(pod))
        try:
            planes, out = self.backend.run(pod, snapshot)
        except FallbackNeeded:
            self.fallback_count += 1
            return super().schedule_pod(state, pod, snapshot)
        self.kernel_count += 1
        if hybrid:
            return self._schedule_hybrid(state, pod, snapshot, planes, out,
                                         pre_filter_done=pre_filter_done)

        feasible_idx = np.flatnonzero(out["feasible"][: planes.n])
        if feasible_idx.size == 0:
            # Populate CycleState via the host PreFilter chain before raising:
            # DefaultPreemption's victim dry-run re-runs Filter plugins against
            # this state (preemption.go SelectVictimsOnNode), and e.g.
            # PodTopologySpread.filter is a no-op without its prefilter state —
            # skipping this would let preemption nominate skew-violating nodes.
            self.fw.run_pre_filter_plugins(state, pod, snapshot.list_nodes())
            diagnosis = self.backend.build_diagnosis(pod, planes, out)
            raise FitError(pod, snapshot.num_nodes(), diagnosis)
        if feasible_idx.size == 1:
            evaluated = planes.n  # every node was evaluated by the kernel
            return ScheduleResult(
                suggested_host=planes.node_names[int(feasible_idx[0])],
                evaluated_nodes=evaluated,
                feasible_nodes=1,
            )
        totals = out["total"][feasible_idx]
        best = totals.max()
        winners = feasible_idx[totals == best]
        if winners.size > 1:
            win = int(winners[self.rng.randrange(winners.size)])
        else:
            win = int(winners[0])
        return ScheduleResult(
            suggested_host=planes.node_names[win],
            evaluated_nodes=planes.n,
            feasible_nodes=int(feasible_idx.size),
        )

    def _needs_host_compose(self, pod: Pod) -> bool:
        """Pods whose long-tail stages (volume plugins, DRA, declared
        features, HTTP extenders) must run host-side ON TOP of the kernel's
        dense feasibility/scores — the hybrid path, not a full fallback."""
        from ...api.storage import pod_claim_names
        from ..plugins.node_declared_features import infer_required_features

        if pod_claim_names(pod) or pod.spec.resource_claims:
            return True
        if infer_required_features(pod):
            return True
        # extenders ride on the feasible set exactly as in the host path
        # (filter after in-tree, prioritize added to totals)
        return bool(self.extenders
                    and any(e.is_interested(pod) for e in self.extenders))

    def wave_eligible(self, pod: Pod) -> bool:
        """Fully-kernel pods ride the batched wave, and so do claim pods
        whose volume decision is provably node-NEUTRAL (binder.
        node_neutral_volumes): their host volume stage collapses to a
        per-pod constant the wave finish applies after node selection.
        Accepting such a pod aches an immediate binder assume (stashed in
        _wave_plans) so the NEXT pod's neutrality check sees this pod's
        chosen volume — the sequential-greedy invariant the wave carries
        for resources, mirrored for volumes."""
        if self._must_fall_back(pod) or self._has_relevant_nominations(pod):
            return False
        from ...api.storage import pod_claim_names
        from ..plugins.node_declared_features import infer_required_features

        if pod.spec.resource_claims:
            return False
        if infer_required_features(pod):
            return False
        if self.extenders and any(e.is_interested(pod)
                                  for e in self.extenders):
            return False
        if pod_claim_names(pod):
            binder = self._volume_binder()
            if binder is None:
                return False
            plan = binder.node_neutral_volumes(pod)
            if plan is None:
                return False
            binder.assume_pod_volumes(plan)
            self._wave_plans[pod.meta.key] = plan
            return True
        return True

    def _volume_binder(self):
        from ..plugins.volumes import VolumeBinding

        for p in self.fw.reserve_plugins:
            if isinstance(p, VolumeBinding):
                return p.binder
        return None

    def take_wave_plan(self, pod_key: str):
        """Pop the stashed neutral volume decision (wave finish path)."""
        return self._wave_plans.pop(pod_key, None)

    def revert_wave_plan(self, pod: Pod) -> None:
        """Release a stashed plan's binder assumes — every wave path that
        re-runs the pod per-pod (launch fallback, poisoned carry, kernel
        infeasible) must call this first or the assumed PV stays reserved."""
        plan = self._wave_plans.pop(pod.meta.key, None)
        if plan is not None:
            self.safe_revert_volumes(plan)

    def safe_revert_volumes(self, plan) -> None:
        """Revert only assumes that still belong to this plan's claims — a
        later pod may have legitimately re-assumed the same PV."""
        binder = self._volume_binder()
        if binder is None:
            return
        for pv_key, pvc_key in plan.static_bindings:
            if binder.assumed.get(pv_key) == pvc_key:
                binder.assumed.pop(pv_key, None)

    def _has_relevant_nominations(self, pod: Pod) -> bool:
        """Any nominated pod (≥ priority) that must be simulated during
        this pod's filtering (schedule_one.go:1190)?"""
        if self.nominator is None:
            return False
        fn = getattr(self.nominator, "max_nominated_priority", None)
        if fn is not None:
            top = fn(exclude_key=pod.meta.key)
            return top is not None and top >= pod.spec.priority
        return getattr(self.nominator, "has_nominated_pods", lambda: False)()

    def _schedule_hybrid(self, state, pod: Pod, snapshot, planes,
                         out, pre_filter_done=None) -> ScheduleResult:
        """Kernel feasibility/scores ∩ host long-tail plugins.

        The kernel already filtered+scored the dense plugins over every
        node; the host chain runs ONLY the remaining plugins (skip sets) on
        the kernel-feasible nodes, and their weighted scores add onto the
        kernel totals. Decisions match the pure host path bit-for-bit: the
        kernel's per-plugin math is golden-tested equal to the host
        plugins', node order is snapshot order in both, and selection goes
        through the same select_host rng draw."""
        fw = self.fw
        nodes = snapshot.list_nodes()
        if pre_filter_done is not None:
            # PreFilter already ran this cycle (nominee fast path)
            pre_result, st = pre_filter_done
        else:
            pre_result, st = fw.run_pre_filter_plugins(state, pod, nodes)
        if not st.is_success:
            if st.is_rejected:
                d = Diagnosis()
                d.pre_filter_msg = st.message()
                if st.plugin:
                    d.unschedulable_plugins.add(st.plugin)
                raise FitError(pod, snapshot.num_nodes(), d)
            raise RuntimeError(f"prefilter failed: {st.reasons}")
        allowed = None
        if pre_result is not None and pre_result.node_names is not None:
            allowed = set(pre_result.node_names)
        # dense plugins already ran on device: skip their host Filter. Keep
        # the UNPOLLUTED PreFilter skip set aside — preemption's victim
        # dry-run re-runs the FULL host filter chain against this state
        # (default_preemption SelectVictimsOnNode), and must not inherit
        # kernel skips or it would evict victims for a pod that can never
        # fit (resources/taints unchecked).
        prefilter_skips = set(state.skip_filter_plugins)
        state.skip_filter_plugins = prefilter_skips | set(
            KERNEL_FILTER_PLUGINS
        )
        # host-failure statuses only; the kernel's per-node failure rows are
        # materialized lazily at the FitError site (build_diagnosis walks
        # every infeasible node — O(N) python per pod if done eagerly)
        diagnosis = Diagnosis()
        feasible_mask = out["feasible"]
        node_index = planes.node_index
        # the host long-tail stage follows findNodesThatPassFilters:775
        # exactly: rotate the start index, evaluate kernel-feasible nodes
        # in rotated order, early-exit at numFeasibleNodesToFind. The
        # kernel already gave the dense verdict for EVERY node — sampling
        # here bounds only the per-node host-plugin work. With
        # host_tail_percentage=100 (or < 100 nodes) this walks everything
        # in snapshot order, matching the host path at 100% bit-for-bit.
        host_nodes = (nodes if allowed is None
                      else [ni for ni in nodes if ni.name in allowed])
        num_all = len(host_nodes)
        num_to_find = num_feasible_nodes_to_find(
            self.host_tail_percentage, num_all
        )
        start = self.next_start_node_index % num_all if num_all else 0
        survivors: list[tuple[int, object]] = []
        evaluated = num_all
        pos = 0
        done = False
        while pos < num_all and not done:
            # chunk of kernel-feasible candidates, in rotated order
            chunk: list[tuple[int, object, int]] = []
            want = max(num_to_find - len(survivors), 1)
            while pos < num_all and len(chunk) < want:
                ni = host_nodes[(start + pos) % num_all]
                ki = node_index.get(ni.name)
                pos += 1
                if ki is not None and feasible_mask[ki]:
                    chunk.append((ki, ni, pos))  # pos = evaluated-if-last
            if not chunk:
                break
            noms = [self._nominated_pod_infos(pod, ni)
                    for _, ni, _ in chunk]
            if any(noms):
                sts = []
                for (ki, ni, _), npis in zip(chunk, noms):
                    if npis:
                        # two-pass nominated treatment
                        # (schedule_one.go:1190). Pass 1 — WITH nominated
                        # pods assumed — needs the FULL chain on an
                        # unpolluted state clone: the kernel verdict didn't
                        # model the nominated pods. Pass 2 — the bare
                        # node — keeps the kernel skips: out["feasible"]
                        # already IS the bare-node dense verdict.
                        state.skip_filter_plugins = prefilter_skips
                        state_clone = state.clone()
                        state.skip_filter_plugins = prefilter_skips | set(
                            KERNEL_FILTER_PLUGINS
                        )
                        ni_with = ni.clone()
                        for npi in npis:
                            ni_with.add_pod(npi)
                            fw.run_pre_filter_extension_add_pod(
                                state_clone, pod, npi, ni_with
                            )
                        host_st = fw.run_filter_plugins(
                            state_clone, pod, ni_with
                        )
                        if host_st.is_success:
                            host_st = fw.run_filter_plugins(state, pod, ni)
                    else:
                        host_st = fw.run_filter_plugins(state, pod, ni)
                    sts.append(host_st)
            else:
                sts = fw.run_filter_plugins_batch(
                    state, pod, [ni for _, ni, _ in chunk]
                )
            for (ki, ni, at), host_st in zip(chunk, sts):
                if host_st.is_success:
                    survivors.append((ki, ni))
                    if len(survivors) >= num_to_find:
                        evaluated = at
                        done = True
                        break
                else:
                    diagnosis.node_to_status.set(ni.name, host_st)
                    if host_st.plugin:
                        diagnosis.unschedulable_plugins.add(host_st.plugin)
        self.next_start_node_index = (
            (start + evaluated) % num_all if num_all else 0
        )
        if survivors and self.extenders:
            # extenders prune AFTER in-tree filters (findNodesThatPass-
            # Extenders, schedule_one.go:890) — same position here, on the
            # kernel∩host-feasible set
            from ..extender import find_nodes_that_pass_extenders

            interested = [e for e in self.extenders if e.is_interested(pod)]
            if interested:
                kept = find_nodes_that_pass_extenders(
                    interested, pod, [ni for _, ni in survivors], diagnosis
                )
                kept_names = {ni.name for ni in kept}
                survivors = [(i, ni) for i, ni in survivors
                             if ni.name in kept_names]
        if not survivors:
            state.skip_filter_plugins = prefilter_skips  # see above
            # materialize the kernel's per-node failure rows now (lazy —
            # the success path never pays this O(N) walk), then overlay
            # the host-stage verdicts, which are more specific
            full = self.backend.build_diagnosis(pod, planes, out)
            full.node_to_status.node_to_status.update(
                diagnosis.node_to_status.node_to_status
            )
            full.unschedulable_plugins |= diagnosis.unschedulable_plugins
            if allowed is not None:
                full.node_to_status.absent_nodes_status = Status.unresolvable(
                    "node(s) didn't satisfy plugin prefilter result"
                )
            raise FitError(pod, snapshot.num_nodes(), full)
        node_infos = [ni for _, ni in survivors]
        # kernel-covered score plugins are pre-seeded into the skip set so
        # their host PreScore precompute never runs — their weighted scores
        # are already in the kernel total (counting them host-side too
        # would double them)
        st = fw.run_pre_score_plugins(state, pod, node_infos,
                                      skip=set(KERNEL_SCORE_PLUGINS))
        if not st.is_success:
            raise RuntimeError(f"prescore failed: {st.reasons}")
        host_scores, st = fw.run_score_plugins(state, pod, node_infos)
        if not st.is_success:
            raise RuntimeError(f"score failed: {st.reasons}")
        from ..framework.interface import NodePluginScores

        ext_bonus: dict[str, int] = {}
        if self.extenders:
            from ..extender import extender_scores

            ext_bonus = extender_scores(self.extenders, pod, node_infos) or {}
        combined = []
        for (i, ni), host in zip(survivors, host_scores):
            total = (int(out["total"][i]) + host.total_score
                     + ext_bonus.get(ni.name, 0))
            combined.append(NodePluginScores(name=ni.name, scores=host.scores,
                                             total_score=total))
        host_name, _ = self.select_host(combined)
        return ScheduleResult(
            suggested_host=host_name,
            evaluated_nodes=planes.n,
            feasible_nodes=len(survivors),
        )

    def _must_fall_back(self, pod: Pod) -> bool:
        # a preemptor revisiting its own nomination is handled per-pod
        # (nominee-first in schedule_pod), never batched in a wave.
        # Everything else — including OTHER pods while nominations exist —
        # runs kernel or hybrid (nominated nodes get the host two-pass
        # treatment inside the hybrid survivor loop).
        return bool(pod.status.nominated_node_name)

    def _evaluate_nominated(self, state, pod: Pod, snapshot):
        """Host-side nominee check. Returns (result, pre_filter_done):
        result is a ScheduleResult when the nominee still fits, else None;
        pre_filter_done is the (pre_result, status) pair from the PreFilter
        pass so the hybrid continuation doesn't recompute the most
        expensive host stage for exactly the pods this fast path serves."""
        ni = snapshot.get(pod.status.nominated_node_name)
        if ni is None:
            return None, None
        pre_done = self.fw.run_pre_filter_plugins(
            state, pod, snapshot.list_nodes()
        )
        pre_result, st = pre_done
        if not st.is_success:
            return None, pre_done  # the main cycle diagnoses this
        if (pre_result is not None and pre_result.node_names is not None
                and ni.name not in pre_result.node_names):
            return None, pre_done
        diagnosis = Diagnosis()
        if self._filter_one(state, pod, ni, diagnosis):
            return ScheduleResult(
                suggested_host=ni.name, evaluated_nodes=1, feasible_nodes=1
            ), pre_done
        return None, pre_done
