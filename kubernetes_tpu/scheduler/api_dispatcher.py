"""Async API-call dispatcher + cache: binding/status writes never block the
scheduling loop.

Reference: pkg/scheduler/backend/api_dispatcher/ (APIDispatcher:32-112,
call_queue.go relevance-merge) + backend/api_cache/api_cache.go:29-61 and the
call types in pkg/scheduler/framework/api_calls/ (Relevances at
api_calls.go:33). SchedulerAsyncAPICalls feature
(pkg/features/kube_features.go:899).

Semantics preserved:
- one in-flight/queued call per object; a newer call against the same object
  merges with or replaces the queued one by relevance comparison
- a less-relevant incoming call is dropped (ErrCallSkipped)
- `parallelism` worker threads drain the queue; callers can wait on a future
"""

from __future__ import annotations

import queue as _queue
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ..store.store import ConflictError
from ..utils import faultinject
from ..utils.backoff import RetryPolicy, retry_call
from ..utils.envknob import float_env, int_env

# call-type relevance (api_calls.go Relevances): higher wins on conflict
POD_STATUS_PATCH = "pod_status_patch"
POD_BINDING = "pod_binding"
POD_DELETE = "pod_delete"  # preemption evictions supersede everything
RELEVANCES = {POD_STATUS_PATCH: 1, POD_BINDING: 2, POD_DELETE: 3}


class CallSkippedError(Exception):
    """A queued more-relevant call made this one redundant."""


class DispatcherClosedError(Exception):
    """Terminal: the dispatcher shut down before this call could run."""


def _default_retry_policy() -> RetryPolicy:
    """Transient store conflicts and injected flakes merit another attempt;
    NotFoundError (pod deleted mid-flight) and everything else must surface
    through on_finish unchanged."""
    return RetryPolicy(
        max_attempts=int_env("KUBE_TPU_RETRY_MAX", 4),
        base_s=float_env("KUBE_TPU_RETRY_BASE_S", 0.002),
        cap_s=float_env("KUBE_TPU_RETRY_CAP_S", 0.1),
        retryable=(ConflictError, faultinject.TransientFault),
    )


@dataclass
class APICall:
    call_type: str
    object_key: str
    execute: Callable[[], Any]
    on_finish: Callable[[Exception | None], None] | None = None
    done: threading.Event = field(default_factory=threading.Event)
    error: Exception | None = None

    @property
    def relevance(self) -> int:
        return RELEVANCES.get(self.call_type, 0)

    def sync_or_merge(self, older: "APICall") -> bool:
        """Can this call subsume `older`? Same type merges (latest wins);
        higher relevance replaces; lower relevance is skipped."""
        return self.relevance >= older.relevance


class APIDispatcher:
    """Queue + workers (api_dispatcher.go APIDispatcher)."""

    def __init__(self, parallelism: int = 16, metrics=None, tracer=None,
                 retry_policy: RetryPolicy | None = None, recorder=None):
        self.parallelism = parallelism
        self.metrics = metrics
        self.tracer = tracer  # optional utils.tracing.Tracer: span per call
        self.recorder = recorder  # optional FlightRecorder: retry counts
        self.retry_policy = retry_policy or _default_retry_policy()
        self._retry_rng = random.Random(0xD15)  # jitter only, never decisions
        self._queued: dict[str, APICall] = {}  # object key -> pending call
        self._executing: set[str] = set()  # keys a worker is executing now
        self._parked: set[str] = set()  # deferred keys awaiting in-flight done
        self._order: _queue.Queue = _queue.Queue()
        self._lock = threading.Lock()
        self._workers: list[threading.Thread] = []
        self._stop = threading.Event()
        self._closed = False
        self.retries = 0  # total retry attempts absorbed by backoff
        # worker busy-seconds: on a GIL'd single-core host this time is
        # stolen from the scheduling thread, so the bench wall-coverage
        # accounting must see it
        self.exec_seconds = 0.0

    # -- enqueue -------------------------------------------------------------

    def add(self, call: APICall) -> APICall:
        """Queue a call; returns the call actually representing the work (the
        merged-into call when dedup applies). Raises CallSkippedError when a
        more relevant call is already pending for the object."""
        superseded: APICall | None = None
        rejected = False
        with self._lock:
            if self._closed:
                rejected = True
            else:
                pending = self._queued.get(call.object_key)
                if pending is not None:
                    if not call.sync_or_merge(pending):
                        raise CallSkippedError(
                            f"{call.call_type} for {call.object_key} "
                            f"skipped: {pending.call_type} already queued"
                        )
                    if call.call_type == pending.call_type:
                        # same type: COMPOSE — two status patches touch
                        # independent fields; dropping one loses an update
                        old_exec, new_exec = pending.execute, call.execute

                        def composed(old_exec=old_exec, new_exec=new_exec):
                            old_exec()
                            new_exec()

                        pending.execute = composed
                        old_finish, new_finish = (pending.on_finish,
                                                  call.on_finish)
                        if old_finish is not None and new_finish is not None:
                            pending.on_finish = lambda err: (
                                old_finish(err), new_finish(err))
                        else:
                            pending.on_finish = new_finish or old_finish
                        return pending
                    # higher relevance REPLACES (a delete supersedes a
                    # binding): the superseded call never runs — its waiters
                    # must see a skip error, NOT inherit the new call's
                    # outcome (a binder waiting on a bind replaced by an
                    # eviction would otherwise 'succeed' and mark a deleted
                    # pod scheduled)
                    superseded = pending
                    self._queued[call.object_key] = call
                    # the key is already in _order; the worker will pop the
                    # replacement
                else:
                    self._queued[call.object_key] = call
                    self._order.put(call.object_key)
                if self.metrics is not None:
                    self.metrics.async_api_pending.set(len(self._queued))
        if rejected:
            # terminal, not silent: a caller that waits on call.done after
            # shutdown must wake with an error, exactly like close() treats
            # the calls it found queued
            err = DispatcherClosedError(
                f"{call.call_type} for {call.object_key} rejected: "
                "dispatcher closed"
            )
            call.error = err
            if call.on_finish is not None:
                call.on_finish(err)
            call.done.set()
            return call
        if superseded is not None:
            err = CallSkippedError(
                f"{superseded.call_type} for {superseded.object_key} "
                f"superseded by {call.call_type}"
            )
            superseded.error = err
            if superseded.on_finish is not None:
                superseded.on_finish(err)
            superseded.done.set()
        return call

    # -- workers -------------------------------------------------------------

    def supersede(self, keys: list[str], relevance: int) -> None:
        """Drop queued calls for these objects with lower relevance — used
        when a wave bind (queued under its own synthetic key) makes per-pod
        status patches moot (api_calls.go relevance ordering: a binding
        replaces a queued status patch for the same pod)."""
        dropped: list[APICall] = []
        with self._lock:
            for key in keys:
                pending = self._queued.get(key)
                if pending is not None and pending.relevance < relevance:
                    del self._queued[key]
                    dropped.append(pending)
                    if self.metrics is not None:
                        self.metrics.async_api_pending.set(len(self._queued))
        # outside the lock (on_finish may re-enter the dispatcher): a
        # superseded call never ran, so its waiters must observe
        # CallSkippedError — done.set() alone would read as success
        for pending in dropped:
            err = CallSkippedError(
                f"{pending.call_type} for {pending.object_key} superseded "
                f"by relevance {relevance}"
            )
            pending.error = err
            if pending.on_finish is not None:
                pending.on_finish(err)
            pending.done.set()

    def run(self) -> None:
        for i in range(self.parallelism):
            t = threading.Thread(target=self._worker, daemon=True,
                                 name=f"api-dispatcher-{i}")
            t.start()
            self._workers.append(t)

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                key = self._order.get(timeout=0.05)
            except _queue.Empty:
                continue
            with self._lock:
                if key in self._executing:
                    # strictly one executing call per object
                    # (call_queue.go semantics): PARK the key — the worker
                    # finishing the in-flight call re-enqueues it, so no
                    # thread spins re-putting/re-popping it every ~1ms
                    self._parked.add(key)
                    call = None
                else:
                    call = self._queued.pop(key, None)
                    if call is not None:
                        self._executing.add(key)
                    if self.metrics is not None:
                        self.metrics.async_api_pending.set(len(self._queued))
            if call is None:
                continue
            try:
                self._execute(call)
            finally:
                with self._lock:
                    self._executing.discard(key)
                    if key in self._parked:
                        self._parked.discard(key)
                        # only re-enqueue if a call is actually still queued
                        # for the key — it may have been superseded or
                        # drained while parked
                        if key in self._queued:
                            self._order.put(key)

    def _execute(self, call: APICall) -> None:
        err: Exception | None = None
        # box, not int: on_backoff is a closure mutating across attempts
        stats = {"attempts": 1, "backoff_s": 0.0}

        def attempt():
            faultinject.fire("dispatcher.execute")
            if self.tracer is not None:
                # worker threads get their own span stacks (thread-local),
                # so each api/<type> call exports as its own root span
                with self.tracer.span(f"api/{call.call_type}",
                                      object_key=call.object_key):
                    call.execute()
            else:
                call.execute()

        def on_backoff(attempt_no: int, delay_s: float) -> None:
            stats["attempts"] = attempt_no + 1
            stats["backoff_s"] += delay_s

        t0 = time.perf_counter()
        try:
            # bounded retry absorbs transient failures (store conflicts,
            # injected flakes) without ever releasing the object key: the
            # one-in-flight-per-object and relevance-supersede invariants
            # hold across attempts because the key stays in _executing
            retry_call(
                attempt,
                self.retry_policy,
                self._retry_rng,
                should_abort=self._stop.is_set,
                on_backoff=on_backoff,
            )
        except Exception as e:  # noqa: BLE001 - surfaced via on_finish
            err = e
        finally:
            with self._lock:
                self.exec_seconds += time.perf_counter() - t0
                self.retries += stats["attempts"] - 1
        if stats["attempts"] > 1:
            if self.recorder is not None:
                self.recorder.note_retries(stats["attempts"] - 1)
            if self.metrics is not None:
                self.metrics.async_api_retries.observe(
                    stats["attempts"], call.call_type
                )
                self.metrics.async_api_backoff_seconds.observe(
                    stats["backoff_s"], call.call_type
                )
        call.error = err
        if self.metrics is not None:
            self.metrics.async_api_calls.inc(
                call.call_type, "error" if err else "success"
            )
        if call.on_finish is not None:
            call.on_finish(err)
        call.done.set()

    def drain(self, timeout: float = 5.0) -> None:
        """Synchronously execute everything still queued (tests/shutdown);
        respects the one-executing-call-per-object invariant."""
        # monotonic: a wall-clock step backwards must not extend the drain
        # window (or forwards, cut it short) — this is a duration, not a time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                key = next(
                    (k for k in self._queued if k not in self._executing), None
                )
                if key is None:
                    if not self._queued and not self._executing:
                        return
                    call = None  # everything left is busy in a worker
                else:
                    call = self._queued.pop(key)
                    self._executing.add(key)
            if call is None:
                time.sleep(0.001)
                continue
            try:
                self._execute(call)
            finally:
                with self._lock:
                    self._executing.discard(key)
                    if key in self._parked:
                        self._parked.discard(key)
                        if key in self._queued:
                            self._order.put(key)

    def close(self) -> None:
        """Stop workers and FAIL whatever is still queued: every waiter on
        call.done wakes with a terminal DispatcherClosedError and on_finish
        fires exactly once — close never silently abandons a call."""
        self._stop.set()
        for t in self._workers:
            t.join(timeout=1)
        self._workers.clear()
        with self._lock:
            self._closed = True
            abandoned = list(self._queued.values())
            self._queued.clear()
            self._parked.clear()
            if self.metrics is not None:
                self.metrics.async_api_pending.set(0)
        # outside the lock: on_finish may re-enter the dispatcher
        for call in abandoned:
            err = DispatcherClosedError(
                f"{call.call_type} for {call.object_key} abandoned: "
                "dispatcher closed"
            )
            call.error = err
            if call.on_finish is not None:
                call.on_finish(err)
            call.done.set()


class APICacher:
    """api_cache.go APICacher — routes scheduler API writes through the
    dispatcher while keeping queue/cache consistent. The store write happens
    asynchronously; the cache already holds the assumed pod, so scheduling
    correctness never depends on the write having landed."""

    def __init__(self, store, dispatcher: APIDispatcher):
        self.store = store
        self.dispatcher = dispatcher
        self._wave_seq = 0

    def bind_pod(self, pod, node_name: str) -> APICall:
        def execute():
            # NotFoundError propagates: a pod deleted mid-flight must fail
            # the binding cycle so handleBindingCycleError forgets the
            # cache assume — swallowing it would leak the assumed resources
            # (the DELETED event for an unbound pod never touches the cache)
            cur = self.store.get("Pod", pod.meta.key)
            cur.spec.node_name = node_name
            self.store.update(cur, check_version=False)

        return self.dispatcher.add(
            APICall(POD_BINDING, pod.meta.key, execute)
        )

    def bind_pods(self, bindings: list[tuple[str, str]],
                  on_done: Callable[[list[bool] | None, Exception | None], None] | None = None) -> APICall:
        """One dispatcher call binding a whole wave (store.bind_pods
        transaction). The synthetic object key makes each wave its own
        dedup domain — waves never merge with or supersede each other."""
        results: list = [None]

        def execute():
            results[0] = self.store.bind_pods(bindings)

        def finish(err):
            if on_done is not None:
                on_done(results[0], err)

        # a queued failure patch for any wave member is now moot — per-pod
        # binds supersede it via same-key relevance; the wave's synthetic
        # key needs the explicit form
        self.dispatcher.supersede([k for k, _ in bindings],
                                  RELEVANCES[POD_BINDING])
        self._wave_seq += 1
        return self.dispatcher.add(APICall(
            POD_BINDING, f"__wave__/{self._wave_seq}", execute,
            on_finish=finish,
        ))

    def patch_pod_status(self, pod, condition=None, nominated_node: str | None = None) -> APICall:
        def execute():
            # atomic under the store lock: wave binds run under their own
            # dispatcher key, so this patch may execute CONCURRENTLY with
            # the bind — the store primitive both serializes the write and
            # drops a stale failure condition once the pod is bound
            self.store.patch_pod_status(
                pod.meta.key, condition=condition, nominated_node=nominated_node
            )

        return self.dispatcher.add(
            APICall(POD_STATUS_PATCH, pod.meta.key, execute)
        )
