"""NodeInfo / PodInfo — the per-node aggregates every filter/score consumes.

Reference: pkg/scheduler/framework/types.go (NodeInfo :165-208 with Requested,
NonZeroRequested, Allocatable, UsedPorts, PodsWithAffinity, ImageStates,
Generation; PodInfo with precomputed RequiredAffinityTerms). These are the rows
of the device planes: a NodeInfo's vectors are already in plane units.
"""

from __future__ import annotations

import itertools
from typing import Iterable

from ..api.labels import LabelSelector
from ..api.resource import (
    ResourceNames,
    ResourceVec,
    nonzero_request_vec,
    pod_request_vec,
)
from ..api.types import Node, Pod, PodAffinityTerm

_generation = itertools.count(1)


def next_generation() -> int:
    return next(_generation)


class AffinityTerm:
    """A PodAffinityTerm with its namespace set resolved.

    Reference: framework/types.go AffinityTerm + GetAffinityTerms.
    """

    __slots__ = ("selector", "topology_key", "namespaces")

    def __init__(self, term: PodAffinityTerm, pod_namespace: str):
        self.selector: LabelSelector | None = term.label_selector
        self.topology_key = term.topology_key
        self.namespaces = frozenset(term.namespaces) if term.namespaces else frozenset(
            (pod_namespace,)
        )

    def matches(self, pod: Pod) -> bool:
        if pod.meta.namespace not in self.namespaces:
            return False
        return self.selector is not None and self.selector.matches(pod.meta.labels)


class PodInfo:
    """Pod plus precomputed scheduling-relevant derivations (one-time cost)."""

    __slots__ = (
        "pod",
        "request",
        "nonzero_request",
        "ports",
        "pvc_keys",
        "required_affinity_terms",
        "required_anti_affinity_terms",
        "preferred_affinity_terms",
        "preferred_anti_affinity_terms",
    )

    def __init__(self, pod: Pod, names: ResourceNames):
        self.pod = pod
        self.request = pod_request_vec(pod, names)
        self.nonzero_request = nonzero_request_vec(self.request)
        self.ports: list[tuple[str, str, int]] = []
        for c in pod.spec.containers:
            for p in c.ports:
                if p.host_port > 0:
                    self.ports.append((p.host_ip or "0.0.0.0", p.protocol, p.host_port))
        from ..api.storage import pod_claim_names

        self.pvc_keys = [
            f"{pod.meta.namespace}/{name}" for name in pod_claim_names(pod)
        ]
        aff = pod.spec.affinity
        ns = pod.meta.namespace
        self.required_affinity_terms = (
            [AffinityTerm(t, ns) for t in aff.pod_affinity.required]
            if aff and aff.pod_affinity
            else []
        )
        self.required_anti_affinity_terms = (
            [AffinityTerm(t, ns) for t in aff.pod_anti_affinity.required]
            if aff and aff.pod_anti_affinity
            else []
        )
        self.preferred_affinity_terms = (
            [(w.weight, AffinityTerm(w.term, ns)) for w in aff.pod_affinity.preferred]
            if aff and aff.pod_affinity
            else []
        )
        self.preferred_anti_affinity_terms = (
            [(w.weight, AffinityTerm(w.term, ns)) for w in aff.pod_anti_affinity.preferred]
            if aff and aff.pod_anti_affinity
            else []
        )

    @property
    def key(self) -> str:
        return self.pod.meta.key

    @property
    def has_affinity_constraints(self) -> bool:
        return bool(self.required_affinity_terms or self.preferred_affinity_terms or
                    self.required_anti_affinity_terms or self.preferred_anti_affinity_terms)

    @property
    def has_required_anti_affinity(self) -> bool:
        return bool(self.required_anti_affinity_terms)


class NodeInfo:
    """Aggregated node state; all vectors in plane units."""

    __slots__ = (
        "node",
        "pods",
        "pods_with_affinity",
        "pods_with_required_anti_affinity",
        "requested",
        "nonzero_requested",
        "allocatable",
        "used_ports",
        "image_sizes",
        "pvc_ref_counts",
        "generation",
        "names",
    )

    def __init__(self, names: ResourceNames, node: Node | None = None):
        self.names = names
        self.node: Node | None = None
        self.pods: dict[str, PodInfo] = {}
        self.pods_with_affinity: list[PodInfo] = []
        self.pods_with_required_anti_affinity: list[PodInfo] = []
        self.requested = ResourceVec(names.width)
        self.nonzero_requested = ResourceVec(names.width)
        self.allocatable = ResourceVec(names.width)
        self.used_ports: dict[tuple[str, str, int], int] = {}
        self.image_sizes: dict[str, int] = {}
        self.pvc_ref_counts: dict[str, int] = {}
        self.generation = 0
        if node is not None:
            self.set_node(node)

    # -- node --------------------------------------------------------------

    def set_node(self, node: Node) -> None:
        self.node = node
        self.allocatable = ResourceVec.from_map(
            node.status.allocatable, self.names, floor=True
        )
        self.image_sizes = {
            name: img.size_bytes for img in node.status.images for name in img.names
        }
        self.generation = next_generation()

    @property
    def name(self) -> str:
        return self.node.meta.name if self.node else ""

    # -- pods --------------------------------------------------------------

    def add_pod(self, pi: PodInfo) -> None:
        self.pods[pi.key] = pi
        self.requested.add(pi.request)
        self.nonzero_requested.add(pi.nonzero_request)
        for port in pi.ports:
            self.used_ports[port] = self.used_ports.get(port, 0) + 1
        for k in pi.pvc_keys:
            self.pvc_ref_counts[k] = self.pvc_ref_counts.get(k, 0) + 1
        if pi.has_affinity_constraints:
            self.pods_with_affinity.append(pi)
        if pi.has_required_anti_affinity:
            self.pods_with_required_anti_affinity.append(pi)
        self.generation = next_generation()

    def remove_pod(self, key: str) -> PodInfo | None:
        pi = self.pods.pop(key, None)
        if pi is None:
            return None
        self.requested.sub(pi.request)
        self.nonzero_requested.sub(pi.nonzero_request)
        for port in pi.ports:
            n = self.used_ports.get(port, 0) - 1
            if n <= 0:
                self.used_ports.pop(port, None)
            else:
                self.used_ports[port] = n
        for k in pi.pvc_keys:
            n = self.pvc_ref_counts.get(k, 0) - 1
            if n <= 0:
                self.pvc_ref_counts.pop(k, None)
            else:
                self.pvc_ref_counts[k] = n
        self.pods_with_affinity = [p for p in self.pods_with_affinity if p.key != key]
        self.pods_with_required_anti_affinity = [
            p for p in self.pods_with_required_anti_affinity if p.key != key
        ]
        self.generation = next_generation()
        return pi

    def clone(self) -> "NodeInfo":
        ni = NodeInfo(self.names)
        ni.node = self.node
        ni.pods = dict(self.pods)
        ni.pods_with_affinity = list(self.pods_with_affinity)
        ni.pods_with_required_anti_affinity = list(self.pods_with_required_anti_affinity)
        ni.requested = self.requested.clone()
        ni.nonzero_requested = self.nonzero_requested.clone()
        ni.allocatable = self.allocatable.clone()
        ni.used_ports = dict(self.used_ports)
        ni.image_sizes = dict(self.image_sizes)
        ni.pvc_ref_counts = dict(self.pvc_ref_counts)
        ni.generation = self.generation
        return ni

    def iter_pods(self) -> Iterable[PodInfo]:
        return self.pods.values()

    def __repr__(self) -> str:
        return f"NodeInfo({self.name}, pods={len(self.pods)}, gen={self.generation})"
