"""Device-mesh sharding for the dense scheduling kernels (ICI data plane).

The reference scales the two hot loops with 16 goroutines on one host
(pkg/scheduler/framework/parallelize/parallelism.go) and scales the cluster
with sampling (percentageOfNodesToScore, schedule_one.go:862-888). The TPU
rebuild instead shards the *nodes axis* of every plane across a
`jax.sharding.Mesh` — v5e-8 style, collectives riding ICI — and lets GSPMD
insert the cross-chip reductions:

- per-domain segment-sums (PodTopologySpread) become scatter-add + psum,
- normalize passes (max/min over the feasible set) become all-reduces,
- the final winner selection is a per-shard argmax + allgather.

A second optional mesh axis, "wave", data-parallelizes independent pod
evaluations: `wave_fit_and_score` computes the full pods×nodes
feasibility-and-score matrix (the BASELINE.json north-star kernel) with pods
sharded over "wave" and nodes over "nodes". The sequential-greedy
`batched_assign` scan (pod i+1 sees pod i's assumes) keeps pods on the scan
axis — that dependency chain is inherently sequential — with all its per-step
node math sharded.

No NCCL/MPI translation anywhere: sharding annotations + jit are the whole
communication backend (SURVEY.md §2.9, §5.8).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

# jax >= 0.5 promotes shard_map to jax.shard_map (replication check renamed
# check_vma); 0.4.x only has the experimental entry point with check_rep
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SHARD_MAP_CHECK_KW = "check_vma"
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    _SHARD_MAP_CHECK_KW = "check_rep"

from ..ops.kernels import (
    ZERO_TIE_WORDS,
    AxisComm,
    KernelConfig,
    _batched_assign_core,
    _fit_and_score_jit,
    batched_assign,
    dedup_fast_capable,
    filter_masks,
    fit_and_score,
    scores,
)

NODE_AXIS = "nodes"
WAVE_AXIS = "wave"

# which dim of each kernel-input array is the nodes axis (None = replicated)
_NODE_DIM = {
    "alloc": 0, "used": 0, "nonzero_used": 0, "valid": 0, "unsched": 0,
    "group_id": 0, "taints": 0, "prefer_taints": 0, "domain": 0,
    "sel_counts": 0, "port_words": 0, "image_kib": 0,
    "ipa_counts": 0, "ipa_anti": 0, "ipa_pref": 0,
    # global term → topology-key table replicates
    "ipa_term_key": None,
    # affinity signature tables: [A, G] rows replicate, [A, Nb] shards dim 1
    "aff_match": None, "aff_pref": None, "aff_has_pref": None,
    "aff_allow": 1,
}


def scheduler_mesh(n_devices: int | None = None, wave: int = 1, devices=None) -> Mesh:
    """A (wave, nodes) mesh over the first n_devices available devices.

    wave=1 dedicates the whole slice to the nodes axis (max single-pod
    latency); wave>1 trades node-shard width for pod-wave data parallelism.
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"scheduler_mesh wants {n_devices} devices but only "
                f"{len(devs)} are visible ({devs[0].platform if devs else 'none'}); "
                "provision a virtual CPU mesh first "
                "(XLA_FLAGS=--xla_force_host_platform_device_count=N "
                "JAX_PLATFORMS=cpu before jax init, or "
                "__graft_entry__._ensure_devices)"
            )
        devs = devs[:n_devices]
    n = len(devs)
    if n == 0:
        raise ValueError("no devices for mesh")
    if n % wave:
        raise ValueError(f"wave={wave} does not divide device count {n}")
    return Mesh(np.asarray(devs).reshape(wave, n // wave), (WAVE_AXIS, NODE_AXIS))


def shard_planes(mesh: Mesh, planes_dict: dict) -> dict:
    """Put every plane on the mesh with its node axis (dim 0) sharded.

    Plane buckets are powers of two ≥ 8 (ops/vocab.py next_pow2), so any
    power-of-two node-shard count ≤ 8 divides evenly; reject the rest loudly
    rather than letting GSPMD silently replicate.
    """
    shards = mesh.shape[NODE_AXIS]
    out = {}
    for k, a in planes_dict.items():
        a = np.asarray(a)
        if k not in _NODE_DIM:
            raise ValueError(
                f"unknown kernel input {k!r}: add it to _NODE_DIM so its "
                "node axis (or replication) is explicit"
            )
        dim = _NODE_DIM[k]
        if dim is None:
            spec = P()
        else:
            if a.shape[dim] % shards:
                raise ValueError(
                    f"plane {k!r} node bucket {a.shape[dim]} not divisible "
                    f"by {shards} node shards"
                )
            spec = P(*([None] * dim + [NODE_AXIS]))
        out[k] = jax.device_put(a, NamedSharding(mesh, spec))
    return out


def replicate(mesh: Mesh, tree):
    """Replicate pod features (tiny) across the mesh."""
    sh = NamedSharding(mesh, P())
    return jax.tree.map(lambda a: jax.device_put(np.asarray(a), sh), tree)


# -- sharded kernel entry points --------------------------------------------
#
# The jitted kernels are shared with the single-chip path: computation
# follows data, so calling them on sharded planes partitions the whole
# program. Only the wave (pods×nodes matrix) kernel needs its own trace.


def sharded_fit_and_score(cfg: KernelConfig, mesh: Mesh, sharded_planes: dict, f: dict):
    """One pod against the node-sharded cluster (fused filter+score)."""
    return _fit_and_score_jit(cfg, sharded_planes, replicate(mesh, f))


# spec of the resident per-signature score-row table (sig_table): row
# columns shard with the nodes, the domain tables ride replicated —
# identical on the way out of one wave and back into the next chained one
_SIG_TABLE_SPEC = {"ew": P(None, NODE_AXIS), "ffit": P(None, NODE_AXIS),
                   "feas": P(None, NODE_AXIS), "segs": P(), "pcs": P()}


@functools.partial(jax.jit, static_argnums=(0, 1, 3, 6, 9))
def _sharded_assign_jit(cfg: KernelConfig, mesh: Mesh, planes: dict, layout,
                        packed_f, tie_words, dedup, sig_ids, uniq_idx,
                        xwave, cursor_init, frame_shift, carry_map,
                        sig_table):
    """Explicit shard_map over the nodes axis: every plane arrives
    shard-local, features/tie stream replicated, and the scan step's only
    cross-shard traffic is the scalar collectives AxisComm emits (per-shard
    tie counts + winner publication + normalization pmax/pmin) — NOT the
    full-vector reductions GSPMD inferred for the same program (which made
    the sharded scan a 6.7x pessimization in round 4).

    With dedup the signature-replay tier runs shard-safe: score-row columns
    stay shard-local while the replay predicate and domain-table deltas ride
    the same scalar/segment psums, so every shard takes the same cond
    branch. Cross-wave reuse (xwave) has full parity with the single-device
    path: the previous chained wave's sig_table hands back in with the same
    shard layout it came out with, and the tie cursor chains as a replicated
    device scalar (cursor_init - frame_shift inside the trace)."""
    n_shards = mesh.shape[NODE_AXIS]
    comm = AxisComm(NODE_AXIS, n_shards)

    def body(planes_l, packed_l, tie_l, sig_l, uniq_l, cur_l, fs_l,
             cmap_l, stab_l):
        return _batched_assign_core(
            cfg, planes_l, packed_l, layout, tie_l,
            cur_l, fs_l, comm,
            sig_ids=sig_l, uniq_idx=uniq_l, dedup=dedup,
            carry_map=cmap_l if xwave else None,
            sig_table=stab_l if xwave else None, xwave=xwave,
        )

    plane_specs = {}
    for k in planes:
        dim = _NODE_DIM.get(k)
        plane_specs[k] = (P() if dim is None
                          else P(*([None] * dim + [NODE_AXIS])))
    fast = dedup and dedup_fast_capable(cfg, comm)
    # outputs: winners/packed/tie scalars replicated; carry planes sharded;
    # resident score-row columns sharded like the planes, domain tables and
    # validity replicated (they're maintained via psum'd deltas)
    out_specs = (
        P(),
        {
            "used": P(NODE_AXIS), "nonzero_used": P(NODE_AXIS),
            "sel_counts": P(NODE_AXIS), "tie_consumed": P(),
            "tie_overflow": P(), "packed": P(),
            **({"sig_scores": P(None, NODE_AXIS),
                "sig_table": dict(_SIG_TABLE_SPEC)} if fast else {}),
            **({"ipa_counts": P(NODE_AXIS), "ipa_anti": P(NODE_AXIS),
                "ipa_pref": P(NODE_AXIS)} if cfg.ipa_active else {}),
        },
    )
    return _shard_map(
        body, mesh=mesh,
        in_specs=(plane_specs, P(), P(), P(), P(), P(), P(), P(),
                  dict(_SIG_TABLE_SPEC) if xwave else P()),
        out_specs=out_specs,
        **{_SHARD_MAP_CHECK_KW: False},
    )(planes, packed_f, tie_words, sig_ids, uniq_idx, cursor_init,
      frame_shift, carry_map, sig_table)


def sharded_batched_assign(cfg: KernelConfig, mesh: Mesh, sharded_planes: dict,
                           batched_f: dict, tie_words=None, cursor_init=0,
                           frame_shift=0, sig_ids=None, uniq_idx=None,
                           carry_map=None, sig_table=None):
    """Sequential-greedy wave over node-sharded planes (lax.scan on pods),
    decisions bit-identical to the single-device batched_assign. sig_ids /
    uniq_idx (see batched_assign) enable signature dedup with the same
    bit-compat contract; the replay tier applies whenever the config is
    dedup_fast_capable. cursor_init / frame_shift / carry_map / sig_table
    mirror batched_assign exactly: pipelined launches chain their tie
    cursor as a device scalar and hand the previous chained wave's resident
    score-row table back for cross-wave signature replay."""
    from ..ops.planes import pack_features

    if tie_words is None:
        tie_words = ZERO_TIE_WORDS
    packed, layout = pack_features(batched_f)
    dedup = sig_ids is not None and uniq_idx is not None
    xwave = bool(dedup and carry_map is not None and sig_table is not None)
    sig_r = (replicate(mesh, np.asarray(sig_ids, np.int32))
             if dedup else replicate(mesh, np.zeros(1, np.int32)))
    uniq_r = (replicate(mesh, np.asarray(uniq_idx, np.int32))
              if dedup else replicate(mesh, np.zeros(1, np.int32)))
    if isinstance(cursor_init, (int, np.integer)):
        # np.int32, not a weak python int: keeps the jit signature identical
        # between first launches and chained ones (see batched_assign)
        cursor_r = replicate(mesh, np.int32(cursor_init))
    else:
        cursor_r = cursor_init  # previous wave's tie_consumed, replicated
    fs_r = replicate(mesh, np.int32(frame_shift))
    cmap_r = (replicate(mesh, np.asarray(carry_map, np.int32))
              if xwave else replicate(mesh, np.zeros(1, np.int32)))
    stab = sig_table if xwave else replicate(mesh, np.zeros(1, np.int32))
    return _sharded_assign_jit(cfg, mesh, sharded_planes, layout,
                               replicate(mesh, packed),
                               replicate(mesh, tie_words),
                               dedup, sig_r, uniq_r, xwave, cursor_r,
                               fs_r, cmap_r, stab)


@functools.partial(jax.jit, static_argnums=0)
def _wave_fit_and_score_jit(cfg: KernelConfig, planes: dict, batched_f: dict):
    def one(f):
        _, feasible, _, _ = filter_masks(cfg, planes, f)
        total, _ = scores(cfg, planes, f, feasible)
        return feasible, jnp.where(feasible, total, -1)

    return jax.vmap(one)(batched_f)


def wave_fit_and_score(cfg: KernelConfig, mesh: Mesh, sharded_planes: dict,
                       batched_f: dict):
    """The pods×nodes matrix kernel: every pod scored against every node in
    one program, pods sharded over WAVE_AXIS, nodes over NODE_AXIS.

    Each pod's row is evaluated against the *same* snapshot (no assumes
    between pods) — this is the placement-enumeration / gang-scoring shape
    (schedule_one_podgroup.go:520), and the input to host-side winner
    assignment when decisions must not interact.

    Returns (feasible [P, Nb] bool, total [P, Nb] int32 with -1 infeasible).
    """
    wave = mesh.shape[WAVE_AXIS]
    sh = NamedSharding(mesh, P(WAVE_AXIS))
    bf = {}
    for k, a in batched_f.items():
        a = np.asarray(a)
        if a.shape[0] % wave:
            raise ValueError(
                f"pod batch {a.shape[0]} not divisible by wave={wave}; pad the batch"
            )
        bf[k] = jax.device_put(a, sh)
    return _wave_fit_and_score_jit(cfg, sharded_planes, bf)


# -- execution-context seam ---------------------------------------------------
#
# ONE seam serves 1 device or a sharded mesh (SNIPPETS [2]'s
# pjit-with-cpu-fallback shape): the backend holds a context and routes
# every plane placement and kernel entry through it. LocalContext is the
# fallback — plain device_put + the single-device jitted kernels, byte-for-
# byte what the backend did before the seam existed — and MeshContext is
# the NamedSharding path over a (wave, nodes) mesh. Decisions are
# bit-identical across contexts (golden-tested); only placement and the
# collective plumbing differ.


class LocalContext:
    """Single-device execution context: the cpu/1-chip fallback of the seam.

    `put` ignores the plane name (everything lives on the one default
    device) and the kernel entries are exactly ops.kernels' jitted
    functions, so a backend holding a LocalContext is bit- and
    compile-cache-identical to one predating the seam."""

    mesh = None
    n_shards = 1
    is_sharded = False

    def put(self, value, name=None):
        del name
        return jax.device_put(value)

    # delta-scatter rows/indices are not node-shaped; on one device the
    # distinction is moot but the seam keeps both entry points so sharded
    # call sites read the same either way
    put_replicated = put

    def fit_and_score(self, cfg: KernelConfig, planes: dict, f: dict):
        return fit_and_score(cfg, planes, f)

    def batched_assign(self, cfg: KernelConfig, planes: dict, batched_f,
                       tie_words=None, cursor_init=0, frame_shift=0,
                       sig_ids=None, uniq_idx=None, carry_map=None,
                       sig_table=None):
        return batched_assign(cfg, planes, batched_f, tie_words,
                              cursor_init, frame_shift, sig_ids=sig_ids,
                              uniq_idx=uniq_idx, carry_map=carry_map,
                              sig_table=sig_table)


class MeshContext:
    """Node-sharded execution context over a scheduler_mesh.

    `put` consults _NODE_DIM so every plane lands with its node axis
    sharded (NamedSharding) and globals replicated; the kernel entries are
    the explicit shard_map programs above. One backend holds ONE context
    for its lifetime — resident state (base mirror, carry overlay,
    sig_table) all shares the mesh, so handles chain between waves without
    resharding."""

    is_sharded = True

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.n_shards = int(mesh.shape[NODE_AXIS])

    def put(self, value, name=None):
        a = np.asarray(value)
        dim = _NODE_DIM.get(name)
        if name not in _NODE_DIM or dim is None:
            spec = P()
        else:
            if a.shape[dim] % self.n_shards:
                raise ValueError(
                    f"plane {name!r} node bucket {a.shape[dim]} not "
                    f"divisible by {self.n_shards} node shards"
                )
            spec = P(*([None] * dim + [NODE_AXIS]))
        return jax.device_put(a, NamedSharding(self.mesh, spec))

    def put_replicated(self, value, name=None):
        del name
        return jax.device_put(np.asarray(value),
                              NamedSharding(self.mesh, P()))

    def fit_and_score(self, cfg: KernelConfig, planes: dict, f: dict):
        return sharded_fit_and_score(cfg, self.mesh, planes, f)

    def batched_assign(self, cfg: KernelConfig, planes: dict, batched_f,
                       tie_words=None, cursor_init=0, frame_shift=0,
                       sig_ids=None, uniq_idx=None, carry_map=None,
                       sig_table=None):
        return sharded_batched_assign(cfg, self.mesh, planes, batched_f,
                                      tie_words, cursor_init, frame_shift,
                                      sig_ids=sig_ids, uniq_idx=uniq_idx,
                                      carry_map=carry_map,
                                      sig_table=sig_table)


def context_from_env(environ=None):
    """The deployment seam: KUBE_TPU_MESH_DEVICES=N asks for an N-way
    node-sharded MeshContext; unset, 1, or more shards than visible
    devices falls back to LocalContext (the cpu fallback — on a laptop or
    a single-chip test box the same code path runs unsharded). On a CPU
    box a virtual multi-device mesh comes from __graft_entry__'s
    jax_num_cpu_devices guard (`_ensure_devices(N)`) before jax init."""
    import os

    env = environ if environ is not None else os.environ
    raw = env.get("KUBE_TPU_MESH_DEVICES", "").strip()
    if not raw:
        return LocalContext()
    try:
        n = int(raw)
    except ValueError:
        return LocalContext()
    if n <= 1 or n > len(jax.devices()):
        return LocalContext()
    return MeshContext(scheduler_mesh(n))
