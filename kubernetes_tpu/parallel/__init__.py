"""Parallelism layer: device-mesh sharding of the scheduling kernels.

The TPU-native replacement for the reference's five parallelism mechanisms
(SURVEY.md §2.9): intra-cycle node fan-out → node-axis sharding over ICI;
batch reuse → the batched/wave kernels; the rest (binding pipeline, async
API, multi-profile) stay host-side in kubernetes_tpu.scheduler.
"""

from .mesh import (
    NODE_AXIS,
    WAVE_AXIS,
    LocalContext,
    MeshContext,
    context_from_env,
    replicate,
    scheduler_mesh,
    shard_planes,
    sharded_batched_assign,
    sharded_fit_and_score,
    wave_fit_and_score,
)

__all__ = [
    "NODE_AXIS", "WAVE_AXIS", "LocalContext", "MeshContext",
    "context_from_env", "replicate", "scheduler_mesh", "shard_planes",
    "sharded_batched_assign", "sharded_fit_and_score", "wave_fit_and_score",
]
