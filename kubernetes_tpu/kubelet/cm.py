"""Container manager: QoS classes, cgroup tree model, node-allocatable
admission.

Reference: pkg/kubelet/cm/ — the kubelet's resource-enforcement layer.
Modeled here: QoS classification (pkg/apis/core/v1/helper/qos GetPodQOS),
the kubepods cgroup hierarchy (qos_container_manager.go: Guaranteed pods sit
directly under kubepods, Burstable/BestEffort under their QoS parents), and
the node-allocatable admission predicate (pkg/kubelet/lifecycle/predicate.go:
a pod whose requests exceed what is left of allocatable is rejected with
OutOf<resource> BEFORE any container starts — the kubelet's last line of
defense when a race beats the scheduler's view).
"""

from __future__ import annotations

from ..api.resource import CPU, MEM, ResourceNames, ResourceVec

GUARANTEED = "Guaranteed"
BURSTABLE = "Burstable"
BEST_EFFORT = "BestEffort"


def pod_qos(pod) -> str:
    """GetPodQOS: Guaranteed iff every container has cpu+mem limits equal
    to its requests; BestEffort iff nothing sets requests or limits;
    Burstable otherwise."""
    containers = list(pod.spec.init_containers) + list(pod.spec.containers)
    any_set = False
    guaranteed = bool(containers)
    for c in containers:
        req = {k: v for k, v in c.requests.items() if k in ("cpu", "memory")}
        lim = {k: v for k, v in c.limits.items() if k in ("cpu", "memory")}
        if req or lim:
            any_set = True
        if not (set(lim) == {"cpu", "memory"}
                and all(req.get(k, lim[k]) == lim[k] for k in lim)):
            guaranteed = False
    if guaranteed and any_set:
        return GUARANTEED
    if any_set:
        return BURSTABLE
    return BEST_EFFORT


class ContainerManager:
    """Tracks admitted pods' reservations against node allocatable and
    models their cgroup placement."""

    def __init__(self, node, names: ResourceNames | None = None):
        self.names = names or ResourceNames()
        self.allocatable = ResourceVec.from_map(
            node.status.allocatable, self.names, floor=True
        )
        self._reserved: dict[str, ResourceVec] = {}  # pod key -> requests
        self._qos: dict[str, str] = {}

    def _pod_requests(self, pod) -> ResourceVec:
        from ..api.resource import pod_request_vec

        return pod_request_vec(pod, self.names)

    def admit(self, pod) -> tuple[bool, str, str]:
        """(ok, reason, message) — the allocatable admission predicate.
        Idempotent per pod key (re-syncs re-admit freely)."""
        key = pod.meta.key
        if key in self._reserved:
            return True, "", ""
        req = self._pod_requests(pod)
        used = ResourceVec(self.names.width)
        for r in self._reserved.values():
            used.add(r)
        width = max(len(req.v), len(self.allocatable.v))
        for i in range(width):
            if req[i] > 0 and req[i] > self.allocatable[i] - used[i]:
                rname = (self.names.names[i] if i < self.names.width
                         else f"res{i}")
                reason = "OutOf" + ("cpu" if i == CPU else
                                    "memory" if i == MEM else rname)
                return False, reason, (
                    f"Node didn't have enough resource: {rname}, "
                    f"requested: {req[i]}, used: {used[i]}, "
                    f"capacity: {self.allocatable[i]}"
                )
        self._reserved[key] = req
        self._qos[key] = pod_qos(pod)
        return True, "", ""

    def release(self, pod_key: str) -> None:
        self._reserved.pop(pod_key, None)
        self._qos.pop(pod_key, None)

    def cgroup_path(self, pod) -> str:
        """qos_container_manager.go hierarchy: Guaranteed pods live
        directly under kubepods; the other classes under their QoS
        parent."""
        qos = self._qos.get(pod.meta.key) or pod_qos(pod)
        slug = (pod.meta.uid or pod.meta.key).replace("/", "_")
        if qos == GUARANTEED:
            return f"/kubepods/pod{slug}"
        return f"/kubepods/{qos.lower()}/pod{slug}"

    def reserved_total(self) -> ResourceVec:
        total = ResourceVec(self.names.width)
        for r in self._reserved.values():
            total.add(r)
        return total
