"""PLEG: pod lifecycle event generator.

Reference: pkg/kubelet/pleg/generic.go — the kubelet doesn't poll every pod
every loop; a single relist() compares the runtime's current container
states against the previous relist and emits per-pod lifecycle events
(ContainerStarted/ContainerDied/ContainerRemoved) into the channel the sync
loop selects on (syncLoopIteration's plegCh). Only pods with events get
synced, which is what keeps a 100-pod node's sync loop cheap.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from ..utils import faultinject
from .cri import CONTAINER_RUNNING, EXITED, RuntimeService

CONTAINER_STARTED = "ContainerStarted"
CONTAINER_DIED = "ContainerDied"
CONTAINER_REMOVED = "ContainerRemoved"


@dataclass(frozen=True)
class PodLifecycleEvent:
    pod_key: str
    type: str
    container_id: str


class GenericPLEG:
    def __init__(self, runtime: RuntimeService):
        self.runtime = runtime
        # container id → (pod_key, state) as of the last relist
        self._last: dict[str, tuple[str, str]] = {}
        self.events: deque[PodLifecycleEvent] = deque()

    def relist(self) -> int:
        """One relist pass; queues events for every observed transition.
        Returns the number of events generated."""
        # chaos: a stalled relist. Safe to skip wholesale — the diff is
        # against `_last`, which this leaves untouched, so the missed
        # transitions are emitted by the next healthy relist (the PLEG is
        # level-triggered, not edge-triggered)
        if faultinject.fire("kubelet.pleg"):
            return 0
        sandboxes = {s.id: s.pod_key for s in self.runtime.list_pod_sandboxes()}
        current: dict[str, tuple[str, str]] = {}
        for c in self.runtime.list_containers():
            pod_key = sandboxes.get(c.sandbox_id, "")
            current[c.id] = (pod_key, c.state)
        n = 0
        for cid, (pod_key, state) in current.items():
            old = self._last.get(cid)
            if old is None:
                if state == CONTAINER_RUNNING:
                    self.events.append(
                        PodLifecycleEvent(pod_key, CONTAINER_STARTED, cid)
                    )
                    n += 1
                elif state == EXITED:
                    # created-and-died between relists
                    self.events.append(
                        PodLifecycleEvent(pod_key, CONTAINER_DIED, cid)
                    )
                    n += 1
            elif old[1] != state:
                if state == CONTAINER_RUNNING:
                    self.events.append(
                        PodLifecycleEvent(pod_key, CONTAINER_STARTED, cid)
                    )
                    n += 1
                elif state == EXITED:
                    self.events.append(
                        PodLifecycleEvent(pod_key, CONTAINER_DIED, cid)
                    )
                    n += 1
        for cid, (pod_key, _state) in self._last.items():
            if cid not in current:
                self.events.append(
                    PodLifecycleEvent(pod_key, CONTAINER_REMOVED, cid)
                )
                n += 1
        self._last = current
        return n

    def drain(self) -> list[PodLifecycleEvent]:
        out = list(self.events)
        self.events.clear()
        return out
