"""Hollow kubelet: a node agent with a fake container runtime.

Reference: pkg/kubelet (Run:1833, syncLoop:2602, SyncPod:2002, node lease
heartbeat kubelet.go:1122-1128) in its kubemark form
(pkg/kubemark/hollow_kubelet.go:62 — real kubelet logic, fake CRI). The sync
loop here is the same shape: watch pods assigned to this node, drive them
through a fake runtime (Pending -> Running -> Succeeded), report NodeStatus,
heartbeat a Lease, and finalize deletions.
"""

from __future__ import annotations

import threading

from ..api.types import (
    Node,
    PENDING,
    RUNNING,
    SUCCEEDED,
    PodCondition,
)
from ..store.store import ConflictError, NotFoundError
from ..utils import faultinject
from .agent import NodeAgentBase


class FakeRuntime:
    """The kubemark fake CRI: containers 'run' instantly; a spec'd run
    duration lets Jobs complete."""

    def __init__(self, clock):
        self.clock = clock
        self.containers: dict[str, float] = {}  # pod key -> start time

    def start_pod(self, pod) -> None:
        self.containers[pod.meta.key] = self.clock.now()

    def pod_finished(self, pod) -> bool:
        """Pods annotated with a run duration complete; service pods don't."""
        duration = pod.meta.annotations.get("kubemark.io/run-seconds")
        if duration is None:
            return False
        start = self.containers.get(pod.meta.key)
        return start is not None and self.clock.now() - start >= float(duration)

    def kill_pod(self, key: str) -> None:
        self.containers.pop(key, None)


class HollowKubelet(NodeAgentBase):
    """One hollow node agent (cmd/kubemark hollow-node)."""

    def __init__(self, store, node: Node, clock=None,
                 lease_duration: float = 40.0):
        from ..utils.clock import Clock

        self.store = store
        self.node = node
        self.node_name = node.meta.name
        self.clock = clock or Clock()
        self.lease_duration = lease_duration
        self.runtime = FakeRuntime(self.clock)
        self._watch = None

    # registration + heartbeat come from NodeAgentBase

    def register(self) -> None:
        super().register()
        # from the CURRENT revision: the watch is only drained as a wakeup
        # signal (state is re-listed each sync), and a node started mid-run
        # must not demand compacted history (watch(0) raises CompactedError
        # once >log_cap Pod events have ever happened)
        _, rev = self.store.list("Pod")
        self._watch = self.store.watch("Pod", from_revision=rev)

    # -- pod sync loop -------------------------------------------------------

    def _my_pods(self):
        return [p for p in self.store.pods() if p.spec.node_name == self.node_name]

    def sync_once(self) -> int:
        """One syncLoopIteration: converge every assigned pod; returns the
        number of pods whose status changed."""
        # chaos: a dead/hung kubelet (see Kubelet.sync_loop_iteration) —
        # skipping the iteration skips the heartbeat too, so the node's
        # lease goes stale and the lifecycle controller reacts
        if faultinject.fire("kubelet.sync"):
            return 0
        self.heartbeat()
        if self._watch is not None:
            self._watch.drain()  # consume; state is re-listed below
        changed = 0
        seen = set()
        for pod in self._my_pods():
            seen.add(pod.meta.key)
            if pod.is_terminating:
                # finalize: the runtime stops containers, then the API object
                # goes away (kubelet's graceful deletion handshake)
                self.runtime.kill_pod(pod.meta.key)
                self.store.try_delete("Pod", pod.meta.key)
                changed += 1
                continue
            if pod.status.phase == PENDING:
                self.runtime.start_pod(pod)
                pod.status.phase = RUNNING
                pod.status.start_time = self.clock.now()
                if not pod.status.pod_ip:
                    # sandbox networking: stable per-pod address (crc32 of
                    # uid — same scheme the endpointslice controller falls
                    # back to for pods that never report one)
                    from ..utils.net import stable_pod_ip

                    pod.status.pod_ip = stable_pod_ip(
                        pod.meta.uid or pod.meta.key
                    )
                ready = PodCondition(type="Ready", status="True")
                pod.status.conditions = [
                    c for c in pod.status.conditions if c.type != "Ready"
                ] + [ready]
                self._update_status(pod)
                changed += 1
            elif pod.status.phase == RUNNING and self.runtime.pod_finished(pod):
                pod.status.phase = (
                    SUCCEEDED if pod.spec.restart_policy != "Always" else RUNNING
                )
                if pod.status.phase == SUCCEEDED:
                    self.runtime.kill_pod(pod.meta.key)
                    self._update_status(pod)
                    changed += 1
        # reap runtime state for pods that vanished without deletion_timestamp
        for key in list(self.runtime.containers):
            if key not in seen:
                self.runtime.kill_pod(key)
        return changed

    def _update_status(self, pod) -> None:
        try:
            self.store.update(pod, check_version=False)
        except (ConflictError, NotFoundError):
            pass

    def run(self, stop_event: threading.Event, sync_period: float = 0.05) -> threading.Thread:
        def loop():
            while not stop_event.is_set():
                self.sync_once()
                stop_event.wait(sync_period)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        return t


def start_hollow_nodes(store, n: int, clock=None, cpu: str = "32",
                       mem: str = "64Gi", zones: int = 8) -> list[HollowKubelet]:
    """kubemark cluster bootstrap: n hollow nodes registered and synced."""
    from ..testing.wrappers import make_node

    kubelets = []
    for i in range(n):
        node = make_node(f"hollow-node-{i}", cpu=cpu, mem=mem,
                         zone=f"zone-{i % zones}")
        k = HollowKubelet(store, node, clock=clock)
        k.register()
        kubelets.append(k)
    return kubelets
