"""Node agent layer.

Two forms, as in the reference: the full-shaped agent (kubelet.Kubelet —
CRI runtime boundary, PLEG, eviction manager, per-pod workers; pkg/kubelet)
and the kubemark hollow form (HollowKubelet — fake runtime, batch sync;
pkg/kubemark) used for scale simulation.
"""

from .cri import InMemoryRuntime
from .eviction import EvictionManager, PodStats, Threshold
from .hollow import FakeRuntime, HollowKubelet, start_hollow_nodes
from .kubelet import Kubelet
from .pleg import GenericPLEG, PodLifecycleEvent
from .pod_workers import PodWorkers

__all__ = [
    "FakeRuntime", "HollowKubelet", "start_hollow_nodes",
    "Kubelet", "InMemoryRuntime", "GenericPLEG", "PodLifecycleEvent",
    "PodWorkers", "EvictionManager", "PodStats", "Threshold",
]
