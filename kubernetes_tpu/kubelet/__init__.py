"""Node agent layer (pkg/kubelet in its kubemark hollow form)."""

from .hollow import FakeRuntime, HollowKubelet, start_hollow_nodes

__all__ = ["FakeRuntime", "HollowKubelet", "start_hollow_nodes"]
