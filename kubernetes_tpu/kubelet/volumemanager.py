"""Volume manager: attach/mount bookkeeping for claim-backed volumes.

Reference: pkg/kubelet/volumemanager/ — the kubelet reconciles a desired
state (every claim-backed volume of every admitted pod) against an actual
state (attached volumes, per-pod mounts), and containers may not start
until every volume is mounted (kubelet's WaitForAttachAndMount; pods sit
in ContainerCreating with an "unmounted volumes" message until then).

In this in-memory runtime model "attach" and "mount" are bookkeeping
transitions, but the CONTRACT is real: an unbound or missing claim blocks
the pod's containers, claims resolve through the PV they are bound to, and
teardown unmounts (and detaches when the last pod using the volume goes)."""

from __future__ import annotations


class VolumeManager:
    def __init__(self, store, node_name: str = ""):
        self.store = store
        self.node_name = node_name
        self.attached: set[str] = set()  # PV names attached to this node
        self.mounts: dict[str, set[str]] = {}  # pod key -> mounted PV names

    def _attach_blocked(self, pv) -> str:
        """CSI volumes wait on the attach-detach controller's
        VolumeAttachment reaching attached=True before mount (the attach
        half of WaitForAttachAndMount; reference: volumemanager waits on
        the actual_state_of_world the attacher populates). In-tree volumes
        ('' csi_driver) attach implicitly."""
        if not pv.spec.csi_driver or not self.node_name:
            return ""
        from ..api.storage import VolumeAttachment

        name = VolumeAttachment.expected_name(pv.meta.name, self.node_name)
        va = self.store.try_get("VolumeAttachment", name)
        if va is None:
            return (f'volume "{pv.meta.name}" is not attached to node '
                    f'"{self.node_name}" (no VolumeAttachment)')
        if not va.status.get("attached"):
            return (f'volume "{pv.meta.name}" attachment is pending'
                    + (f': {va.status.get("attach_error")}'
                       if va.status.get("attach_error") else ""))
        return ""

    def mount_pod(self, pod) -> tuple[bool, str]:
        """WaitForAttachAndMount: resolve every claim-backed volume to its
        bound PV and mount it; (False, why) leaves the pod blocked in
        ContainerCreating."""
        from ..api.storage import CLAIM_BOUND

        if pod.meta.key in self.mounts:
            # already mounted: a Running pod keeps its volumes even if the
            # claim is later deleted/unbound (the real kubelet never
            # unmounts a live pod's volumes behind it); re-validation would
            # demote Running pods on every sync
            return True, ""
        wanted: list[str] = []
        for v in pod.spec.volumes:
            claim_name = v.claim_name(pod.meta.name)
            if not claim_name:
                continue  # hostPath / emptyDir need no attach
            key = f"{pod.meta.namespace}/{claim_name}"
            pvc = self.store.try_get("PersistentVolumeClaim", key)
            if pvc is None:
                return False, (
                    f'unmounted volumes=[{v.name}]: claim "{key}" not found'
                )
            if pvc.status.phase != CLAIM_BOUND or not pvc.spec.volume_name:
                return False, (
                    f'unmounted volumes=[{v.name}]: claim "{key}" is not '
                    "bound"
                )
            pv = self.store.try_get("PersistentVolume",
                                    pvc.spec.volume_name)
            if pv is None:
                return False, (
                    f'unmounted volumes=[{v.name}]: volume '
                    f'"{pvc.spec.volume_name}" not found'
                )
            blocked = self._attach_blocked(pv)
            if blocked:
                return False, f"unmounted volumes=[{v.name}]: {blocked}"
            wanted.append(pv.meta.name)
        for name in wanted:
            self.attached.add(name)
        self.mounts[pod.meta.key] = set(wanted)
        return True, ""

    def unmount_pod(self, pod_key: str) -> None:
        """Teardown: unmount this pod's volumes; detach a volume once its
        last mount is gone (attach_detach reconciler semantics)."""
        gone = self.mounts.pop(pod_key, set())
        still = set()
        for mounts in self.mounts.values():
            still |= mounts
        for name in gone - still:
            self.attached.discard(name)

    def volumes_in_use(self) -> list[str]:
        """NodeStatus.volumesInUse equivalent (sorted PV names)."""
        return sorted(self.attached)
