"""Eviction manager: node-pressure pod eviction.

Reference: pkg/kubelet/eviction/eviction_manager.go — the manager observes
node resource signals (memory.available, nodefs.available), compares them
against configured thresholds, sets the matching node condition
(MemoryPressure/DiskPressure), and evicts pods one per sync until the
signal clears. Victim ranking mirrors the reference's quality-of-service
ordering (helpers.go rankMemoryPressure): pods exceeding their requests
first, then by priority, then by usage — so a guaranteed high-priority pod
is the last thing a leaky neighbor can take down.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from ..api.types import Taint

MEMORY_AVAILABLE = "memory.available"
NODEFS_AVAILABLE = "nodefs.available"

_SIGNAL_CONDITION = {
    MEMORY_AVAILABLE: ("MemoryPressure", "node.kubernetes.io/memory-pressure"),
    NODEFS_AVAILABLE: ("DiskPressure", "node.kubernetes.io/disk-pressure"),
}


@dataclass(frozen=True)
class Threshold:
    signal: str  # MEMORY_AVAILABLE | NODEFS_AVAILABLE
    min_available: int  # evict when observed available < this


@dataclass
class PodStats:
    """Per-pod usage sample (the summary-API role): feeds both the
    eviction manager (memory/disk pressure) and the published PodMetrics
    objects the HPA consumes (cpu)."""

    memory_bytes: int = 0
    disk_bytes: int = 0
    cpu_milli: int = 0


class EvictionManager:
    """One node's eviction loop.

    stats_fn() returns (node available by signal, usage by pod key) — the
    summary-API role. evict_fn(pod, reason) performs the API eviction; the
    kubelet wires it to a status-Failed + delete write."""

    def __init__(self, thresholds: list[Threshold],
                 stats_fn: Callable[[], tuple[dict[str, int], dict[str, PodStats]]],
                 evict_fn: Callable[[object, str], None]):
        self.thresholds = thresholds
        self.stats_fn = stats_fn
        self.evict_fn = evict_fn
        self.pressure: set[str] = set()  # active condition types

    def synchronize(self, pods: list) -> list:
        """One manager sync (eviction_manager.go synchronize): returns the
        pods evicted this pass (at most one per pressured signal)."""
        available, usage = self.stats_fn()
        evicted = []
        self.pressure = set()
        for th in self.thresholds:
            cond, _taint = _SIGNAL_CONDITION[th.signal]
            obs = available.get(th.signal)
            if obs is None or obs >= th.min_available:
                continue
            self.pressure.add(cond)
            victims = self._rank(pods, usage, th.signal)
            if victims:
                pod = victims[0]
                self.evict_fn(pod, f"node had {cond}: {th.signal} "
                                   f"{obs} < {th.min_available}")
                evicted.append(pod)
        return evicted

    def node_conditions(self) -> set[str]:
        return set(self.pressure)

    def node_taints(self) -> list[Taint]:
        return [
            Taint(key=taint, value="", effect="NoSchedule")
            for cond, taint in _SIGNAL_CONDITION.values()
            if cond in self.pressure
        ]

    def _rank(self, pods: list, usage: dict[str, PodStats],
              signal: str) -> list:
        def pod_usage(p) -> int:
            st = usage.get(p.meta.key)
            if st is None:
                return 0
            return st.memory_bytes if signal == MEMORY_AVAILABLE else st.disk_bytes

        def pod_request(p) -> int:
            if signal != MEMORY_AVAILABLE:
                return 0
            total = 0
            for c in p.spec.containers:
                req = c.requests.get("memory")
                if req is not None:
                    from ..api.quantity import parse_quantity

                    total += int(parse_quantity(req))
            return total

        candidates = [p for p in pods if pod_usage(p) > 0]
        # (exceeds requests first) then (lowest priority) then (most usage)
        candidates.sort(key=lambda p: (
            0 if pod_usage(p) > pod_request(p) else 1,
            p.spec.priority,
            -pod_usage(p),
        ))
        return candidates
