"""Shared node-agent plumbing: registration + lease heartbeat.

Reference: both kubelet forms share one registration/heartbeat contract —
registerWithAPIServer (pkg/kubelet/kubelet_node_status.go) and the
fast-path Lease heartbeat (kubelet.go:1122-1128). Lease recreation on
heartbeat matters: the lease controller re-creates a deleted lease, and an
agent that only renews would be permanently NotReady after a lease GC.
"""

from __future__ import annotations

from ..api.coordination import Lease, LeaseSpec
from ..api.meta import ObjectMeta
from ..api.types import Node, NodeCondition
from ..store.store import ConflictError, NotFoundError
from ..utils import faultinject

LEASE_NAMESPACE = "kube-node-lease"


class NodeAgentBase:
    """Mixin: subclasses set store/node/node_name/clock/lease_duration."""

    lease_duration: float = 40.0

    def register(self) -> None:
        """Create/refresh the Node object with Ready=True + first lease."""
        existing = self.store.try_get("Node", self.node_name)
        ready = NodeCondition(type="Ready", status="True")
        self.node.status.conditions = [
            c for c in self.node.status.conditions if c.type != "Ready"
        ] + [ready]
        if existing is None:
            self.store.create(self.node)
        else:
            existing.status = self.node.status
            self.store.update(existing, check_version=False)
            self.node = existing
        self.heartbeat()

    def heartbeat(self) -> None:
        # chaos: a lost heartbeat — the node keeps running pods but its
        # lease goes stale, the exact asymmetry the lifecycle controller's
        # grace period exists for. DROP skips this renewal only; the next
        # heartbeat recreates/renews as usual (degrades ERROR to a skip —
        # a crashed heartbeat and a lost one look identical to the lease)
        try:
            if faultinject.fire("kubelet.lease"):
                return
        except faultinject.FaultInjected:
            return
        key = f"{LEASE_NAMESPACE}/{self.node_name}"
        now = self.clock.now()
        lease = self.store.try_get("Lease", key)
        if lease is None:
            try:
                self.store.create(Lease(
                    meta=ObjectMeta(name=self.node_name,
                                    namespace=LEASE_NAMESPACE),
                    spec=LeaseSpec(
                        holder_identity=self.node_name,
                        lease_duration_seconds=self.lease_duration,
                        acquire_time=now, renew_time=now,
                    ),
                ))
            except ConflictError:
                pass
            return
        lease.spec.renew_time = now
        try:
            self.store.update(lease, check_version=False)
        except (ConflictError, NotFoundError):
            pass
