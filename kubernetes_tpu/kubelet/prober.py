"""Probe manager: liveness + readiness worker state machines.

Reference: pkg/kubelet/prober (prober_manager.go + worker.go) — each
probed container gets a worker ticking at the probe period, counting
consecutive successes/failures against the thresholds; readiness results
feed the pod Ready condition (and thence EndpointSlices → proxy
backends), liveness failures kill the container so the restart policy
takes over. The probe ACTION is pluggable (`prober(pod, container) ->
bool`): real kubelets exec/http/tcp into the sandbox; the default prober
reports success while the container runs, and tests/simulations inject
outcomes (e.g. by pod annotation).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from ..api.types import Pod, Probe

LIVENESS = "liveness"
READINESS = "readiness"

# simulation hook: a pod annotated with this ("false") fails readiness;
# the default prober honors it so hollow clusters can flip readiness
READY_ANNOTATION = "probe.k8s.io/ready"
LIVE_ANNOTATION = "probe.k8s.io/live"


def default_prober(pod: Pod, container) -> dict[str, bool]:
    """{probe kind: success}. Honors the simulation annotations."""
    return {
        READINESS: pod.meta.annotations.get(READY_ANNOTATION, "true") != "false",
        LIVENESS: pod.meta.annotations.get(LIVE_ANNOTATION, "true") != "false",
    }


@dataclass
class _WorkerState:
    probe: Probe
    kind: str
    started_at: float
    last_probe: float | None = None
    successes: int = 0
    failures: int = 0
    # readiness starts False until the first success (worker.go initial
    # value), liveness starts True
    result: bool = field(default=False)


class ProbeManager:
    def __init__(self, clock, prober: Callable | None = None):
        self.clock = clock
        self.prober = prober or default_prober
        # (pod key, container name, kind) → worker state
        self._workers: dict[tuple[str, str, str], _WorkerState] = {}

    def sync_pod(self, pod: Pod, running_containers: set[str]) -> tuple[bool, list[str]]:
        """Tick every due probe for this pod.

        Returns (pod_ready, containers_to_kill): pod_ready ANDs the
        readiness results of probed running containers (unprobed
        containers are ready by definition); containers_to_kill lists
        containers whose liveness crossed the failure threshold."""
        now = self.clock.now()
        key = pod.meta.key
        ready = True
        kill: list[str] = []
        for c in pod.spec.containers:
            if c.name not in running_containers:
                # container died: drop its workers so a restarted container
                # starts FRESH (readiness False until first success, full
                # initial delay) instead of inheriting stale results — and
                # so a permanently-dead container stops showing up as "due"
                self._workers.pop((key, c.name, READINESS), None)
                self._workers.pop((key, c.name, LIVENESS), None)
                if c.readiness_probe is not None:
                    # a dead readiness-probed container gates the pod:
                    # nothing is serving behind that probe
                    ready = False
                continue
            for kind, probe in ((READINESS, c.readiness_probe),
                                (LIVENESS, c.liveness_probe)):
                if probe is None:
                    continue
                wk = (key, c.name, kind)
                st = self._workers.get(wk)
                if st is None:
                    st = _WorkerState(probe=probe, kind=kind, started_at=now,
                                      result=(kind == LIVENESS))
                    self._workers[wk] = st
                self._tick(st, pod, c, now)
                if kind == READINESS:
                    ready = ready and st.result
                elif not st.result:
                    kill.append(c.name)
                    # the container will restart: reset the worker so the
                    # replacement gets a fresh start (manager removes the
                    # worker when the container dies)
                    del self._workers[wk]
        return ready, kill

    def _tick(self, st: _WorkerState, pod: Pod, container, now: float) -> None:
        if now - st.started_at < st.probe.initial_delay_s:
            return
        if st.last_probe is not None and now - st.last_probe < st.probe.period_s:
            return
        st.last_probe = now
        ok = bool(self.prober(pod, container).get(st.kind, True))
        if ok:
            st.successes += 1
            st.failures = 0
            if st.successes >= st.probe.success_threshold:
                st.result = True
        else:
            st.failures += 1
            st.successes = 0
            if st.failures >= st.probe.failure_threshold:
                st.result = False

    def pods_due(self, now: float) -> set[str]:
        """Pod keys with at least one probe whose next tick is ≤ now — the
        sync loop re-dispatches these (probe workers are self-ticking
        goroutines in the reference; here the loop provides the ticks)."""
        out: set[str] = set()
        # snapshot: worker threads mutate the dict concurrently via
        # sync_pod/forget_pod (same pattern as _housekeeping's sandbox scan)
        for (key, _c, _kind), st in list(self._workers.items()):
            if st.last_probe is None:
                nxt = st.started_at + st.probe.initial_delay_s
            else:
                nxt = st.last_probe + st.probe.period_s
            if now >= nxt:
                out.add(key)
        return out

    def forget_pod(self, pod_key: str) -> None:
        for wk in [w for w in self._workers if w[0] == pod_key]:
            del self._workers[wk]
