"""CRI: the container-runtime boundary of the node agent.

Reference: the kubelet drives its runtime exclusively through the CRI gRPC
services (staging/src/k8s.io/cri-api RuntimeService/ImageService, client in
staging/src/k8s.io/cri-client); pkg/kubelet/kuberuntime translates pod specs
into sandbox + container calls against that boundary. This module defines
the same boundary as a Python protocol with the CRI state machines
(sandbox: READY/NOTREADY; container: CREATED→RUNNING→EXITED) and an
in-memory runtime implementing it — the seam where containerd/crun would
attach on a real node, and what kubemark fakes.
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Protocol

# sandbox states
SANDBOX_READY = "SANDBOX_READY"
SANDBOX_NOTREADY = "SANDBOX_NOTREADY"

# container states
CREATED = "CONTAINER_CREATED"
CONTAINER_RUNNING = "CONTAINER_RUNNING"
EXITED = "CONTAINER_EXITED"


@dataclass
class PodSandbox:
    id: str
    pod_key: str
    state: str = SANDBOX_READY
    ip: str = ""
    created_at: float = 0.0


@dataclass
class CRIContainer:
    id: str
    sandbox_id: str
    name: str
    image: str
    state: str = CREATED
    exit_code: int = 0
    started_at: float = 0.0
    finished_at: float = 0.0
    # fake-runtime knob: seconds after start when the container exits on
    # its own (None = runs until stopped), driving Job completion
    run_seconds: float | None = None
    # resolved environment handed over at create (CRI ContainerConfig.envs)
    env: dict = field(default_factory=dict)


@dataclass
class Image:
    ref: str
    size_bytes: int = 0


class RuntimeService(Protocol):
    """The RuntimeService RPC surface the kubelet consumes."""

    def run_pod_sandbox(self, pod_key: str, ip: str = "") -> str: ...
    def stop_pod_sandbox(self, sandbox_id: str) -> None: ...
    def remove_pod_sandbox(self, sandbox_id: str) -> None: ...
    def create_container(self, sandbox_id: str, name: str, image: str,
                         run_seconds: float | None = None) -> str: ...
    def start_container(self, container_id: str) -> None: ...
    def stop_container(self, container_id: str, timeout_s: float = 0) -> None: ...
    def remove_container(self, container_id: str) -> None: ...
    def list_pod_sandboxes(self) -> list[PodSandbox]: ...
    def list_containers(self) -> list[CRIContainer]: ...
    def container_status(self, container_id: str) -> CRIContainer: ...


class ImageService(Protocol):
    def pull_image(self, ref: str) -> str: ...
    def list_images(self) -> list[Image]: ...
    def remove_image(self, ref: str) -> None: ...


class InMemoryRuntime:
    """A CRI runtime with real state machines and no kernel underneath.

    Containers with run_seconds transition RUNNING→EXITED as the clock
    passes their deadline (observed lazily at list/status time — the same
    way a remote runtime's state is only as fresh as the last poll)."""

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._ids = itertools.count(1)
        self.sandboxes: dict[str, PodSandbox] = {}
        self.containers: dict[str, CRIContainer] = {}
        self.images: dict[str, Image] = {}
        # per-container log buffers (kubelet ReadLogs boundary: the real
        # runtime writes /var/log/pods/...; here lifecycle lines stand in
        # for process output, keyed by container id)
        self._logs: dict[str, list[str]] = {}

    # -- RuntimeService ------------------------------------------------------

    def run_pod_sandbox(self, pod_key: str, ip: str = "") -> str:
        sid = f"sb-{next(self._ids)}"
        self.sandboxes[sid] = PodSandbox(
            id=sid, pod_key=pod_key, ip=ip, created_at=self._clock()
        )
        return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        sb = self.sandboxes.get(sandbox_id)
        if sb is not None:
            sb.state = SANDBOX_NOTREADY
            for c in self.containers.values():
                if c.sandbox_id == sandbox_id and c.state == CONTAINER_RUNNING:
                    self._exit(c, code=137)

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        sb = self.sandboxes.get(sandbox_id)
        if sb is not None and sb.state == SANDBOX_READY:
            raise RuntimeError(f"sandbox {sandbox_id} not stopped")
        self.sandboxes.pop(sandbox_id, None)
        for cid in [c.id for c in self.containers.values()
                    if c.sandbox_id == sandbox_id]:
            self.containers.pop(cid, None)

    def create_container(self, sandbox_id: str, name: str, image: str,
                         run_seconds: float | None = None,
                         env: dict | None = None) -> str:
        if sandbox_id not in self.sandboxes:
            raise RuntimeError(f"no sandbox {sandbox_id}")
        cid = f"c-{next(self._ids)}"
        self.containers[cid] = CRIContainer(
            id=cid, sandbox_id=sandbox_id, name=name, image=image,
            run_seconds=run_seconds, env=dict(env or {}),
        )
        self._log(cid, f"created container {name} (image {image})")
        return cid

    def start_container(self, container_id: str) -> None:
        c = self.containers[container_id]
        if c.state != CREATED:
            raise RuntimeError(f"container {container_id} is {c.state}")
        c.state = CONTAINER_RUNNING
        c.started_at = self._clock()
        self._log(container_id, f"started container {c.name}")

    def stop_container(self, container_id: str, timeout_s: float = 0) -> None:
        c = self.containers.get(container_id)
        if c is not None and c.state == CONTAINER_RUNNING:
            self._exit(c, code=137)

    def remove_container(self, container_id: str) -> None:
        c = self.containers.get(container_id)
        if c is not None and c.state == CONTAINER_RUNNING:
            raise RuntimeError(f"container {container_id} still running")
        self.containers.pop(container_id, None)
        self._logs.pop(container_id, None)

    def read_logs(self, container_id: str, tail_lines: int | None = None
                  ) -> str:
        """CRI ReadLogs equivalent (the kubelet's /containerLogs source)."""
        self._tick()
        lines = self._logs.get(container_id, [])
        if tail_lines is not None:
            # kubectl --tail semantics: 0 prints nothing (lines[-0:] would
            # be everything); negatives are treated the same
            lines = lines[-tail_lines:] if tail_lines > 0 else []
        return "".join(lines)

    def list_pod_sandboxes(self) -> list[PodSandbox]:
        return list(self.sandboxes.values())

    def list_containers(self) -> list[CRIContainer]:
        self._tick()
        return list(self.containers.values())

    def container_status(self, container_id: str) -> CRIContainer:
        self._tick()
        return self.containers[container_id]

    # -- ImageService --------------------------------------------------------

    def pull_image(self, ref: str) -> str:
        self.images.setdefault(ref, Image(ref=ref, size_bytes=64 << 20))
        return ref

    def list_images(self) -> list[Image]:
        return list(self.images.values())

    def remove_image(self, ref: str) -> None:
        self.images.pop(ref, None)

    # -- internals -----------------------------------------------------------

    def _exit(self, c: CRIContainer, code: int) -> None:
        c.state = EXITED
        c.exit_code = code
        c.finished_at = self._clock()
        self._log(c.id, f"container {c.name} exited (code {code})")

    def _tick(self) -> None:
        now = self._clock()
        for c in self.containers.values():
            if (c.state == CONTAINER_RUNNING and c.run_seconds is not None
                    and now - c.started_at >= c.run_seconds):
                c.state = EXITED
                c.exit_code = 0
                c.finished_at = now
                self._log(c.id, f"container {c.name} exited (code 0)")

    def _log(self, container_id: str, line: str) -> None:
        self._logs.setdefault(container_id, []).append(
            f"{self._clock():.3f} {line}\n")
