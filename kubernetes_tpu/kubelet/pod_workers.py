"""Pod workers: per-pod serialized sync dispatch.

Reference: pkg/kubelet/pod_workers.go — every pod gets its own goroutine
processing that pod's sync requests strictly in order; new requests for a
pod already syncing coalesce into one pending request (the kubelet never
queues more than the latest state per pod). Here a fixed worker pool plays
the goroutine-per-pod role with the same two invariants: per-key
serialization and latest-wins coalescing.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable


class PodWorkers:
    def __init__(self, sync_fn: Callable[[str], None], workers: int = 4):
        self.sync_fn = sync_fn
        self._lock = threading.Lock()
        self._queue: deque[str] = deque()
        self._queued: set[str] = set()   # keys in _queue
        self._active: set[str] = set()   # keys being synced right now
        self._repeat: set[str] = set()   # re-request arrived mid-sync
        self._cv = threading.Condition(self._lock)
        self._stop = False
        self._threads = [
            threading.Thread(target=self._run, daemon=True)
            for _ in range(workers)
        ]
        for t in self._threads:
            t.start()

    def update_pod(self, key: str) -> None:
        """Request a sync for this pod (UpdatePod). Coalesces: a pod already
        queued stays queued once; a pod mid-sync gets exactly one follow-up."""
        with self._cv:
            if self._stop:
                return
            if key in self._active:
                self._repeat.add(key)
            elif key not in self._queued:
                self._queued.add(key)
                self._queue.append(key)
                self._cv.notify()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stop and not self._queue:
                    self._cv.wait()
                if self._stop:
                    return
                key = self._queue.popleft()
                self._queued.discard(key)
                self._active.add(key)
            try:
                self.sync_fn(key)
            except Exception:  # noqa: BLE001 - a pod's sync error is its own
                pass
            with self._cv:
                self._active.discard(key)
                if key in self._repeat:
                    self._repeat.discard(key)
                    self._queued.add(key)
                    self._queue.append(key)
                    self._cv.notify()

    def drain(self, timeout: float = 5.0) -> bool:
        """Test helper: wait until no work is queued or active."""
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._queue and not self._active and not self._repeat:
                    return True
            time.sleep(0.002)
        return False

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)
