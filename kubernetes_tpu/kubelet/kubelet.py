"""The node agent: sync loop over CRI + PLEG + eviction + pod workers.

Reference: pkg/kubelet/kubelet.go — Run:1833 starts the managers and enters
syncLoop:2602; syncLoopIteration:2677 selects over config changes (API pod
assignments), PLEG events, and housekeeping ticks, dispatching each affected
pod to its worker whose SyncPod:2002 converges the runtime (sandbox up,
containers created/started via CRI) and reports status. The HollowKubelet
(hollow.py) remains the kubemark form; this Kubelet is the full-shaped agent
that a real CRI runtime would slot into.
"""

from __future__ import annotations

from ..api.types import (
    FAILED,
    PENDING,
    RUNNING,
    SUCCEEDED,
    Node,
    NodeCondition,
    PodCondition,
)
from ..store.store import ConflictError, NotFoundError
from ..utils import faultinject
from .agent import NodeAgentBase
from .cri import CONTAINER_RUNNING, CREATED, EXITED, InMemoryRuntime
from .eviction import EvictionManager, PodStats, Threshold
from .pleg import GenericPLEG
from .pod_workers import PodWorkers


class Kubelet(NodeAgentBase):
    def __init__(self, store, node: Node, runtime=None, clock=None,
                 eviction_thresholds: list[Threshold] | None = None,
                 workers: int = 4, prober=None):
        from ..utils.clock import Clock
        from .prober import ProbeManager

        self.store = store
        self.node = node
        self.node_name = node.meta.name
        self.clock = clock or Clock()
        self.runtime = runtime or InMemoryRuntime(clock=self.clock.now)
        self.pleg = GenericPLEG(self.runtime)
        self.prober = ProbeManager(self.clock, prober=prober)
        self.workers = PodWorkers(self._sync_pod, workers=workers)
        self.eviction = EvictionManager(
            eviction_thresholds or [], self._stats, self._evict
        )
        # pod key → sandbox id (the runtime cache of kuberuntime manager)
        self._sandboxes: dict[str, str] = {}
        # configCh change detection: key → (resource_version, terminating)
        # as of the last dispatch — only changed pods are re-dispatched
        self._seen: dict[str, tuple[int, bool]] = {}
        # CrashLoopBackOff state (kuberuntime's backOff): (pod, container) →
        # (restart count, no-restart-before); pod key → earliest wakeup
        self._restart_backoff: dict[tuple[str, str], tuple[int, float]] = {}
        self._backoff_wakeup: dict[str, float] = {}
        # pods blocked on missing ConfigMap/Secret refs: retried each
        # housekeeping pass until the reference appears
        self._config_errors: set[str] = set()
        # activeDeadlineSeconds wakeups: pod key → fail-at time
        self._deadline_wakeup: dict[str, float] = {}
        # injected usage for tests / simulations (summary-API stand-in)
        self.pod_stats: dict[str, PodStats] = {}
        self.node_available: dict[str, int] = {}
        # resource managers (pkg/kubelet/cm + volumemanager)
        from .cm import ContainerManager
        from .volumemanager import VolumeManager

        self.container_manager = ContainerManager(node)
        self.volume_manager = VolumeManager(store, node_name=self.node_name)

    RESTART_BACKOFF_BASE_S = 10.0   # kubelet.go MaxContainerBackOff family
    RESTART_BACKOFF_MAX_S = 300.0
    RESTART_BACKOFF_RESET_S = 600.0  # ran this long → loop considered over

    # registration/heartbeat shared via NodeAgentBase (lease recreated on
    # heartbeat — a renew-only agent would stay NotReady after a lease GC)

    # -- the sync loop -------------------------------------------------------

    def sync_loop_iteration(self) -> int:
        """One syncLoopIteration: config changes + PLEG events +
        housekeeping. Returns pods dispatched to workers."""
        # chaos: a dead/hung kubelet. DROP skips the whole iteration
        # (heartbeat included, so the lease goes stale and the lifecycle
        # controller takes over); ERROR models a crashing sync loop the
        # driving harness catches
        if faultinject.fire("kubelet.sync"):
            return 0
        self.heartbeat()
        dispatched = set()
        # configCh: only pods whose API object CHANGED since the last
        # dispatch (new assignment, spec update, deletion mark) — steady-
        # state pods are the PLEG's job, which is the whole point of a PLEG
        current: dict[str, tuple[int, bool]] = {}
        for pod in self._my_pods():
            key = pod.meta.key
            state = (pod.meta.resource_version, pod.is_terminating)
            current[key] = state
            if self._seen.get(key) != state:
                self.workers.update_pod(key)
                dispatched.add(key)
        for key in self._seen:
            if key not in current and key not in dispatched:
                # vanished from the API: one teardown dispatch
                self.workers.update_pod(key)
                dispatched.add(key)
        self._seen = current
        # plegCh: runtime-observed transitions (covers pods whose API object
        # is already gone but whose containers still exist)
        self.pleg.relist()
        for ev in self.pleg.drain():
            if ev.pod_key not in dispatched:
                self.workers.update_pod(ev.pod_key)
                dispatched.add(ev.pod_key)
        # probe ticks: pods with a due liveness/readiness probe re-sync
        now = self.clock.now()
        for key in self.prober.pods_due(now):
            if key not in dispatched:
                self.workers.update_pod(key)
                dispatched.add(key)
        # config-blocked pods: retry (their ConfigMap/Secret may exist now)
        for key in list(self._config_errors):
            if key not in dispatched:
                self.workers.update_pod(key)
                dispatched.add(key)
        # expired active deadlines: fail the pod on time, not on the next
        # unrelated event
        for key, expiry in list(self._deadline_wakeup.items()):
            if now >= expiry:
                self._deadline_wakeup.pop(key, None)
                if key not in dispatched:
                    self.workers.update_pod(key)
                    dispatched.add(key)
        # expired restart backoffs: retry the parked container (pop, not
        # del: a concurrent _teardown on a worker thread may already have
        # removed the entry)
        for key, until in list(self._backoff_wakeup.items()):
            if now >= until:
                self._backoff_wakeup.pop(key, None)
                if key not in dispatched:
                    self.workers.update_pod(key)
                    dispatched.add(key)
        # housekeeping: eviction + orphaned-sandbox cleanup
        self._housekeeping()
        return len(dispatched)

    def container_logs(self, pod_key: str, container: str = "",
                       tail_lines: int | None = None) -> str:
        """kubelet /containerLogs source (kuberuntime ReadLogs): the pod's
        named container's log from the CRI runtime. Empty container name
        picks the pod's only container (kubectl logs semantics)."""
        sid = self._sandboxes.get(pod_key)
        if sid is None:
            raise KeyError(f"no running sandbox for {pod_key}")
        cands = [c for c in self.runtime.list_containers()
                 if c.sandbox_id == sid
                 and (not container or c.name == container)]
        if not cands:
            raise KeyError(f"no container {container!r} in {pod_key}")
        if len(cands) > 1 and not container:
            names = sorted(c.name for c in cands)
            raise KeyError(f"container name required (one of {names})")
        return self.runtime.read_logs(cands[0].id, tail_lines=tail_lines)

    def _my_pods(self):
        return [p for p in self.store.pods()
                if p.spec.node_name == self.node_name]

    # -- SyncPod (per-pod, serialized by PodWorkers) -------------------------

    def _sync_pod(self, key: str) -> None:
        pod = self.store.try_get("Pod", key)
        if pod is None or pod.is_terminating:
            self._teardown(key)
            if pod is not None:
                self.store.try_delete("Pod", key)
            return
        if pod.spec.node_name != self.node_name:
            # same-named pod reassigned elsewhere (StatefulSet identity
            # reuse): OUR sandbox is an orphan — tear down, never resurrect
            # another node's pod here
            self._teardown(key)
            return
        if pod.status.phase in (FAILED, SUCCEEDED):
            # terminal phases are never resynced into running (the corpse
            # keeps its containers for inspection until the object is GC'd)
            # — and their probe workers die NOW, or pods_due would
            # re-dispatch this dead pod on every sync forever
            self.prober.forget_pod(key)
            self._deadline_wakeup.pop(key, None)
            return
        # activeDeadlineSeconds (kubelet_pods activeDeadlineHandler): a
        # Running pod past its deadline fails terminally
        deadline = pod.spec.active_deadline_seconds
        if (deadline is not None and pod.status.start_time is not None
                and pod.status.phase == RUNNING):
            expiry = pod.status.start_time + deadline
            if self.clock.now() >= expiry:
                self._deadline_wakeup.pop(key, None)
                self._fail_pod(pod, "DeadlineExceeded",
                               f"pod exceeded activeDeadlineSeconds="
                               f"{deadline}")
                return
            self._deadline_wakeup[key] = expiry
        # node-allocatable admission (lifecycle/predicate.go): runs before
        # ANY container work; a pod that lost the race for node resources
        # fails terminally with OutOf<resource>
        ok, reason, msg = self.container_manager.admit(pod)
        if not ok:
            self._fail_pod(pod, reason, msg)
            return
        # WaitForAttachAndMount: claim-backed volumes must resolve to a
        # bound PV and mount before containers start; a blocked pod waits
        # in the retry set exactly like a missing ConfigMap reference,
        # with the unmounted-volumes message surfaced on the Ready
        # condition so the stall is diagnosable
        mounted, vol_msg = self.volume_manager.mount_pod(pod)
        if not mounted:
            self._config_errors.add(key)
            self._report_volume_blocked(pod, vol_msg)
            return
        sid = self._sandboxes.get(key)
        if sid is None or all(
            s.id != sid for s in self.runtime.list_pod_sandboxes()
        ):
            from ..utils.net import stable_pod_ip

            ip = pod.status.pod_ip or stable_pod_ip(pod.meta.uid or key)
            sid = self.runtime.run_pod_sandbox(key, ip=ip)
            self._sandboxes[key] = sid
            pod.status.pod_ip = ip
        existing = {c.name: c for c in self.runtime.list_containers()
                    if c.sandbox_id == sid}
        run_s = pod.meta.annotations.get("kubemark.io/run-seconds")
        policy = pod.spec.restart_policy
        # init containers run SEQUENTIALLY to completion before any main
        # container starts (kuberuntime computePodActions: next init starts
        # only after the previous succeeded; a failure under Never fails
        # the pod, otherwise the init container retries per backoff)
        if pod.spec.init_containers:
            done, blocked = self._converge_init(pod, key, sid, existing)
            if not done:
                # a config-blocked INIT step must enter the retry set too,
                # or the pod never re-syncs when the reference appears
                if blocked:
                    self._config_errors.add(key)
                else:
                    self._config_errors.discard(key)
                self._report_status(pod, sid, config_blocked=blocked,
                                    initializing=True)
                return
        # converge MAIN containers: one CRI container per spec container;
        # EXITED containers are restarted per restartPolicy (kuberuntime's
        # computePodActions: Always restarts any exit, OnFailure restarts
        # non-zero exits, Never leaves the corpse for status reporting)
        config_blocked = False  # pod-level: ANY container missing its refs
        for spec_c in pod.spec.containers:
            c = existing.get(spec_c.name)
            if c is not None and c.state == EXITED and (
                policy == "Always"
                or (policy == "OnFailure" and c.exit_code != 0)
            ):
                if not self._may_restart(key, spec_c.name, c):
                    continue  # CrashLoopBackOff: leave the corpse for now
                self.runtime.remove_container(c.id)
                c = None
            if c is None:
                env = self._resolve_env(pod, spec_c)
                if env is None:
                    # CreateContainerConfigError: a referenced ConfigMap/
                    # Secret key is missing — the container cannot start;
                    # housekeeping retries until the reference appears
                    config_blocked = True
                    continue
                if spec_c.image:
                    self.runtime.pull_image(spec_c.image)
                cid = self.runtime.create_container(
                    sid, spec_c.name, spec_c.image,
                    run_seconds=float(run_s) if run_s is not None else None,
                    env=env,
                )
                self.runtime.start_container(cid)
            elif c.state == CREATED:
                self.runtime.start_container(c.id)
        # ONE pod-level set update after the loop: per-container updates
        # would make retry bookkeeping depend on container order
        if config_blocked:
            self._config_errors.add(key)
        else:
            self._config_errors.discard(key)
        self._report_status(pod, sid, config_blocked=config_blocked)

    def _converge_init(self, pod, key: str, sid: str,
                       existing: dict) -> tuple[bool, bool]:
        """Run init containers one at a time; (all_succeeded,
        config_blocked). Init containers default their run duration to 0
        (instant success) unless the pod carries the init-run annotation."""
        run_s = pod.meta.annotations.get("kubemark.io/init-run-seconds", "0")
        for spec_c in pod.spec.init_containers:
            c = existing.get(spec_c.name)
            if c is not None and c.state == EXITED:
                if c.exit_code == 0:
                    continue  # this init step done; next one
                if pod.spec.restart_policy == "Never":
                    return False, False  # pod fails via status reporting
                if not self._may_restart(key, spec_c.name, c):
                    return False, False  # parked in backoff
                self.runtime.remove_container(c.id)
                c = None
            if c is None:
                env = self._resolve_env(pod, spec_c)
                if env is None:
                    return False, True  # CreateContainerConfigError
                if spec_c.image:
                    self.runtime.pull_image(spec_c.image)
                cid = self.runtime.create_container(
                    sid, spec_c.name, spec_c.image,
                    run_seconds=float(run_s), env=env,
                )
                self.runtime.start_container(cid)
                return False, False  # wait for it (sequential)
            if c.state != EXITED:
                return False, False  # still running: wait
        return True, False

    def _resolve_env(self, pod, spec_c) -> dict | None:
        """EnvVar refs → concrete values (kubelet_pods makeEnvironment-
        Variables); None = a non-optional reference is missing."""
        env: dict[str, str] = {}
        for ev in spec_c.env:
            if ev.config_map_key_ref is not None:
                ref = ev.config_map_key_ref
                src = self.store.try_get(
                    "ConfigMap", f"{pod.meta.namespace}/{ref.name}"
                )
            elif ev.secret_key_ref is not None:
                ref = ev.secret_key_ref
                src = self.store.try_get(
                    "Secret", f"{pod.meta.namespace}/{ref.name}"
                )
            else:
                env[ev.name] = ev.value
                continue
            if src is None or ref.key not in src.data:
                if ref.optional:
                    continue
                return None
            env[ev.name] = src.data[ref.key]
        return env

    def _report_volume_blocked(self, pod, message: str) -> None:
        """Pending + Ready=False with the unmounted-volumes message (the
        kubelet's ContainersNotReady report while WaitForAttachAndMount
        blocks); idempotent so retries don't storm the store."""
        cond = next((c for c in pod.status.conditions if c.type == "Ready"),
                    None)
        if (pod.status.phase == PENDING and cond is not None
                and cond.status == "False" and cond.message == message):
            return
        pod.status.phase = PENDING
        pod.status.conditions = [
            c for c in pod.status.conditions if c.type != "Ready"
        ] + [PodCondition(type="Ready", status="False",
                          reason="ContainersNotReady", message=message)]
        try:
            self.store.update(pod, check_version=False)
        except (ConflictError, NotFoundError):
            pass

    def _fail_pod(self, pod, reason: str, message: str) -> None:
        """Terminal failure: stop containers, report Failed + NotReady."""
        key = pod.meta.key
        sid = self._sandboxes.get(key)
        if sid is not None:
            for c in self.runtime.list_containers():
                if c.sandbox_id == sid:
                    self.runtime.stop_container(c.id)
        pod.status.phase = FAILED
        pod.status.conditions = [
            c for c in pod.status.conditions if c.type != "Ready"
        ] + [PodCondition(type="Ready", status="False", reason=reason,
                          message=message)]
        try:
            self.store.update(pod, check_version=False)
        except (ConflictError, NotFoundError):
            pass

    def _may_restart(self, key: str, cname: str, c) -> bool:
        """CrashLoopBackOff: exponential delay between restarts of the same
        container; a long successful run resets the loop."""
        now = self.clock.now()
        bk = (key, cname)
        count, until = self._restart_backoff.get(bk, (0, 0.0))
        if c.finished_at and c.started_at and (
            c.finished_at - c.started_at >= self.RESTART_BACKOFF_RESET_S
        ):
            count, until = 0, 0.0
        if now < until:
            # parked: remember when to wake this pod for the retry
            cur = self._backoff_wakeup.get(key)
            if cur is None or until < cur:
                self._backoff_wakeup[key] = until
            return False
        delay = min(self.RESTART_BACKOFF_BASE_S * (2 ** count),
                    self.RESTART_BACKOFF_MAX_S)
        self._restart_backoff[bk] = (count + 1, now + delay)
        return True

    def _report_status(self, pod, sid: str, config_blocked: bool = False,
                       initializing: bool = False) -> None:
        """Container states → pod phase (kubelet's status manager), with
        probe results folded in: liveness failures kill the container
        (restart policy then applies next sync), readiness gates Ready.
        config_blocked (CreateContainerConfigError on any container) pins
        the pod Pending and NotReady — a pod missing one of its containers
        must not serve traffic. initializing: init containers are still
        running — Pending/NotReady, or Failed when an init step failed
        under restartPolicy Never."""
        if initializing:
            init_failed = any(
                c.state == EXITED and c.exit_code != 0
                for c in self.runtime.list_containers()
                if c.sandbox_id == sid
                and c.name in {ic.name for ic in pod.spec.init_containers}
            ) and pod.spec.restart_policy == "Never"
            phase = FAILED if init_failed else PENDING
            changed = phase != pod.status.phase
            pod.status.phase = phase
            cond = next((c for c in pod.status.conditions
                         if c.type == "Ready"), None)
            if cond is None or cond.status != "False":
                pod.status.conditions = [
                    c for c in pod.status.conditions if c.type != "Ready"
                ] + [PodCondition(type="Ready", status="False")]
                changed = True
            if changed:
                try:
                    self.store.update(pod, check_version=False)
                except (ConflictError, NotFoundError):
                    pass
            return
        states = [c for c in self.runtime.list_containers()
                  if c.sandbox_id == sid]
        running = {c.name for c in states
                   if c.state not in (EXITED,)}
        probes_ready, kill = self.prober.sync_pod(pod, running)
        for c in states:
            if c.name in kill:
                self.runtime.stop_container(c.id)
        if kill:
            states = [c for c in self.runtime.list_containers()
                      if c.sandbox_id == sid]
            # a liveness kill needs a follow-up sync to restart the
            # container per restartPolicy
            self.workers.update_pod(pod.meta.key)
        if not states or config_blocked:
            # a container that never got created keeps the POD Pending
            # (real phase semantics: Running requires every container
            # started at least once)
            phase = PENDING
        elif all(c.state == EXITED for c in states):
            failed = any(c.exit_code != 0 for c in states)
            if pod.spec.restart_policy == "Always":
                phase = RUNNING  # restarts pending next sync
            else:
                phase = FAILED if failed else SUCCEEDED
        else:
            phase = RUNNING
        changed = phase != pod.status.phase
        pod.status.phase = phase
        if phase == RUNNING and pod.status.start_time is None:
            pod.status.start_time = self.clock.now()
            changed = True
        # Ready needs probes AND at least one actually-running container:
        # a CrashLoopBackOff-parked pod reports phase=Running (restart
        # pending) but must not keep receiving service traffic
        any_running = any(c.state == CONTAINER_RUNNING for c in states)
        ready = ("True" if phase == RUNNING and probes_ready and any_running
                 else "False")
        cond = next((c for c in pod.status.conditions if c.type == "Ready"),
                    None)
        if cond is None or cond.status != ready:
            pod.status.conditions = [
                c for c in pod.status.conditions if c.type != "Ready"
            ] + [PodCondition(type="Ready", status=ready)]
            changed = True
        if changed:
            try:
                self.store.update(pod, check_version=False)
            except (ConflictError, NotFoundError):
                pass

    def _teardown(self, key: str) -> None:
        # the pod's published metrics die with it: a same-named successor
        # (StatefulSet identity reuse) must not inherit stale usage and
        # churn must not leak PodMetrics objects
        self.pod_stats.pop(key, None)
        self.prober.forget_pod(key)
        self._config_errors.discard(key)
        self._backoff_wakeup.pop(key, None)
        self._deadline_wakeup.pop(key, None)
        for bk in [b for b in self._restart_backoff if b[0] == key]:
            del self._restart_backoff[bk]
        self.store.try_delete("PodMetrics", key)
        self.container_manager.release(key)
        self.volume_manager.unmount_pod(key)
        sid = self._sandboxes.pop(key, None)
        if sid is None:
            return
        self.runtime.stop_pod_sandbox(sid)
        self.runtime.remove_pod_sandbox(sid)

    # -- housekeeping --------------------------------------------------------

    def _housekeeping(self) -> None:
        # orphaned sandboxes: runtime pods whose API object vanished.
        # Dispatch through the workers — _sync_pod observes the missing API
        # object and tears down — so teardown serializes with any in-flight
        # sync of the same pod (direct _teardown here would race a worker
        # into re-creating the sandbox)
        my = {p.meta.key for p in self._my_pods()}
        for key in list(self._sandboxes):
            if key not in my:
                self.workers.update_pod(key)
        # node-pressure eviction + condition/taint reporting
        if self.eviction.thresholds:
            self.eviction.synchronize(self._my_pods())
            self._report_pressure()
        # publish per-pod usage as PodMetrics (the metrics-server role the
        # HPA controller consumes)
        if self.pod_stats:
            self._publish_metrics()
        self._report_images()

    def _report_images(self) -> None:
        """NodeStatus.images from the CRI image store (kubelet_node_status
        nodestatus.Images) — what the scheduler's ImageLocality scores."""
        from ..api.types import ContainerImage

        images = sorted(
            (ContainerImage(names=(img.ref,), size_bytes=img.size_bytes)
             for img in self.runtime.list_images()),
            key=lambda i: i.names,
        )
        node = self.store.try_get("Node", self.node_name)
        if node is None or node.status.images == images:
            return
        node.status.images = images
        try:
            self.store.update(node, check_version=False)
        except (ConflictError, NotFoundError):
            pass

    def _publish_metrics(self) -> None:
        from ..api.meta import ObjectMeta
        from ..api.workloads import PodMetrics

        for key, st in self.pod_stats.items():
            ns, _, name = key.partition("/")
            existing = self.store.try_get("PodMetrics", key)
            if existing is None:
                self.store.create(PodMetrics(
                    meta=ObjectMeta(name=name, namespace=ns),
                    cpu_usage_milli=st.cpu_milli,
                    memory_usage_bytes=st.memory_bytes,
                ))
            elif (existing.cpu_usage_milli != st.cpu_milli
                  or existing.memory_usage_bytes != st.memory_bytes):
                existing.cpu_usage_milli = st.cpu_milli
                existing.memory_usage_bytes = st.memory_bytes
                try:
                    self.store.update(existing, check_version=False)
                except (ConflictError, NotFoundError):
                    pass

    def _report_pressure(self) -> None:
        node = self.store.try_get("Node", self.node_name)
        if node is None:
            return
        conds = self.eviction.node_conditions()
        changed = False
        for cond_type in ("MemoryPressure", "DiskPressure"):
            want = "True" if cond_type in conds else "False"
            cur = next((c for c in node.status.conditions
                        if c.type == cond_type), None)
            if cur is None or cur.status != want:
                node.status.conditions = [
                    c for c in node.status.conditions if c.type != cond_type
                ] + [NodeCondition(type=cond_type, status=want)]
                changed = True
        taints = {(t.key, t.effect) for t in self.eviction.node_taints()}
        keep = [t for t in node.spec.taints
                if not t.key.endswith("-pressure") or (t.key, t.effect) in taints]
        add = [t for t in self.eviction.node_taints()
               if (t.key, t.effect) not in {(x.key, x.effect) for x in keep}]
        if add or len(keep) != len(node.spec.taints):
            node.spec.taints = tuple(keep) + tuple(add)
            changed = True
        if changed:
            try:
                self.store.update(node, check_version=False)
            except (ConflictError, NotFoundError):
                pass

    # -- eviction plumbing ---------------------------------------------------

    def _stats(self):
        return dict(self.node_available), dict(self.pod_stats)

    def _evict(self, pod, reason: str) -> None:
        """Status-Failed + delete (the eviction API write path). Runtime
        teardown goes through the pod's worker, not inline — _sync_pod sees
        the deleted object and tears down under per-key serialization."""
        pod.status.phase = FAILED
        pod.status.conditions = [
            c for c in pod.status.conditions if c.type != "Ready"
        ] + [PodCondition(type="DisruptionTarget", status="True",
                          reason="TerminationByKubelet", message=reason)]
        try:
            self.store.update(pod, check_version=False)
            self.store.delete("Pod", pod.meta.key)
        except (ConflictError, NotFoundError):
            pass
        self.workers.update_pod(pod.meta.key)

    def shutdown(self) -> None:
        self.workers.stop()
