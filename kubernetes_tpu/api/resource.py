"""Dense resource vectors — the row type of the NodeInfo device planes.

Reference: pkg/scheduler/framework/types.go (Resource struct: MilliCPU, Memory,
EphemeralStorage, AllowedPodNumber, ScalarResources map). Here a resource
vector IS a fixed-width int array in plane units so the same object feeds the
host fit/score math and the [nodes, R] device tensors unchanged.
"""

from __future__ import annotations

from typing import Iterable, Mapping

from .quantity import parse_cpu, parse_mem_mib, parse_count

# Fixed base resource indices (plane columns).
CPU = 0  # millicores
MEM = 1  # MiB
EPHEMERAL = 2  # MiB
PODS = 3  # count
NUM_BASE_RESOURCES = 4

# Defaults for pods that request nothing, used by NonZero accounting only
# (reference: pkg/scheduler/util/pod_resources.go:29-31 — 100 mCPU, 200 MB).
# 200 MB = 190.73 MiB -> ceil 191 MiB in plane units.
DEFAULT_MILLI_CPU = 100
DEFAULT_MEM_MIB = 191


class ResourceNames:
    """Registry mapping resource names to plane columns.

    Base resources have fixed columns; extended resources (nvidia.com/gpu,
    google.com/tpu, hugepages-*) get columns appended in registration order.
    One registry instance is shared by a cluster's cache + tensor snapshots so
    every NodeInfo row has the same width.
    """

    BASE = ("cpu", "memory", "ephemeral-storage", "pods")

    def __init__(self) -> None:
        self._index: dict[str, int] = {n: i for i, n in enumerate(self.BASE)}
        self._names: list[str] = list(self.BASE)

    def index_of(self, name: str) -> int:
        i = self._index.get(name)
        if i is None:
            i = len(self._names)
            self._index[name] = i
            self._names.append(name)
        return i

    def get(self, name: str) -> int | None:
        return self._index.get(name)

    @property
    def width(self) -> int:
        return len(self._names)

    @property
    def names(self) -> list[str]:
        return list(self._names)

    def parse(self, name: str, value, *, floor: bool = False) -> int:
        """Parse a quantity for resource `name` into its plane unit."""
        if name == "cpu":
            if floor:
                # capacities: floor at milli granularity
                from .quantity import parse_quantity

                v = parse_quantity(value) * 1000
                return v.numerator // v.denominator
            return parse_cpu(value)
        if name in ("memory", "ephemeral-storage") or name.startswith("hugepages-"):
            return parse_mem_mib(value, floor=floor)
        return parse_count(value, floor=floor)


class ResourceVec:
    """A mutable fixed-width int vector of plane-unit resource amounts."""

    __slots__ = ("v",)

    def __init__(self, width: int = NUM_BASE_RESOURCES, values: Iterable[int] | None = None):
        if values is not None:
            self.v = list(values)
            if len(self.v) < width:
                self.v.extend([0] * (width - len(self.v)))
        else:
            self.v = [0] * width

    @classmethod
    def from_map(
        cls, m: Mapping[str, object], names: ResourceNames, *, floor: bool = False
    ) -> "ResourceVec":
        r = cls(names.width)
        for k, q in m.items():
            i = names.index_of(k)
            if i >= len(r.v):
                r.v.extend([0] * (i + 1 - len(r.v)))
            r.v[i] = names.parse(k, q, floor=floor)
        return r

    def widen(self, width: int) -> None:
        if width > len(self.v):
            self.v.extend([0] * (width - len(self.v)))

    def add(self, other: "ResourceVec") -> None:
        self.widen(len(other.v))
        for i, x in enumerate(other.v):
            self.v[i] += x

    def sub(self, other: "ResourceVec") -> None:
        self.widen(len(other.v))
        for i, x in enumerate(other.v):
            self.v[i] -= x

    def max_with(self, other: "ResourceVec") -> None:
        """Elementwise max — container-limits semantics for pod requests."""
        self.widen(len(other.v))
        for i, x in enumerate(other.v):
            if x > self.v[i]:
                self.v[i] = x

    def clone(self) -> "ResourceVec":
        return ResourceVec(len(self.v), self.v)

    def row(self, width: int) -> list[int]:
        """Fixed-width row for tensor materialization."""
        if len(self.v) >= width:
            return self.v[:width]
        return self.v + [0] * (width - len(self.v))

    def __getitem__(self, i: int) -> int:
        return self.v[i] if i < len(self.v) else 0

    def __setitem__(self, i: int, val: int) -> None:
        self.widen(i + 1)
        self.v[i] = val

    def __eq__(self, other) -> bool:
        if not isinstance(other, ResourceVec):
            return NotImplemented
        n = max(len(self.v), len(other.v))
        return all(self[i] == other[i] for i in range(n))

    def __repr__(self) -> str:
        return f"ResourceVec({self.v})"


def pod_request_vec(pod, names: ResourceNames) -> ResourceVec:
    """Effective pod resource request in plane units.

    Reference: computePodResourceRequest (pkg/scheduler/framework/plugins/
    noderesources/fit.go:317) — sum of container requests, elementwise-max with
    each init container, plus overhead. The +1 pod slot is accounted by the
    caller via the PODS column.
    """
    req = ResourceVec(names.width)
    for c in pod.spec.containers:
        req.add(ResourceVec.from_map(c.requests, names))
    for c in pod.spec.init_containers:
        req.max_with(ResourceVec.from_map(c.requests, names))
    if pod.spec.overhead:
        req.add(ResourceVec.from_map(pod.spec.overhead, names))
    req[PODS] = 1
    return req


def nonzero_request_vec(req: ResourceVec) -> ResourceVec:
    """Request with zero cpu/mem replaced by defaults.

    Reference: pkg/scheduler/util/pod_resources.go GetNonzeroRequests — used by
    scoring so empty pods still register load.
    """
    nz = req.clone()
    if nz[CPU] == 0:
        nz[CPU] = DEFAULT_MILLI_CPU
    if nz[MEM] == 0:
        nz[MEM] = DEFAULT_MEM_MIB
    return nz
