"""Typed object core: the subset of the Kubernetes API surface the framework uses.

Reference: staging/src/k8s.io/api/core/v1/types.go and
staging/src/k8s.io/apimachinery. Quantities are canonicalized to integer "plane
units" (CPU millicores, memory/storage MiB) at parse time so that host-path and
TPU-path arithmetic is bit-identical by construction.
"""

from .quantity import parse_quantity, parse_cpu, parse_mem_mib  # noqa: F401
from .resource import (  # noqa: F401
    ResourceNames,
    ResourceVec,
    CPU,
    MEM,
    EPHEMERAL,
    PODS,
    NUM_BASE_RESOURCES,
)
from .meta import ObjectMeta  # noqa: F401
from .labels import (  # noqa: F401
    Requirement,
    LabelSelector,
    matches_selector,
    format_labels,
)
from .types import (  # noqa: F401
    Container,
    ContainerPort,
    Pod,
    PodSpec,
    PodStatus,
    PodCondition,
    Node,
    NodeSpec,
    NodeStatus,
    ContainerImage,
    Taint,
    Toleration,
    Affinity,
    NodeAffinity,
    NodeSelector,
    NodeSelectorTerm,
    NodeSelectorRequirement,
    PreferredSchedulingTerm,
    PodAffinity,
    PodAntiAffinity,
    PodAffinityTerm,
    WeightedPodAffinityTerm,
    TopologySpreadConstraint,
    SchedulingGroup,
    PodGroup,
    PodGroupSpec,
    PodGroupStatus,
    GangPolicy,
    TopologyConstraint,
    Binding,
)
