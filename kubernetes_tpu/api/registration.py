"""apiregistration.k8s.io — APIService objects for the aggregation layer.

Reference: staging/src/k8s.io/kube-aggregator/pkg/apis/apiregistration —
the aggregator (first server in the reference's delegation chain,
cmd/kube-apiserver/app/server.go:176) proxies every request under
/apis/<group>/<version>/ to the Service named by the matching APIService,
so out-of-process servers (metrics-server being the canonical one) mount
API groups into the main server's surface and discovery.

Here the ServiceReference is a base URL (the delegate's listener): the
main server proxies method/body/query through and merges the group into
/apis discovery. Names follow the reference's "<version>.<group>"
convention.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta


@dataclass
class APIServiceSpec:
    """APIServiceSpec subset: the ServiceReference collapses to the
    delegate's base URL; groupPriorityMinimum ordering is by name."""

    group: str = ""
    version: str = ""
    # delegate base URL, e.g. "http://127.0.0.1:9443" — the proxy appends
    # the original request path (/apis/<group>/<version>/...); an empty
    # URL makes the group discoverable but unavailable (503), matching an
    # APIService whose backing Service has no endpoints
    service_url: str = ""
    insecure_skip_tls_verify: bool = True


@dataclass
class APIService:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: APIServiceSpec = field(default_factory=APIServiceSpec)
    # {"conditions": [{"type": "Available", "status": "True"|"False", ...}]}
    status: dict = field(default_factory=dict)

    kind = "APIService"

    @staticmethod
    def expected_name(group: str, version: str) -> str:
        return f"{version}.{group}"
