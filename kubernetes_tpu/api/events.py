"""events.k8s.io/v1 Event — the API object component event recorders write.

Reference: staging/src/k8s.io/api/events/v1/types.go. Lives in the api
package (not the scheduler) so the wire scheme registers it for EVERY
process: an apiserver that never imports the scheduler must still decode
'Event' POSTs from a remote component's recorder.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


@dataclass
class Event:
    """events.k8s.io/v1 Event (scheduling-relevant subset)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: str = ""  # "<kind>/<namespace>/<name>"
    type: str = EVENT_TYPE_NORMAL
    reason: str = ""
    message: str = ""
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0
    reporting_controller: str = "default-scheduler"

    kind = "Event"
