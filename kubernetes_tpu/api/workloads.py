"""Workload + networking API types: ReplicaSet, Deployment, Job, Service,
EndpointSlice, Namespace.

Reference: staging/src/k8s.io/api/apps/v1/types.go (Deployment, ReplicaSet),
batch/v1/types.go (Job), core/v1 (Service, Namespace),
discovery/v1/types.go (EndpointSlice). Scheduling/controller-relevant subset.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .labels import LabelSelector
from .meta import ObjectMeta
from .types import PodSpec


@dataclass
class PodTemplateSpec:
    labels: dict[str, str] = field(default_factory=dict)
    spec: PodSpec = field(default_factory=PodSpec)


# --- apps/v1 ----------------------------------------------------------------


@dataclass
class ReplicaSetSpec:
    replicas: int = 1
    selector: LabelSelector | None = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


@dataclass
class ReplicaSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicaSet:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicaSetSpec = field(default_factory=ReplicaSetSpec)
    status: ReplicaSetStatus = field(default_factory=ReplicaSetStatus)

    kind = "ReplicaSet"


@dataclass
class DeploymentStrategy:
    type: str = "RollingUpdate"  # RollingUpdate | Recreate
    max_surge: int = 1
    max_unavailable: int = 0


@dataclass
class DeploymentSpec:
    replicas: int = 1
    selector: LabelSelector | None = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: DeploymentStrategy = field(default_factory=DeploymentStrategy)
    # rollout pause (kubectl rollout pause): template changes don't roll
    # while paused; pure scaling of the current RS still applies
    paused: bool = False


@dataclass
class DeploymentStatus:
    replicas: int = 0
    updated_replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class Deployment:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DeploymentSpec = field(default_factory=DeploymentSpec)
    status: DeploymentStatus = field(default_factory=DeploymentStatus)

    kind = "Deployment"


@dataclass
class StatefulSetSpec:
    """apps/v1 StatefulSetSpec (scheduling/controller-relevant subset)."""

    replicas: int = 1
    selector: LabelSelector | None = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    service_name: str = ""
    # OrderedReady: ordinal i+1 waits for ordinal i to be running;
    # Parallel: all at once (apps/v1 PodManagementPolicyType)
    pod_management_policy: str = "OrderedReady"
    # volumeClaimTemplates: per-ordinal stable storage — the controller
    # mints PVC <tpl>-<set>-<ordinal> and mounts it; the PVC OUTLIVES its
    # pod, so a recreated ordinal reattaches the same data
    volume_claim_templates: tuple = ()  # tuple[storage.PersistentVolumeClaim]


@dataclass
class StatefulSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    observed_generation: int = 0


@dataclass
class StatefulSet:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: StatefulSetSpec = field(default_factory=StatefulSetSpec)
    status: StatefulSetStatus = field(default_factory=StatefulSetStatus)

    kind = "StatefulSet"


@dataclass
class DaemonSetSpec:
    selector: LabelSelector | None = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    # RollingUpdate strategy: at most this many nodes may be without a
    # running daemon during a template roll (apps/v1 default 1)
    max_unavailable: int = 1


@dataclass
class DaemonSetStatus:
    desired_number_scheduled: int = 0
    current_number_scheduled: int = 0
    number_ready: int = 0


@dataclass
class DaemonSet:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: DaemonSetSpec = field(default_factory=DaemonSetSpec)
    status: DaemonSetStatus = field(default_factory=DaemonSetStatus)

    kind = "DaemonSet"


# --- batch/v1 ---------------------------------------------------------------


@dataclass
class JobSpec:
    completions: int = 1
    parallelism: int = 1
    backoff_limit: int = 6
    selector: LabelSelector | None = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    # batch/v1 ttlSecondsAfterFinished: the TTL-after-finished controller
    # deletes the Job this long after it completes (None = keep forever)
    ttl_seconds_after_finished: int | None = None
    # batch/v1 activeDeadlineSeconds: the job controller fails the whole
    # job (terminating its pods) once it has run this long
    active_deadline_seconds: int | None = None


@dataclass
class JobStatus:
    active: int = 0
    succeeded: int = 0
    failed: int = 0
    completed: bool = False
    completion_time: float | None = None
    start_time: float | None = None
    # terminal failure reason ("BackoffLimitExceeded"/"DeadlineExceeded" —
    # the Failed condition's reason in batch/v1)
    failure_reason: str = ""


@dataclass
class Job:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: JobSpec = field(default_factory=JobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    kind = "Job"


@dataclass
class PodMetrics:
    """metrics.k8s.io/v1beta1 PodMetrics subset: per-pod usage published by
    the node agent (the metrics-server role) and consumed by the HPA."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    cpu_usage_milli: int = 0
    memory_usage_bytes: int = 0
    window_s: float = 15.0

    kind = "PodMetrics"


@dataclass
class HPASpec:
    """autoscaling/v2 subset: one CPU-utilization metric target."""

    scale_target_kind: str = "Deployment"
    scale_target_name: str = ""
    min_replicas: int = 1
    max_replicas: int = 10
    target_cpu_utilization_percent: int = 80
    # scale-down stabilization (autoscaling/v2 behavior.scaleDown default
    # 300s): the controller applies the HIGHEST recommendation in the window
    scale_down_stabilization_s: float = 300.0


@dataclass
class HPAStatus:
    current_replicas: int = 0
    desired_replicas: int = 0
    current_cpu_utilization_percent: int | None = None
    last_scale_time: float | None = None


@dataclass
class HorizontalPodAutoscaler:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: HPASpec = field(default_factory=HPASpec)
    status: HPAStatus = field(default_factory=HPAStatus)

    kind = "HorizontalPodAutoscaler"


@dataclass
class CronJobSpec:
    """batch/v1 CronJobSpec subset: 5-field cron schedule + concurrency
    policy + history limits."""

    schedule: str = "* * * * *"
    job_template: JobSpec = field(default_factory=JobSpec)
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    suspend: bool = False
    successful_jobs_history_limit: int = 3
    failed_jobs_history_limit: int = 1
    starting_deadline_seconds: int | None = None


@dataclass
class CronJobStatus:
    last_schedule_time: float | None = None
    active: tuple[str, ...] = ()  # job keys


@dataclass
class CronJob:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CronJobSpec = field(default_factory=CronJobSpec)
    status: CronJobStatus = field(default_factory=CronJobStatus)

    kind = "CronJob"


# --- core/v1 Service + discovery/v1 EndpointSlice ---------------------------


@dataclass(frozen=True)
class ServicePort:
    port: int
    target_port: int = 0
    protocol: str = "TCP"
    name: str = ""
    node_port: int = 0


@dataclass
class ServiceSpec:
    selector: dict[str, str] = field(default_factory=dict)
    ports: tuple[ServicePort, ...] = ()
    cluster_ip: str = ""
    type: str = "ClusterIP"
    # core/v1 ServiceSpec traffic-routing knobs consumed by the proxy layer
    session_affinity: str = "None"  # "None" | "ClientIP"
    session_affinity_timeout_s: int = 10800
    internal_traffic_policy: str = "Cluster"  # "Cluster" | "Local"
    external_traffic_policy: str = "Cluster"  # "Cluster" | "Local"


@dataclass
class Service:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)

    kind = "Service"


@dataclass(frozen=True)
class Endpoint:
    addresses: tuple[str, ...]
    node_name: str = ""
    ready: bool = True
    target_pod: str = ""  # pod key
    # discovery/v1 EndpointConditions: serving mirrors readiness but stays
    # true for terminating pods; the proxy falls back to serving-terminating
    # endpoints when a service has no ready ones (pkg/proxy/topology.go)
    serving: bool = True
    terminating: bool = False


@dataclass
class EndpointSlice:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    service_name: str = ""
    endpoints: tuple[Endpoint, ...] = ()
    ports: tuple[ServicePort, ...] = ()

    kind = "EndpointSlice"


@dataclass
class Namespace:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    phase: str = "Active"

    kind = "Namespace"


@dataclass
class ConfigMap:
    """core/v1 ConfigMap: plain key→value configuration data."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict[str, str] = field(default_factory=dict)

    kind = "ConfigMap"


@dataclass
class Secret:
    """core/v1 Secret subset: stringData semantics (values handled as
    strings; at-rest encoding is the store's concern, not the type's)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict[str, str] = field(default_factory=dict)
    type: str = "Opaque"

    kind = "Secret"


@dataclass
class ResourceQuota:
    """core/v1 ResourceQuota subset: hard caps per namespace over
    requests.cpu / requests.memory (milli / MiB) and object counts
    ("pods", "count/<kind>"). `used` is maintained by the quota controller
    and enforced at admission."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    hard: dict[str, int] = field(default_factory=dict)
    used: dict[str, int] = field(default_factory=dict)

    kind = "ResourceQuota"
