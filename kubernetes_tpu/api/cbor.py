"""CBOR (RFC 8949) codec: the binary wire format.

Reference: apimachinery ships three serializers — JSON, protobuf, and CBOR
(staging/src/k8s.io/apimachinery/pkg/runtime/serializer/cbor, the KEP-4222
format Kubernetes is moving to for the protobuf role on CRDs). JSON is the
debuggable format; the binary format is what components negotiate for bulk
traffic (lists, watches) because it cuts encode time and bytes. This is a
self-contained RFC 8949 subset covering the JSON data model the object
codec (serialization.py) produces: None/bool/int/float/str/bytes/list/dict.

Deterministic encoding: definite lengths, shortest-form integers — the
"core deterministic encoding" RFC 8949 §4.2 requires, which makes encoded
objects byte-comparable.
"""

from __future__ import annotations

import struct

_MAJOR_UINT = 0
_MAJOR_NEGINT = 1
_MAJOR_BYTES = 2
_MAJOR_TEXT = 3
_MAJOR_ARRAY = 4
_MAJOR_MAP = 5
_SIMPLE_FALSE = b"\xf4"
_SIMPLE_TRUE = b"\xf5"
_SIMPLE_NULL = b"\xf6"
_FLOAT64 = b"\xfb"


def _head(major: int, n: int) -> bytes:
    mb = major << 5
    if n < 24:
        return bytes([mb | n])
    if n < 0x100:
        return bytes([mb | 24, n])
    if n < 0x10000:
        return bytes([mb | 25]) + n.to_bytes(2, "big")
    if n < 0x100000000:
        return bytes([mb | 26]) + n.to_bytes(4, "big")
    return bytes([mb | 27]) + n.to_bytes(8, "big")


def dumps(obj) -> bytes:
    out = bytearray()
    _encode(obj, out)
    return bytes(out)


def _encode(obj, out: bytearray) -> None:
    if obj is None:
        out += _SIMPLE_NULL
    elif obj is True:
        out += _SIMPLE_TRUE
    elif obj is False:
        out += _SIMPLE_FALSE
    elif isinstance(obj, int):
        if obj >= 0:
            out += _head(_MAJOR_UINT, obj)
        else:
            out += _head(_MAJOR_NEGINT, -1 - obj)
    elif isinstance(obj, float):
        out += _FLOAT64 + struct.pack(">d", obj)
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out += _head(_MAJOR_TEXT, len(b))
        out += b
    elif isinstance(obj, (bytes, bytearray)):
        out += _head(_MAJOR_BYTES, len(obj))
        out += obj
    elif isinstance(obj, (list, tuple)):
        out += _head(_MAJOR_ARRAY, len(obj))
        for item in obj:
            _encode(item, out)
    elif isinstance(obj, dict):
        out += _head(_MAJOR_MAP, len(obj))
        for k, v in obj.items():
            _encode(k, out)
            _encode(v, out)
    else:
        raise TypeError(f"cbor: unsupported type {type(obj).__name__}")


class _Decoder:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("cbor: truncated input")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def _length(self, info: int) -> int:
        if info < 24:
            return info
        if info == 24:
            return self._take(1)[0]
        if info == 25:
            return int.from_bytes(self._take(2), "big")
        if info == 26:
            return int.from_bytes(self._take(4), "big")
        if info == 27:
            return int.from_bytes(self._take(8), "big")
        raise ValueError(f"cbor: indefinite/reserved length {info}")

    def decode(self):
        ib = self._take(1)[0]
        major, info = ib >> 5, ib & 0x1F
        if major == _MAJOR_UINT:
            return self._length(info)
        if major == _MAJOR_NEGINT:
            return -1 - self._length(info)
        if major == _MAJOR_BYTES:
            return bytes(self._take(self._length(info)))
        if major == _MAJOR_TEXT:
            return self._take(self._length(info)).decode("utf-8")
        if major == _MAJOR_ARRAY:
            return [self.decode() for _ in range(self._length(info))]
        if major == _MAJOR_MAP:
            return {self.decode(): self.decode()
                    for _ in range(self._length(info))}
        if major == 7:
            if ib == _SIMPLE_NULL[0]:
                return None
            if ib == _SIMPLE_TRUE[0]:
                return True
            if ib == _SIMPLE_FALSE[0]:
                return False
            if ib == _FLOAT64[0]:
                return struct.unpack(">d", self._take(8))[0]
        raise ValueError(f"cbor: unsupported item 0x{ib:02x}")


def loads(data: bytes):
    dec = _Decoder(data)
    obj = dec.decode()
    if dec.pos != len(data):
        raise ValueError("cbor: trailing bytes")
    return obj
