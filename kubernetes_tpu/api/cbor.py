"""CBOR (RFC 8949) codec: the binary wire format.

Reference: apimachinery ships three serializers — JSON, protobuf, and CBOR
(staging/src/k8s.io/apimachinery/pkg/runtime/serializer/cbor, the KEP-4222
format Kubernetes is moving to for the protobuf role on CRDs). JSON is the
debuggable format; the binary format is what components negotiate for bulk
traffic (lists, watches) because it cuts encode time and bytes. This is a
self-contained RFC 8949 subset covering the JSON data model the object
codec (serialization.py) produces: None/bool/int/float/str/bytes/list/dict.

Deterministic encoding: definite lengths, shortest-form integers — the
"core deterministic encoding" RFC 8949 §4.2 requires, which makes encoded
objects byte-comparable.
"""

from __future__ import annotations

import struct

_MAJOR_UINT = 0
_MAJOR_NEGINT = 1
_MAJOR_BYTES = 2
_MAJOR_TEXT = 3
_MAJOR_ARRAY = 4
_MAJOR_MAP = 5
_SIMPLE_FALSE = b"\xf4"
_SIMPLE_TRUE = b"\xf5"
_SIMPLE_NULL = b"\xf6"
_FLOAT64 = b"\xfb"


def _head(major: int, n: int) -> bytes:
    mb = major << 5
    if n < 24:
        return bytes([mb | n])
    if n < 0x100:
        return bytes([mb | 24, n])
    if n < 0x10000:
        return bytes([mb | 25]) + n.to_bytes(2, "big")
    if n < 0x100000000:
        return bytes([mb | 26]) + n.to_bytes(4, "big")
    return bytes([mb | 27]) + n.to_bytes(8, "big")


# -- native transcoder (JSON text ↔ CBOR in C++) -----------------------------
#
# The pure-Python encoder walks objects byte by byte; for the list-sized
# payloads the binary format exists for, that is slower than the
# C-accelerated json module. The native path (native/cbor_core.cpp) rides
# json.dumps/json.loads for the Python-object half and does the byte work
# in C++. Values outside the JSON data model (byte strings, >64-bit ints,
# non-string map keys) transparently fall back to the pure codec.

_native = None
_native_tried = False


def _load_native():
    global _native, _native_tried
    if _native_tried:
        return _native
    import ctypes

    from ..utils.nativelib import load_native

    lib = load_native("libcbor_core.so")  # locked build-and-load
    if lib is not None and not hasattr(lib, "_cj_prototyped"):
        lib.cj_json_to_cbor.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.cj_json_to_cbor.restype = ctypes.c_int64
        lib.cj_cbor_to_json.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.cj_cbor_to_json.restype = ctypes.c_int64
        lib.cj_free.argtypes = [ctypes.c_void_p]
        lib.cj_free.restype = None
        lib._cj_prototyped = True
    _native = lib
    _native_tried = True
    return _native


def _str_keys_only(obj) -> bool:
    """json.dumps STRINGIFIES int/bool/None dict keys instead of raising —
    the native path must not silently corrupt them; walk containers (not
    leaf values) and punt to the pure codec on any non-str key."""
    if isinstance(obj, dict):
        return all(
            isinstance(k, str) and _str_keys_only(v) for k, v in obj.items()
        )
    if isinstance(obj, (list, tuple)):
        return all(_str_keys_only(v) for v in obj)
    return True


def _native_dumps(obj) -> bytes | None:
    lib = _load_native()
    if lib is None:
        return None
    if not _str_keys_only(obj):
        return None  # non-str map keys: pure codec preserves them
    import ctypes
    import json as _json

    try:
        text = _json.dumps(obj, ensure_ascii=False, separators=(",", ":"))
    except (TypeError, ValueError):
        return None  # bytes or other non-JSON values: pure codec
    raw = text.encode("utf-8")
    out = ctypes.POINTER(ctypes.c_uint8)()
    out_len = ctypes.c_size_t()
    rc = lib.cj_json_to_cbor(raw, len(raw), ctypes.byref(out),
                             ctypes.byref(out_len))
    if rc != 0:
        return None
    try:
        return ctypes.string_at(out, out_len.value)
    finally:
        lib.cj_free(out)


def _native_loads(data: bytes):
    lib = _load_native()
    if lib is None:
        return None
    import ctypes
    import json as _json

    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else \
        (ctypes.c_uint8 * 1)()
    out = ctypes.c_char_p()
    out_len = ctypes.c_size_t()
    rc = lib.cj_cbor_to_json(buf, len(data), ctypes.byref(out),
                             ctypes.byref(out_len))
    if rc != 0:
        return None
    try:
        text = ctypes.string_at(out, out_len.value).decode("utf-8")
    finally:
        lib.cj_free(out)
    return (_json.loads(text),)


def dumps(obj) -> bytes:
    native = _native_dumps(obj)
    if native is not None:
        return native
    out = bytearray()
    _encode(obj, out)
    return bytes(out)


def _encode(obj, out: bytearray) -> None:
    if obj is None:
        out += _SIMPLE_NULL
    elif obj is True:
        out += _SIMPLE_TRUE
    elif obj is False:
        out += _SIMPLE_FALSE
    elif isinstance(obj, int):
        if obj >= 0:
            out += _head(_MAJOR_UINT, obj)
        else:
            out += _head(_MAJOR_NEGINT, -1 - obj)
    elif isinstance(obj, float):
        out += _FLOAT64 + struct.pack(">d", obj)
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out += _head(_MAJOR_TEXT, len(b))
        out += b
    elif isinstance(obj, (bytes, bytearray)):
        out += _head(_MAJOR_BYTES, len(obj))
        out += obj
    elif isinstance(obj, (list, tuple)):
        out += _head(_MAJOR_ARRAY, len(obj))
        for item in obj:
            _encode(item, out)
    elif isinstance(obj, dict):
        out += _head(_MAJOR_MAP, len(obj))
        for k, v in obj.items():
            _encode(k, out)
            _encode(v, out)
    else:
        raise TypeError(f"cbor: unsupported type {type(obj).__name__}")


class _Decoder:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("cbor: truncated input")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def _length(self, info: int) -> int:
        if info < 24:
            return info
        if info == 24:
            return self._take(1)[0]
        if info == 25:
            return int.from_bytes(self._take(2), "big")
        if info == 26:
            return int.from_bytes(self._take(4), "big")
        if info == 27:
            return int.from_bytes(self._take(8), "big")
        raise ValueError(f"cbor: indefinite/reserved length {info}")

    def decode(self):
        ib = self._take(1)[0]
        major, info = ib >> 5, ib & 0x1F
        if major == _MAJOR_UINT:
            return self._length(info)
        if major == _MAJOR_NEGINT:
            return -1 - self._length(info)
        if major == _MAJOR_BYTES:
            return bytes(self._take(self._length(info)))
        if major == _MAJOR_TEXT:
            return self._take(self._length(info)).decode("utf-8")
        if major == _MAJOR_ARRAY:
            return [self.decode() for _ in range(self._length(info))]
        if major == _MAJOR_MAP:
            return {self.decode(): self.decode()
                    for _ in range(self._length(info))}
        if major == 7:
            if ib == _SIMPLE_NULL[0]:
                return None
            if ib == _SIMPLE_TRUE[0]:
                return True
            if ib == _SIMPLE_FALSE[0]:
                return False
            if ib == _FLOAT64[0]:
                return struct.unpack(">d", self._take(8))[0]
        raise ValueError(f"cbor: unsupported item 0x{ib:02x}")


def loads(data: bytes):
    native = _native_loads(data)
    if native is not None:
        return native[0]
    dec = _Decoder(data)
    obj = dec.decode()
    if dec.pos != len(data):
        raise ValueError("cbor: trailing bytes")
    return obj
