"""Generic dataclass <-> JSON codec + kind registry (the runtime.Scheme).

Reference: staging/src/k8s.io/apimachinery/pkg/runtime (Scheme, serializers).
Go serializes via generated deepcopy/marshal code per type; here one
reflective codec covers every API dataclass, with a kind registry playing the
Scheme's GVK role. Wire format keys are the python field names (our API IS
the python object model; HTTP clients are in-tree).
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, get_args, get_origin, get_type_hints

_KINDS: dict[str, type] = {}
_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def register_kind(cls: type) -> type:
    _KINDS[cls.kind] = cls  # type: ignore[attr-defined]
    return cls


def kind_class(kind: str) -> type:
    if kind not in _KINDS:
        _register_all()
    return _KINDS[kind]


def _register_all() -> None:
    """Populate the registry from the api modules (runtime.Scheme builders)."""
    from . import (
        certificates,
        coordination,
        dra,
        events,
        extensions,
        rbac,
        registration,
        storage,
        types,
        workloads,
    )

    for mod in (types, storage, dra, coordination, workloads, rbac,
                extensions, events, registration, certificates):
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type) and hasattr(obj, "kind") and dataclasses.is_dataclass(obj):
                _KINDS.setdefault(obj.kind, obj)


# Fields whose element type can't be read off the annotation (bare `tuple`).
_FIELD_ELEM_HINTS: dict[tuple[str, str], str] = {
    ("PodSpec", "volumes"): "api.storage:Volume",
    ("PodSpec", "resource_claims"): "api.dra:PodResourceClaim",
}


def _elem_hint(cls: type, field: str):
    key = (cls.__name__, field)
    spec = _FIELD_ELEM_HINTS.get(key)
    if spec is None:
        return None
    mod_path, _, name = spec.partition(":")
    import importlib

    mod = importlib.import_module(f"kubernetes_tpu.{mod_path.replace(':', '.')}")
    return getattr(mod, name)


def encode(obj: Any) -> Any:
    """Object -> JSON-compatible structure."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        if hasattr(obj, "kind"):
            out["kind"] = obj.kind
        for f in dataclasses.fields(obj):
            out[f.name] = encode(getattr(obj, f.name))
        return out
    if isinstance(obj, dict):
        return {str(k): encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    if isinstance(obj, frozenset):
        return sorted(encode(v) for v in obj)
    if hasattr(obj, "numerator") and hasattr(obj, "denominator") and not isinstance(obj, (int, bool)):
        # Fraction quantities round-trip as strings
        return str(obj)
    return obj


def _hints(cls: type) -> dict[str, Any]:
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = _HINTS_CACHE[cls] = get_type_hints(cls)
    return hints


def _strip_optional(tp):
    import types as _types

    # typing.Optional[X] and PEP-604 `X | None` have different origins
    if get_origin(tp) in (typing.Union, _types.UnionType):
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def decode(data: Any, cls: type | None = None, _field_of: tuple | None = None) -> Any:
    """JSON structure -> object of `cls` (or registry lookup via 'kind')."""
    if data is None:
        return None
    if cls is None:
        if isinstance(data, dict) and "kind" in data:
            cls = kind_class(data["kind"])
        else:
            return data
    cls = _strip_optional(cls)
    origin = get_origin(cls)
    if origin in (list, tuple):
        args = get_args(cls)
        elem = args[0] if args and args[0] is not Ellipsis else None
        items = [decode(v, elem) for v in data]
        return tuple(items) if origin is tuple else items
    if origin is dict:
        return dict(data)
    if cls is tuple:
        elem = _elem_hint(*_field_of) if _field_of else None
        return tuple(decode(v, elem) for v in data)
    if dataclasses.is_dataclass(cls):
        kwargs = {}
        hints = _hints(cls)
        field_names = {f.name for f in dataclasses.fields(cls)}
        for name, value in data.items():
            # the type-tag "kind" is not a dataclass field on API objects;
            # OwnerReference legitimately HAS a `kind` field — the field-name
            # check distinguishes the two
            if name not in field_names:
                continue
            kwargs[name] = decode(value, hints.get(name), _field_of=(cls, name))
        return cls(**kwargs)
    if cls in (int, float, str, bool):
        return cls(data) if not isinstance(data, cls) else data
    return data
