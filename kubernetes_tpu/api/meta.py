"""Object metadata — the subset of metav1.ObjectMeta the framework uses.

Reference: staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import Any

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class OwnerReference:
    kind: str
    name: str
    uid: str
    controller: bool = False


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    generation: int = 0
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: float | None = None
    owner_references: list[OwnerReference] = field(default_factory=list)
    # server-side apply field ownership (metadata.managedFields): entries
    # {"manager", "operation", "fields": [dotted paths]} maintained by
    # apiserver/apply.py
    managed_fields: list[dict] = field(default_factory=list)

    @property
    def key(self) -> str:
        """namespace/name cache key (client-go cache.MetaNamespaceKeyFunc)."""
        return f"{self.namespace}/{self.name}" if self.namespace else self.name

    def copy(self) -> "ObjectMeta":
        return replace(
            self,
            labels=dict(self.labels),
            annotations=dict(self.annotations),
            owner_references=list(self.owner_references),
            managed_fields=[dict(e) for e in self.managed_fields],
        )


def obj_key(obj: Any) -> str:
    """namespace/name key of any API object with .meta."""
    return obj.meta.key
