"""Label selectors and requirements.

Reference: staging/src/k8s.io/apimachinery/pkg/labels (Selector, Requirement)
and meta/v1 LabelSelector. Operators: In, NotIn, Exists, DoesNotExist, Gt, Lt.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Sequence

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"


@dataclass(frozen=True)
class Requirement:
    key: str
    operator: str
    values: tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.operator == EXISTS:
            return has
        if self.operator == DOES_NOT_EXIST:
            return not has
        if self.operator == IN:
            return has and labels[self.key] in self.values
        if self.operator == NOT_IN:
            # meta/v1 LabelSelector semantics: key must exist and value not in set
            # (matches LabelSelectorAsSelector conversion).
            return has and labels[self.key] not in self.values
        if self.operator in (GT, LT):
            if not has:
                return False
            try:
                lhs = int(labels[self.key])
                rhs = int(self.values[0])
            except (ValueError, IndexError):
                return False
            return lhs > rhs if self.operator == GT else lhs < rhs
        raise ValueError(f"unknown operator {self.operator!r}")


@dataclass(frozen=True)
class LabelSelector:
    """meta/v1 LabelSelector: AND of match_labels and match_expressions.

    A None selector matches nothing; an empty selector matches everything
    (mirrors LabelSelectorAsSelector).
    """

    match_labels: tuple[tuple[str, str], ...] = ()
    match_expressions: tuple[Requirement, ...] = ()

    @classmethod
    def of(
        cls,
        match_labels: Mapping[str, str] | None = None,
        match_expressions: Sequence[Requirement] = (),
    ) -> "LabelSelector":
        return cls(
            tuple(sorted((match_labels or {}).items())),
            tuple(match_expressions),
        )

    def matches(self, labels: Mapping[str, str]) -> bool:
        for k, v in self.match_labels:
            if labels.get(k) != v:
                return False
        return all(r.matches(labels) for r in self.match_expressions)

    @property
    def empty(self) -> bool:
        return not self.match_labels and not self.match_expressions

    def canonical(self) -> str:
        """Stable string form — used for pod signatures and spread-constraint
        interning (reference: labels.Selector.String())."""
        parts = [f"{k}={v}" for k, v in self.match_labels]
        for r in self.match_expressions:
            parts.append(f"{r.key} {r.operator} ({','.join(sorted(r.values))})")
        return ",".join(parts)


def labels_subset(selector: Mapping[str, str],
                  labels: Mapping[str, str]) -> bool:
    """match_labels semantics: every selector pair present in labels
    (shared by the controllers that select pods by a plain label dict)."""
    return all(labels.get(k) == v for k, v in selector.items())


def matches_selector(sel: LabelSelector | None, labels: Mapping[str, str]) -> bool:
    if sel is None:
        return False
    return sel.matches(labels)


def format_labels(labels: Mapping[str, str]) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
