"""Dynamic Resource Allocation (DRA) API types: ResourceClaim, ResourceSlice,
DeviceClass.

Reference: staging/src/k8s.io/api/resource/v1/types.go (ResourceClaim,
ResourceSlice, DeviceClass with structured parameters) — the device-claim
model behind pkg/scheduler/framework/plugins/dynamicresources/.

Device selectors come in two equivalent forms: typed attribute requirements
(kernel-friendly, the fast path) and CEL expressions over the `device`
variable (the reference's API shape, resource/v1 DeviceSelector.CEL —
evaluated by utils/cel.py's subset compiler)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping

from .meta import ObjectMeta


@dataclass(frozen=True)
class DeviceSelector:
    """One predicate on a device: either a typed attribute requirement
    (key/operator/values — In, NotIn, Exists, DoesNotExist, Gt, Lt) or a
    CEL expression (resource/v1 DeviceSelector.CEL.Expression) evaluated
    against the whole device context."""

    key: str = ""
    operator: str = "Exists"
    values: tuple[str, ...] = ()
    cel: str = ""  # when set, the expression IS the predicate

    def matches(self, attributes: Mapping[str, object], *,
                capacity: Mapping[str, object] | None = None,
                driver: str = "", name: str = "") -> bool:
        if self.cel:
            from ..utils.cel import evaluate_device

            return evaluate_device(self.cel, driver=driver, name=name,
                                   attributes=attributes, capacity=capacity)
        present = self.key in attributes
        val = attributes.get(self.key)
        if self.operator == "Exists":
            return present
        if self.operator == "DoesNotExist":
            return not present
        if self.operator == "In":
            return present and str(val) in self.values
        if self.operator == "NotIn":
            return not present or str(val) not in self.values
        if self.operator in ("Gt", "Lt"):
            if not present or not self.values:
                return False
            try:
                lhs, rhs = int(str(val)), int(self.values[0])
            except ValueError:
                return False
            return lhs > rhs if self.operator == "Gt" else lhs < rhs
        return False


# taint effects shared with node taints (api/types.py is the source)
from .types import NO_EXECUTE, NO_SCHEDULE  # noqa: E402,F401


@dataclass(frozen=True)
class DeviceTaint:
    """A taint on one device (KEP-5055 device taints,
    resource/v1 DeviceTaint + pkg/controller/devicetainteviction):
    NoSchedule keeps new allocations off the device; NoExecute
    additionally evicts pods whose claims hold it."""

    key: str
    value: str = ""
    effect: str = NO_SCHEDULE  # NoSchedule | NoExecute


@dataclass(frozen=True)
class DeviceToleration:
    """resource/v1 DeviceToleration: lets a claim's request accept
    matching device taints (Exists ignores the value; Equal compares)."""

    key: str = ""  # "" + Exists tolerates everything
    operator: str = "Exists"  # Exists | Equal
    value: str = ""
    effect: str = ""  # "" matches every effect

    def tolerates(self, taint: DeviceTaint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if not self.key:
            return self.operator == "Exists"
        if self.key != taint.key:
            return False
        return self.operator == "Exists" or self.value == taint.value


def untolerated_taints(taints, tolerations,
                       effects=(NO_SCHEDULE, NO_EXECUTE)):
    """The device taints (of the given effects) no toleration covers —
    non-empty blocks allocation (and NoExecute evicts)."""
    return [
        t for t in taints
        if t.effect in effects
        and not any(tol.tolerates(t) for tol in tolerations)
    ]


@dataclass(frozen=True)
class Device:
    """One allocatable device in a ResourceSlice (resource/v1 BasicDevice).

    consumes_counters makes the device a PARTITION of a physical device
    (KEP-4815 partitionable devices): counter-set name → {counter →
    amount} drawn from the slice's shared_counters; partitions of one
    physical device can only be allocated while the shared budget holds."""

    name: str
    attributes: Mapping[str, object] = field(default_factory=dict)
    capacity: Mapping[str, int] = field(default_factory=dict)
    consumes_counters: Mapping[str, Mapping[str, int]] = field(
        default_factory=dict
    )
    taints: tuple[DeviceTaint, ...] = ()


@dataclass
class ResourceSlice:
    """Per-(node, driver, pool) device inventory published by a DRA driver
    (resource/v1 ResourceSlice). node_name == "" means network-attached
    devices available to every node (all_nodes). shared_counters:
    counter-set name → {counter → capacity} budgeting the slice's
    partitionable devices (KEP-4815 CounterSet)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    node_name: str = ""
    driver: str = ""
    pool: str = "default"
    devices: tuple[Device, ...] = ()
    all_nodes: bool = False
    shared_counters: Mapping[str, Mapping[str, int]] = field(
        default_factory=dict
    )

    kind = "ResourceSlice"


@dataclass
class DeviceClass:
    """Admin-defined device category (resource/v1 DeviceClass): a driver
    plus common selectors every claim of this class inherits."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    driver: str = ""
    selectors: tuple[DeviceSelector, ...] = ()

    kind = "DeviceClass"


@dataclass(frozen=True)
class DeviceSubRequest:
    """One alternative inside a prioritized-list request (resource/v1
    DeviceSubRequest, KEP-4816)."""

    name: str
    device_class_name: str = ""
    selectors: tuple[DeviceSelector, ...] = ()
    count: int = 1
    tolerations: tuple[DeviceToleration, ...] = ()


@dataclass(frozen=True)
class DeviceRequest:
    """One device request inside a claim (resource/v1 DeviceRequest).

    Either the flat fields describe exactly one shape, or
    `first_available` lists alternatives tried IN ORDER — the first
    satisfiable subrequest wins (the prioritized-list feature: "give me an
    H100, else any GPU"). When `first_available` is set the flat fields
    are ignored (the reference's oneOf exactly/firstAvailable)."""

    name: str
    device_class_name: str = ""
    selectors: tuple[DeviceSelector, ...] = ()
    count: int = 1
    first_available: tuple["DeviceSubRequest", ...] = ()
    tolerations: tuple[DeviceToleration, ...] = ()


@dataclass
class ResourceClaimSpec:
    requests: tuple[DeviceRequest, ...] = ()


@dataclass(frozen=True)
class DeviceAllocationResult:
    """One allocated device (resource/v1 DeviceRequestAllocationResult)."""

    request: str
    driver: str
    pool: str
    device: str


@dataclass
class AllocationResult:
    devices: tuple[DeviceAllocationResult, ...] = ()
    node_name: str = ""  # node the allocation is bound to ("" = any node)


@dataclass
class ResourceClaimStatus:
    allocation: AllocationResult | None = None
    reserved_for: tuple[str, ...] = ()  # pod keys (resource/v1 max 256)


@dataclass
class ResourceClaim:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceClaimSpec = field(default_factory=ResourceClaimSpec)
    status: ResourceClaimStatus = field(default_factory=ResourceClaimStatus)

    kind = "ResourceClaim"

    @property
    def is_allocated(self) -> bool:
        return self.status.allocation is not None


@dataclass(frozen=True)
class PodResourceClaim:
    """pod.spec.resourceClaims entry: a pod-local name mapping to a
    ResourceClaim object in the pod's namespace."""

    name: str
    resource_claim_name: str


RESERVED_FOR_MAX = 256  # resource/v1 ResourceClaimReservedForMaxSize


def pod_resource_claim_keys(pod) -> list[str]:
    """Store keys of all ResourceClaims the pod references."""
    return [
        f"{pod.meta.namespace}/{rc.resource_claim_name}"
        for rc in pod.spec.resource_claims
    ]
