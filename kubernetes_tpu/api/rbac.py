"""RBAC API types: rbac.authorization.k8s.io/v1 subset.

Reference: staging/src/k8s.io/api/rbac/v1/types.go — Role/ClusterRole carry
PolicyRules (verbs × resources, '*' wildcards); bindings attach them to
subjects (users/groups/service accounts). Namespaced Roles grant only within
their namespace; ClusterRoles grant everywhere (including via RoleBinding,
which scopes a ClusterRole's rules down to the binding's namespace).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta

WILDCARD = "*"


@dataclass(frozen=True)
class PolicyRule:
    """verbs × resources this rule allows (rbac/v1/types.go PolicyRule)."""

    verbs: tuple[str, ...] = ()
    resources: tuple[str, ...] = ()

    def matches(self, verb: str, resource: str) -> bool:
        return ((WILDCARD in self.verbs or verb in self.verbs)
                and (WILDCARD in self.resources or resource in self.resources))


def service_account_username(namespace: str, name: str) -> str:
    """system:serviceaccount:<ns>:<name> (serviceaccount.MakeUsername) —
    the ONE place the identity format lives (Subject.matches and the token
    issuer both derive from it)."""
    return f"system:serviceaccount:{namespace}:{name}"


@dataclass
class ServiceAccount:
    """core/v1 ServiceAccount: the in-cluster workload identity
    (pkg/apis/core types.go ServiceAccount). Token issuance lives in the
    apiserver's TokenRequest subresource (apiserver/auth.py
    ServiceAccountIssuer)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)

    kind = "ServiceAccount"


@dataclass(frozen=True)
class Subject:
    """User / Group / ServiceAccount reference."""

    kind: str  # "User" | "Group" | "ServiceAccount"
    name: str
    namespace: str = ""

    def matches(self, user) -> bool:
        if self.kind == "User":
            return self.name == user.name
        if self.kind == "Group":
            return self.name in user.groups
        if self.kind == "ServiceAccount":
            return user.name == service_account_username(
                self.namespace, self.name
            )
        return False


@dataclass(frozen=True)
class RoleRef:
    kind: str  # "Role" | "ClusterRole"
    name: str


@dataclass
class Role:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    rules: tuple[PolicyRule, ...] = ()

    kind = "Role"


@dataclass
class ClusterRole:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    rules: tuple[PolicyRule, ...] = ()

    kind = "ClusterRole"


@dataclass
class RoleBinding:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: tuple[Subject, ...] = ()
    role_ref: RoleRef = field(default_factory=lambda: RoleRef("Role", ""))

    kind = "RoleBinding"


@dataclass
class ClusterRoleBinding:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: tuple[Subject, ...] = ()
    role_ref: RoleRef = field(default_factory=lambda: RoleRef("ClusterRole", ""))

    kind = "ClusterRoleBinding"
