"""Core API object types: Pod, Node, PodGroup, Binding and their sub-structs.

Reference: staging/src/k8s.io/api/core/v1/types.go (Pod at :4604, Node, Taint,
Toleration, Affinity, TopologySpreadConstraint) and
staging/src/k8s.io/api/scheduling/v1alpha2/types.go (PodGroup :191).
Only the scheduling-relevant subset is modeled; everything is a plain
dataclass, treated as immutable once written to the store.
"""

from __future__ import annotations

import copy as copy_mod
from dataclasses import dataclass, field
from typing import Mapping

from .labels import LabelSelector, Requirement
from .meta import ObjectMeta

# --- scheduling constants -------------------------------------------------

MAX_NODE_SCORE = 100  # staging/.../framework/interface.go MaxNodeScore
MIN_NODE_SCORE = 0
MAX_TOTAL_SCORE = (1 << 63) - 1

# Taint effects
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

# Pod phases
PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"

# TopologySpread whenUnsatisfiable
DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"

DEFAULT_SCHEDULER_NAME = "default-scheduler"


# --- node selectors / affinity -------------------------------------------


@dataclass(frozen=True)
class NodeSelectorRequirement:
    """Same operators as labels.Requirement; kept distinct because node-selector
    requirements support Gt/Lt and match node *fields* in the reference."""

    key: str
    operator: str
    values: tuple[str, ...] = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        return Requirement(self.key, self.operator, tuple(self.values)).matches(labels)


@dataclass(frozen=True)
class NodeSelectorTerm:
    match_expressions: tuple[NodeSelectorRequirement, ...] = ()
    match_fields: tuple[NodeSelectorRequirement, ...] = ()

    def matches(self, node_labels: Mapping[str, str], node_fields: Mapping[str, str]) -> bool:
        return all(r.matches(node_labels) for r in self.match_expressions) and all(
            r.matches(node_fields) for r in self.match_fields
        )


@dataclass(frozen=True)
class NodeSelector:
    """OR of terms (each term an AND). Empty term list matches nothing
    (reference: nodeaffinity.NewNodeSelector)."""

    terms: tuple[NodeSelectorTerm, ...] = ()

    def matches(self, node_labels: Mapping[str, str], node_fields: Mapping[str, str]) -> bool:
        return any(t.matches(node_labels, node_fields) for t in self.terms)


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int
    preference: NodeSelectorTerm


@dataclass(frozen=True)
class NodeAffinity:
    required: NodeSelector | None = None
    preferred: tuple[PreferredSchedulingTerm, ...] = ()


@dataclass(frozen=True)
class PodAffinityTerm:
    label_selector: LabelSelector | None = None
    topology_key: str = ""
    namespaces: tuple[str, ...] = ()  # empty -> pod's own namespace


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int
    term: PodAffinityTerm


@dataclass(frozen=True)
class PodAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class PodAntiAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class Affinity:
    node_affinity: NodeAffinity | None = None
    pod_affinity: PodAffinity | None = None
    pod_anti_affinity: PodAntiAffinity | None = None


# --- taints / tolerations -------------------------------------------------


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: str = NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    key: str = ""  # empty key + Exists tolerates everything
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty -> all effects
    toleration_seconds: int | None = None

    def tolerates(self, taint: Taint) -> bool:
        """Reference: component-helpers/scheduling/corev1 Toleration.ToleratesTaint."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


# --- topology spread ------------------------------------------------------


@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str  # DoNotSchedule | ScheduleAnyway
    label_selector: LabelSelector | None = None
    min_domains: int | None = None


# --- containers / pod -----------------------------------------------------


@dataclass(frozen=True)
class ContainerPort:
    container_port: int
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass(frozen=True)
class KeyRef:
    """configMapKeyRef / secretKeyRef: one key of a named config object."""

    name: str
    key: str
    optional: bool = False


@dataclass(frozen=True)
class EnvVar:
    """core/v1 EnvVar: literal value, or a reference resolved by the
    kubelet at container start (missing non-optional refs block the start
    with CreateContainerConfigError)."""

    name: str
    value: str = ""
    config_map_key_ref: KeyRef | None = None
    secret_key_ref: KeyRef | None = None


@dataclass(frozen=True)
class Probe:
    """core/v1 Probe subset: cadence + thresholds. The probe ACTION
    (exec/http/tcp) is the node agent's prober hook — spec carries only
    the policy, as the scheduler/controllers never look inside actions."""

    period_s: float = 10.0
    initial_delay_s: float = 0.0
    failure_threshold: int = 3
    success_threshold: int = 1


@dataclass
class Container:
    name: str = "c"
    image: str = ""
    requests: dict[str, object] = field(default_factory=dict)
    limits: dict[str, object] = field(default_factory=dict)
    ports: tuple[ContainerPort, ...] = ()
    liveness_probe: Probe | None = None
    readiness_probe: Probe | None = None
    env: tuple[EnvVar, ...] = ()


@dataclass(frozen=True)
class SchedulingGroup:
    """Gang membership (fork feature GenericWorkload).

    Reference: staging/src/k8s.io/api/core/v1/types.go:4488 — pod.Spec points
    at a PodGroup by name; all members share it.
    """

    pod_group_name: str


@dataclass
class PodSpec:
    node_name: str = ""
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    volumes: tuple = ()  # tuple[storage.Volume, ...]
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    overhead: dict[str, object] = field(default_factory=dict)
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Affinity | None = None
    tolerations: tuple[Toleration, ...] = ()
    topology_spread_constraints: tuple[TopologySpreadConstraint, ...] = ()
    priority: int = 0
    priority_class_name: str = ""
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    scheduling_gates: tuple[str, ...] = ()
    scheduling_group: SchedulingGroup | None = None
    resource_claims: tuple = ()  # tuple[dra.PodResourceClaim, ...]
    host_network: bool = False
    termination_grace_period_seconds: int = 30
    restart_policy: str = "Always"
    # kubelet fails the pod this many seconds after it starts Running
    # (kubelet_pods.go activeDeadlineHandler); None = no deadline
    active_deadline_seconds: int | None = None
    # in-cluster identity (core/v1 serviceAccountName); defaulted to
    # "default" by the serviceaccount admission plugin
    service_account_name: str = ""


@dataclass
class PodCondition:
    type: str  # "PodScheduled", ...
    status: str  # "True"/"False"/"Unknown"
    reason: str = ""
    message: str = ""


@dataclass
class PodStatus:
    phase: str = PENDING
    conditions: list[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""
    start_time: float | None = None
    pod_ip: str = ""  # set by the kubelet once the sandbox has a network


@dataclass
class Pod:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    kind = "Pod"

    @property
    def is_scheduled(self) -> bool:
        return bool(self.spec.node_name)

    @property
    def is_terminating(self) -> bool:
        return self.meta.deletion_timestamp is not None


# --- node -----------------------------------------------------------------


@dataclass(frozen=True)
class ContainerImage:
    names: tuple[str, ...]
    size_bytes: int


@dataclass
class NodeSpec:
    unschedulable: bool = False
    taints: tuple[Taint, ...] = ()
    pod_cidr: str = ""


@dataclass
class NodeCondition:
    type: str  # "Ready", ...
    status: str = "True"


@dataclass
class NodeStatus:
    capacity: dict[str, object] = field(default_factory=dict)
    allocatable: dict[str, object] = field(default_factory=dict)
    conditions: list[NodeCondition] = field(default_factory=list)
    images: list[ContainerImage] = field(default_factory=list)
    declared_features: tuple[str, ...] = ()
    # daemonEndpoints.kubeletEndpoint.Port: where this node's kubelet
    # serves /containerLogs etc. (the apiserver's log proxy dials it)
    daemon_endpoint_port: int = 0


@dataclass
class Node:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    kind = "Node"


# --- pod group (gang) -----------------------------------------------------


@dataclass(frozen=True)
class GangPolicy:
    min_count: int = 0


@dataclass(frozen=True)
class TopologyConstraint:
    key: str
    mode: str = "Required"  # Required | Preferred


@dataclass(frozen=True)
class SchedulingConstraints:
    topology: tuple[TopologyConstraint, ...] = ()


@dataclass
class PodGroupSpec:
    policy: GangPolicy = field(default_factory=GangPolicy)
    constraints: SchedulingConstraints = field(default_factory=SchedulingConstraints)


@dataclass
class PodGroupStatus:
    all_pods_count: int = 0
    scheduled_pods_count: int = 0


@dataclass
class PodGroup:
    """Reference: staging/src/k8s.io/api/scheduling/v1alpha2/types.go:191."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)

    kind = "PodGroup"


# --- disruption budgets ---------------------------------------------------


@dataclass
class PriorityClass:
    """scheduling.k8s.io/v1 PriorityClass: a named priority value resolved
    onto pods at admission (the reference's priority admission plugin,
    plugin/pkg/admission/priority). Cluster-scoped."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    preemption_policy: str = "PreemptLowerPriority"
    description: str = ""

    kind = "PriorityClass"


@dataclass
class PodDisruptionBudgetSpec:
    """policy/v1 PodDisruptionBudgetSpec (scheduling-relevant subset).

    Exactly one of min_available / max_unavailable is meaningful; both are
    absolute counts (the reference also accepts percentages — resolved by
    the disruption controller before the scheduler ever reads them, so the
    scheduler-side contract is identical)."""

    selector: LabelSelector | None = None  # None matches nothing
    min_available: int | None = None
    max_unavailable: int | None = None


@dataclass
class PodDisruptionBudgetStatus:
    """policy/v1 PodDisruptionBudgetStatus — the scheduler reads ONLY
    disruptions_allowed + disrupted_pods (default_preemption.go:380
    filterPodsWithPDBViolation)."""

    disruptions_allowed: int = 0
    current_healthy: int = 0
    desired_healthy: int = 0
    expected_pods: int = 0
    # pod name -> eviction time; already-processed disruptions don't count
    # against the budget again
    disrupted_pods: dict = field(default_factory=dict)


@dataclass
class PodDisruptionBudget:
    """Reference: staging/src/k8s.io/api/policy/v1/types.go."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodDisruptionBudgetSpec = field(default_factory=PodDisruptionBudgetSpec)
    status: PodDisruptionBudgetStatus = field(default_factory=PodDisruptionBudgetStatus)

    kind = "PodDisruptionBudget"


# --- binding --------------------------------------------------------------


@dataclass
class Binding:
    """POST pods/<name>/binding payload (reference: core/v1 Binding)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    target_node: str = ""

    kind = "Binding"


# --- fast deepcopy hooks --------------------------------------------------
#
# The store's etcd-style isolation deepcopies objects on every write and
# event emit (store/store.py); generic copy.deepcopy recurses ~100 frames
# per Pod and dominated the full-pipeline profile. These hooks keep the
# exact copy semantics while sharing the immutable fragments: every
# frozen dataclass here holds only str/int/tuples of frozen values, so
# returning self is a correct deepcopy (the client-go convention — spec
# fragments are never mutated in place, new values replace them).


def _identity_deepcopy(self, memo):
    return self


for _frozen in (
    NodeSelectorRequirement, NodeSelectorTerm, NodeSelector,
    PreferredSchedulingTerm, NodeAffinity, PodAffinityTerm,
    WeightedPodAffinityTerm, PodAffinity, PodAntiAffinity, Affinity,
    Taint, Toleration, TopologySpreadConstraint, ContainerPort,
    SchedulingGroup, ContainerImage, GangPolicy, TopologyConstraint,
    SchedulingConstraints, Probe, EnvVar, KeyRef,
):
    _frozen.__deepcopy__ = _identity_deepcopy  # type: ignore[attr-defined]


def _container_deepcopy(self: Container, memo) -> Container:
    # probes/env are frozen → shareable; keep this hook in sync with the
    # Container field list (a dropped field silently truncates every
    # object that passes through the store)
    return Container(self.name, self.image, dict(self.requests),
                     dict(self.limits), self.ports,
                     self.liveness_probe, self.readiness_probe, self.env)


def _podspec_deepcopy(self: PodSpec, memo) -> PodSpec:
    s = copy_mod.copy(self)  # shallow: immutable/str fields carried over
    s.containers = [_container_deepcopy(c, memo) for c in self.containers]
    s.init_containers = [_container_deepcopy(c, memo) for c in self.init_containers]
    s.overhead = dict(self.overhead)
    s.node_selector = dict(self.node_selector)
    return s


def _podstatus_deepcopy(self: PodStatus, memo) -> PodStatus:
    s = copy_mod.copy(self)
    s.conditions = [copy_mod.copy(c) for c in self.conditions]
    return s


def _pod_deepcopy(self: Pod, memo) -> Pod:
    return Pod(meta=self.meta.copy(),
               spec=_podspec_deepcopy(self.spec, memo),
               status=_podstatus_deepcopy(self.status, memo))


def _nodestatus_deepcopy(self: NodeStatus, memo) -> NodeStatus:
    s = copy_mod.copy(self)
    s.capacity = dict(self.capacity)
    s.allocatable = dict(self.allocatable)
    s.conditions = [copy_mod.copy(c) for c in self.conditions]
    s.images = list(self.images)  # ContainerImage is frozen: share entries
    return s


def _node_deepcopy(self: Node, memo) -> Node:
    return Node(meta=self.meta.copy(),
                spec=copy_mod.copy(self.spec),  # taints tuple shared (frozen)
                status=_nodestatus_deepcopy(self.status, memo))


Container.__deepcopy__ = _container_deepcopy  # type: ignore[attr-defined]
PodSpec.__deepcopy__ = _podspec_deepcopy  # type: ignore[attr-defined]
PodStatus.__deepcopy__ = _podstatus_deepcopy  # type: ignore[attr-defined]
Pod.__deepcopy__ = _pod_deepcopy  # type: ignore[attr-defined]
NodeStatus.__deepcopy__ = _nodestatus_deepcopy  # type: ignore[attr-defined]
Node.__deepcopy__ = _node_deepcopy  # type: ignore[attr-defined]
