"""CustomResourceDefinitions: user-defined kinds served like built-ins.

Reference: staging/src/k8s.io/apiextensions-apiserver — the third server in
the reference's delegation chain (cmd/kube-apiserver/app/server.go:176).
There, creating a CustomResourceDefinition object dynamically installs REST
storage for the named kind; instances are unstructured objects validated
against a structural OpenAPI v3 schema, and flow through storage, watch,
informers and kubectl exactly like compiled-in kinds.

Here the same effect comes from the runtime registry (`runtime.Scheme`
analogue, api/serialization._KINDS): `register_custom_kind(crd)` mints a
dynamic CustomObject subclass whose `kind` is the CRD's, registers it, and
from then on decode/encode/store/watch/informers/kubectl all handle it with
zero special cases. Validation (a structural-schema subset: type,
properties, required, enum, minimum/maximum, items, pattern) runs in the
apiserver's admission chain (apiserver/admission.py crd_admission).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from .meta import ObjectMeta


@dataclass
class CRDNames:
    """spec.names subset (apiextensions/v1 CustomResourceDefinitionNames)."""

    kind: str = ""
    plural: str = ""  # defaulted to lowercase(kind) + "s"

    def defaulted_plural(self) -> str:
        return self.plural or (self.kind.lower() + "s")


@dataclass
class CRDSpec:
    """apiextensions/v1 CustomResourceDefinitionSpec subset: one served
    version, a structural schema for `spec` (+ optional top-level fields)."""

    names: CRDNames = field(default_factory=CRDNames)
    group: str = "custom.example"
    scope: str = "Namespaced"  # "Namespaced" | "Cluster"
    # JSON-Schema subset applied to the instance's `spec` dict
    schema: dict = field(default_factory=dict)


@dataclass
class CustomResourceDefinition:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CRDSpec = field(default_factory=CRDSpec)
    # "Established" once registered and ready to serve (the apiextensions
    # Established condition)
    status: dict = field(default_factory=dict)

    kind = "CustomResourceDefinition"


@dataclass
class CustomObject:
    """The unstructured instance type every registered CRD kind shares.

    Per-CRD subclasses minted by register_custom_kind override the class
    `kind`, so the reflective codec, the store's _kind_of, informers, and
    kubectl treat instances exactly like compiled-in dataclasses. `spec`
    and `status` are free-form dicts (apiextensions unstructured.Unstructured).
    """

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)

    kind = "CustomObject"


@dataclass
class WebhookRule:
    """admissionregistration/v1 RuleWithOperations subset."""

    operations: tuple[str, ...] = ("CREATE", "UPDATE")
    kinds: tuple[str, ...] = ("*",)

    def matches(self, operation: str, kind: str) -> bool:
        return (("*" in self.operations or operation in self.operations)
                and ("*" in self.kinds or kind in self.kinds))


@dataclass
class ValidatingWebhook:
    """admissionregistration/v1 ValidatingWebhook subset: clientConfig.url
    only (no CA bundle — plain HTTP to in-cluster endpoints here)."""

    name: str = ""
    url: str = ""
    rules: tuple[WebhookRule, ...] = ()
    failure_policy: str = "Fail"  # "Fail" | "Ignore"
    timeout_s: float = 5.0


@dataclass
class ValidatingWebhookConfiguration:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: tuple[ValidatingWebhook, ...] = ()

    kind = "ValidatingWebhookConfiguration"


@dataclass
class MutatingWebhook:
    """admissionregistration/v1 MutatingWebhook subset. The webhook's
    AdmissionReview response may carry `patchType: "JSONPatch"` with a
    base64 RFC 6902 patch (add/replace/remove), applied to the object's
    wire form before the validating phase sees it."""

    name: str = ""
    url: str = ""
    rules: tuple[WebhookRule, ...] = ()
    failure_policy: str = "Fail"  # "Fail" | "Ignore"
    timeout_s: float = 5.0


@dataclass
class MutatingWebhookConfiguration:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    webhooks: tuple[MutatingWebhook, ...] = ()

    kind = "MutatingWebhookConfiguration"


# -- ValidatingAdmissionPolicy (admissionregistration/v1, CEL) --------------


@dataclass
class Validation:
    """admissionregistration/v1 Validation: one CEL expression over
    `object` / `oldObject` / `request`; false (or an evaluation error under
    failurePolicy=Fail) rejects the request with `message`."""

    expression: str = ""
    message: str = ""


@dataclass
class AdmissionPolicySpec:
    """ValidatingAdmissionPolicySpec subset: matchConstraints (rules) +
    validations + failurePolicy.

    Reference: staging/src/k8s.io/apiserver/pkg/admission/plugin/policy/
    validating — expressions are compiled CEL over the declared variables;
    failurePolicy governs evaluation ERRORS (a false expression always
    rejects)."""

    match_rules: tuple[WebhookRule, ...] = ()
    validations: tuple[Validation, ...] = ()
    failure_policy: str = "Fail"  # "Fail" | "Ignore"


@dataclass
class ValidatingAdmissionPolicy:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: AdmissionPolicySpec = field(default_factory=AdmissionPolicySpec)

    kind = "ValidatingAdmissionPolicy"


@dataclass
class ValidatingAdmissionPolicyBinding:
    """A policy takes effect only where a binding names it (the reference's
    two-object model: policies are definitions, bindings scope them).
    `namespaces` narrows the binding; empty = all namespaces."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    policy_name: str = ""
    namespaces: tuple[str, ...] = ()

    kind = "ValidatingAdmissionPolicyBinding"


def apply_json_patch(doc: dict, patch: list) -> dict:
    """RFC 6902 subset (add/replace/remove) over a wire document — the
    patch dialect mutating admission webhooks return (the reference's only
    supported admission patchType, plugin/webhook/mutating).

    RFC 6902 strictness preserved: every intermediate path element must
    EXIST (no auto-vivification), `replace`/`remove` of a missing member is
    an error — a typo'd path from a webhook must fail the request, never
    silently no-op (the policy-mandated mutation would just not happen)."""
    import copy as _copy

    out = _copy.deepcopy(doc)
    if not isinstance(patch, list):
        raise ValueError("patch must be a JSON array of operations")
    for op in patch:
        if not isinstance(op, dict):
            raise ValueError(f"patch operation must be an object: {op!r}")
        action = op.get("op")
        path = op.get("path", "")
        if not path.startswith("/"):
            raise ValueError(f"invalid patch path {path!r}")
        parts = [p.replace("~1", "/").replace("~0", "~")
                 for p in path[1:].split("/")]
        node = out
        for p in parts[:-1]:
            if isinstance(node, list):
                i = int(p)
                if not (-len(node) <= i < len(node)):
                    raise ValueError(
                        f"patch path {path!r}: index {i} out of range"
                    )
                node = node[i]
            elif isinstance(node, dict):
                if p not in node:
                    raise ValueError(
                        f"patch path {path!r}: member {p!r} does not exist"
                    )
                node = node[p]
            else:
                raise ValueError(f"patch path {path!r} walks a scalar")
        leaf = parts[-1]
        if action in ("add", "replace"):
            if isinstance(node, list):
                if leaf == "-":
                    if action == "replace":
                        raise ValueError('replace at "-" is invalid')
                    node.append(op.get("value"))
                else:
                    i = int(leaf)
                    # RFC 6902: add allows index == len (append); beyond
                    # that is an error, NOT a silent clamp-insert
                    limit = len(node) + (1 if action == "add" else 0)
                    if not (0 <= i < limit):
                        raise ValueError(
                            f"{action} path {path!r}: index {i} out of range"
                        )
                    if action == "add":
                        node.insert(i, op.get("value"))
                    else:
                        node[i] = op.get("value")
            elif isinstance(node, dict):
                if action == "replace" and leaf not in node:
                    raise ValueError(
                        f"replace path {path!r}: member does not exist"
                    )
                node[leaf] = op.get("value")
            else:
                raise ValueError(f"patch path {path!r} targets a scalar")
        elif action == "remove":
            if isinstance(node, list):
                i = int(leaf)
                if not (0 <= i < len(node)):
                    raise ValueError(
                        f"remove path {path!r}: index {i} out of range"
                    )
                node.pop(i)
            elif leaf in node:
                del node[leaf]
            else:
                raise ValueError(
                    f"remove path {path!r}: member does not exist"
                )
        else:
            raise ValueError(f"unsupported patch op {action!r}")
    return out


# -- structural-schema validation (apiextensions pkg/apiserver/validation) --

_TYPE_MAP = {
    "object": dict,
    "array": (list, tuple),
    "string": str,
    "boolean": bool,
}


def validate_schema(value, schema: dict, path: str = "spec") -> list[str]:
    """Validate `value` against the structural-schema subset; returns a
    list of violation messages (empty = valid)."""
    errs: list[str] = []
    if not schema:
        return errs
    t = schema.get("type")
    if t:
        if t == "integer":
            if isinstance(value, bool) or not isinstance(value, int):
                return [f"{path}: expected integer, got {type(value).__name__}"]
        elif t == "number":
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                return [f"{path}: expected number, got {type(value).__name__}"]
        else:
            want = _TYPE_MAP.get(t)
            if want is None:
                return [f"{path}: unknown schema type {t!r}"]
            if not isinstance(value, want) or (
                t != "boolean" and isinstance(value, bool)
            ):
                return [f"{path}: expected {t}, got {type(value).__name__}"]
    if "enum" in schema and value not in schema["enum"]:
        errs.append(f"{path}: {value!r} not in enum {schema['enum']}")
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        if "minimum" in schema and value < schema["minimum"]:
            errs.append(f"{path}: {value} below minimum {schema['minimum']}")
        if "maximum" in schema and value > schema["maximum"]:
            errs.append(f"{path}: {value} above maximum {schema['maximum']}")
    if isinstance(value, str) and "pattern" in schema:
        if re.search(schema["pattern"], value) is None:
            errs.append(f"{path}: {value!r} does not match pattern "
                        f"{schema['pattern']!r}")
    if isinstance(value, dict):
        for req in schema.get("required", ()):
            if req not in value:
                errs.append(f"{path}.{req}: required field missing")
        props = schema.get("properties", {})
        for k, v in value.items():
            if k in props:
                errs.extend(validate_schema(v, props[k], f"{path}.{k}"))
    if isinstance(value, (list, tuple)) and "items" in schema:
        for i, v in enumerate(value):
            errs.extend(validate_schema(v, schema["items"], f"{path}[{i}]"))
    return errs


# -- dynamic kind registry -------------------------------------------------

_BUILTIN_GUARD: set[str] | None = None


def _builtin_kinds() -> set[str]:
    global _BUILTIN_GUARD
    if _BUILTIN_GUARD is None:
        from . import serialization

        serialization._register_all()
        _BUILTIN_GUARD = set(serialization._KINDS)
    return _BUILTIN_GUARD


def validate_custom_kind(crd: CustomResourceDefinition) -> None:
    """Name/conflict validation WITHOUT side effects — the admission
    chain's half. Registration itself must only happen after the CRD
    commits to the store (a later admission denial or store conflict must
    not leak scheme/alias/scope state)."""
    from . import serialization

    kind = crd.spec.names.kind
    if not kind or not kind[0].isupper() or not kind.isalnum():
        raise ValueError(f"invalid CRD kind name {kind!r}")
    if kind in _builtin_kinds():
        raise ValueError(f"kind {kind!r} conflicts with a built-in kind")
    existing = serialization._KINDS.get(kind)
    if existing is not None and not issubclass(existing, CustomObject):
        raise ValueError(f"kind {kind!r} already registered")


def register_custom_kind(crd: CustomResourceDefinition) -> type:
    """Install the CRD's kind into the scheme: decode/encode, store,
    watches, informers, kubectl aliases, and discovery all pick it up.
    Idempotent; raises ValueError for invalid or conflicting names."""
    from ..apiserver.discovery import CLUSTER_SCOPED
    from ..cmd.kubectl import ALIASES
    from . import serialization

    validate_custom_kind(crd)
    kind = crd.spec.names.kind
    existing = serialization._KINDS.get(kind)
    if existing is not None:
        return existing
    cls = type(kind, (CustomObject,), {"kind": kind})
    serialization._KINDS[kind] = cls
    ALIASES.setdefault(kind.lower(), kind)
    ALIASES.setdefault(crd.spec.names.defaulted_plural().lower(), kind)
    if crd.spec.scope == "Cluster":
        CLUSTER_SCOPED.add(kind)
    return cls


def unregister_custom_kind(kind: str) -> None:
    """Remove a dynamic kind from the scheme (CRD deletion)."""
    from ..apiserver.discovery import CLUSTER_SCOPED
    from ..cmd.kubectl import ALIASES
    from . import serialization

    cls = serialization._KINDS.get(kind)
    if cls is None or not issubclass(cls, CustomObject) or cls is CustomObject:
        return
    del serialization._KINDS[kind]
    CLUSTER_SCOPED.discard(kind)
    for alias, target in list(ALIASES.items()):
        if target == kind:
            del ALIASES[alias]


def registered_custom_kinds() -> list[str]:
    from . import serialization

    return sorted(
        k for k, cls in serialization._KINDS.items()
        if isinstance(cls, type) and issubclass(cls, CustomObject)
        and cls is not CustomObject
    )
