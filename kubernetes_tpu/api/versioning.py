"""Versioned API conversion: the runtime.Scheme conversion role.

Reference: apimachinery's Scheme holds versioned external types plus
conversion functions to/from the unversioned internal ("hub") types
(runtime/scheme.go Convert; generated zz_generated.conversion.go per
group/version). Components always work on internal types; the wire carries
a specific apiVersion, converted at the codec boundary.

This module is that machinery: register an external dataclass for a
(group/version, kind) with its to/from-internal converters, then
decode_versioned/encode_versioned handle wire objects whose "apiVersion"
names a registered version. Objects without apiVersion (or with "v1") pass
through the plain codec — internal and v1-external are identical here, the
same shortcut the reference takes for groups whose storage version matches.

Registered below: scheduling.k8s.io/v1alpha2 PodGroup — the reference's
actual in-flight gang API (staging/src/k8s.io/api/scheduling/v1alpha2/
types.go:191) whose external shape (minCount at spec top level,
topologyConstraints list) differs from our internal hub types.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from .meta import ObjectMeta
from .serialization import decode, encode
from .types import (
    GangPolicy,
    PodGroup,
    PodGroupSpec,
    PodGroupStatus,
    SchedulingConstraints,
    TopologyConstraint,
)


class ConversionScheme:
    def __init__(self):
        # (api_version, kind) → (external cls, to_internal, from_internal)
        self._by_version: dict[tuple[str, str],
                               tuple[type, Callable, Callable]] = {}

    def register(self, api_version: str, kind: str, external_cls: type,
                 to_internal: Callable, from_internal: Callable) -> None:
        self._by_version[(api_version, kind)] = (
            external_cls, to_internal, from_internal
        )

    def versions_for(self, kind: str) -> list[str]:
        return [v for (v, k) in self._by_version if k == kind]

    def decode_versioned(self, wire: dict):
        """Wire dict → INTERNAL object. apiVersion routes to the matching
        external type + converter; absent/"v1" uses the plain codec."""
        api_version = wire.get("apiVersion", "")
        kind = wire.get("kind", "")
        entry = self._by_version.get((api_version, kind))
        if entry is None:
            if api_version in ("", "v1"):
                return decode(wire)
            raise ValueError(f"no conversion registered for "
                             f"{api_version}/{kind}")
        external_cls, to_internal, _ = entry
        body = {k: v for k, v in wire.items() if k != "apiVersion"}
        return to_internal(decode(body, external_cls))

    def encode_versioned(self, obj, api_version: str = "") -> dict:
        """INTERNAL object → wire dict at the requested apiVersion."""
        kind = getattr(obj, "kind", "")
        entry = self._by_version.get((api_version, kind))
        if entry is None:
            if api_version in ("", "v1"):
                return encode(obj)
            raise ValueError(f"no conversion registered for "
                             f"{api_version}/{kind}")
        _, _, from_internal = entry
        out = encode(from_internal(obj))
        out["apiVersion"] = api_version
        out["kind"] = kind
        return out


# -- scheduling.k8s.io/v1alpha2 PodGroup (external shape) --------------------


@dataclass(frozen=True)
class TopologyConstraintV1alpha2:
    topologyKey: str = ""
    mode: str = "Required"


@dataclass
class PodGroupSpecV1alpha2:
    """External spec: minCount flattened to the top (the gang policy is
    implicit in v1alpha2), constraints as a bare list."""

    minCount: int = 0
    topologyConstraints: tuple[TopologyConstraintV1alpha2, ...] = ()


@dataclass
class PodGroupV1alpha2:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpecV1alpha2 = field(default_factory=PodGroupSpecV1alpha2)
    allPodsCount: int = 0
    scheduledPodsCount: int = 0

    kind = "PodGroup"


def _podgroup_to_internal(ext: PodGroupV1alpha2) -> PodGroup:
    return PodGroup(
        meta=ext.meta,
        spec=PodGroupSpec(
            policy=GangPolicy(min_count=ext.spec.minCount),
            constraints=SchedulingConstraints(topology=tuple(
                TopologyConstraint(key=t.topologyKey, mode=t.mode)
                for t in ext.spec.topologyConstraints
            )),
        ),
        status=PodGroupStatus(
            all_pods_count=ext.allPodsCount,
            scheduled_pods_count=ext.scheduledPodsCount,
        ),
    )


def _podgroup_from_internal(pg: PodGroup) -> PodGroupV1alpha2:
    return PodGroupV1alpha2(
        meta=pg.meta,
        spec=PodGroupSpecV1alpha2(
            minCount=pg.spec.policy.min_count,
            topologyConstraints=tuple(
                TopologyConstraintV1alpha2(topologyKey=t.key, mode=t.mode)
                for t in pg.spec.constraints.topology
            ),
        ),
        allPodsCount=pg.status.all_pods_count,
        scheduledPodsCount=pg.status.scheduled_pods_count,
    )


def default_scheme() -> ConversionScheme:
    scheme = ConversionScheme()
    scheme.register(
        "scheduling.k8s.io/v1alpha2", "PodGroup", PodGroupV1alpha2,
        _podgroup_to_internal, _podgroup_from_internal,
    )
    return scheme
