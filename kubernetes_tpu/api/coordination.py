"""Coordination API: Lease.

Reference: staging/src/k8s.io/api/coordination/v1/types.go — the object
behind leader election and node heartbeats.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta


@dataclass
class LeaseSpec:
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0


@dataclass
class Lease:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)

    kind = "Lease"
