"""Coordination API: Lease.

Reference: staging/src/k8s.io/api/coordination/v1/types.go — the object
behind leader election and node heartbeats.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta


@dataclass
class LeaseSpec:
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_transitions: int = 0

    def deadline(self) -> float:
        """The instant the current term expires: the holder must land a
        renew before this or any candidate may take the lease over."""
        return self.renew_time + self.lease_duration_seconds

    def expired(self, now: float) -> bool:
        """Past the holder's renewal deadline — takeover is legal."""
        return now > self.deadline()


def shard_lease_name(base: str, shard: int) -> str:
    """Per-shard coordination Lease name for the active-active scheduler
    fleet (scheduler/fleet.py): shard ownership is one Lease per shard,
    named off the configured resource name."""
    return f"{base}-shard-{shard}"


@dataclass
class Lease:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LeaseSpec = field(default_factory=LeaseSpec)

    kind = "Lease"
