"""Storage API types: PersistentVolume, PersistentVolumeClaim, StorageClass,
CSINode, and pod Volume sources.

Reference: staging/src/k8s.io/api/core/v1/types.go (PersistentVolume,
PersistentVolumeClaim, Volume), staging/src/k8s.io/api/storage/v1/types.go
(StorageClass, CSINode). Only the scheduling-relevant subset: the volume
plugins need binding state, capacity, access modes, node affinity / zone
labels, binding mode, and CSI attach limits.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta
from .types import NodeSelector

# Access modes (core/v1 PersistentVolumeAccessMode)
READ_WRITE_ONCE = "ReadWriteOnce"
READ_ONLY_MANY = "ReadOnlyMany"
READ_WRITE_MANY = "ReadWriteMany"
READ_WRITE_ONCE_POD = "ReadWriteOncePod"

# PV phases
VOLUME_AVAILABLE = "Available"
VOLUME_BOUND = "Bound"
VOLUME_RELEASED = "Released"

# PVC phases
CLAIM_PENDING = "Pending"
CLAIM_BOUND = "Bound"

# StorageClass volumeBindingMode
BINDING_IMMEDIATE = "Immediate"
BINDING_WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"

# Provisioner value meaning "static PVs only" (storage/v1 well-known)
NO_PROVISIONER = "kubernetes.io/no-provisioner"

# persistentVolumeReclaimPolicy (core/v1)
RECLAIM_RETAIN = "Retain"
RECLAIM_DELETE = "Delete"

# Well-known zone/region labels the VolumeZone plugin matches
# (reference: pkg/scheduler/framework/plugins/volumezone/volume_zone.go
# topologyLabels).
ZONE_LABELS = (
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
)


@dataclass(frozen=True)
class Volume:
    """A pod volume source (core/v1 Volume). Only the sources the scheduler
    inspects are modeled: PVC references and ephemeral volumes (which own a
    generated claim named <pod>-<volume>)."""

    name: str
    persistent_volume_claim: str = ""  # claim name in the pod's namespace
    ephemeral: bool = False  # generic ephemeral volume -> claim <pod>-<name>
    host_path: str = ""
    empty_dir: bool = False

    def claim_name(self, pod_name: str) -> str:
        """The PVC name this volume resolves to, or '' if not claim-backed.

        Reference: ephemeral claims are named <podName>-<volumeName>
        (component-helpers/storage/ephemeral).
        """
        if self.persistent_volume_claim:
            return self.persistent_volume_claim
        if self.ephemeral:
            return f"{pod_name}-{self.name}"
        return ""


@dataclass
class PersistentVolumeSpec:
    capacity: dict[str, object] = field(default_factory=dict)  # {"storage": "10Gi"}
    access_modes: tuple[str, ...] = (READ_WRITE_ONCE,)
    storage_class_name: str = ""
    node_affinity: NodeSelector | None = None  # required topology
    claim_ref: str = ""  # "namespace/name" of the bound claim
    # UID of the bound claim (claimRef.uid): distinguishes the claim
    # INSTANCE — a deleted-and-recreated same-named PVC must not keep the
    # old PV bound (pv_controller.go checks exactly this)
    claim_ref_uid: str = ""
    csi_driver: str = ""  # CSI driver name, "" for in-tree/local volumes
    reclaim_policy: str = RECLAIM_RETAIN  # persistentVolumeReclaimPolicy


@dataclass
class PersistentVolumeStatus:
    phase: str = VOLUME_AVAILABLE


@dataclass
class PersistentVolume:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    status: PersistentVolumeStatus = field(default_factory=PersistentVolumeStatus)

    kind = "PersistentVolume"

    @property
    def storage_capacity(self) -> int:
        from .quantity import parse_quantity

        return int(parse_quantity(self.spec.capacity.get("storage", 0)))


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: tuple[str, ...] = (READ_WRITE_ONCE,)
    storage_class_name: str = ""
    volume_name: str = ""  # set when bound (or pre-bound) to a PV
    request: dict[str, object] = field(default_factory=dict)  # {"storage": "5Gi"}


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = CLAIM_PENDING


@dataclass
class PersistentVolumeClaim:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)
    status: PersistentVolumeClaimStatus = field(
        default_factory=PersistentVolumeClaimStatus
    )

    kind = "PersistentVolumeClaim"

    @property
    def is_bound(self) -> bool:
        return self.status.phase == CLAIM_BOUND and bool(self.spec.volume_name)

    @property
    def requested_storage(self) -> int:
        from .quantity import parse_quantity

        return int(parse_quantity(self.spec.request.get("storage", 0)))


@dataclass
class StorageClass:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = NO_PROVISIONER
    volume_binding_mode: str = BINDING_IMMEDIATE
    # reclaim policy stamped onto dynamically provisioned PVs (the
    # reference defaults provisioned volumes to Delete)
    reclaim_policy: str = RECLAIM_DELETE

    kind = "StorageClass"

    @property
    def is_wait_for_first_consumer(self) -> bool:
        return self.volume_binding_mode == BINDING_WAIT_FOR_FIRST_CONSUMER


@dataclass(frozen=True)
class CSINodeDriver:
    name: str
    allocatable_count: int = 0  # 0 = no limit reported


@dataclass
class CSINode:
    """Per-node CSI driver registration + attach limits (storage/v1 CSINode).
    meta.name == node name."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    drivers: tuple[CSINodeDriver, ...] = ()

    kind = "CSINode"

    def limit_for(self, driver: str) -> int:
        for d in self.drivers:
            if d.name == driver:
                return d.allocatable_count
        return 0


def pod_claim_names(pod) -> list[str]:
    """All PVC names (in the pod's namespace) referenced by the pod's volumes."""
    out = []
    for v in pod.spec.volumes:
        name = v.claim_name(pod.meta.name)
        if name:
            out.append(name)
    return out


@dataclass
class VolumeAttachmentSpec:
    """storage.k8s.io/v1 VolumeAttachmentSpec (attach_detach_controller +
    the external CSI attacher's contract): which PV is being attached to
    which node by which attacher."""

    attacher: str = ""  # CSI driver name ("" = in-tree, attach is a no-op)
    node_name: str = ""
    pv_name: str = ""  # source.persistentVolumeName


@dataclass
class VolumeAttachment:
    """storage.k8s.io/v1 VolumeAttachment: the attach INTENT between PV
    binding and kubelet mount. The attach-detach controller creates these
    for scheduled pods' CSI volumes; the attacher (in-process here) flips
    status["attached"]; the kubelet's volume manager WAITS on that before
    mounting (WaitForAttachAndMount's attach half). Cluster-scoped.

    Reference: pkg/controller/volume/attachdetach/attach_detach_controller.go
    + staging/src/k8s.io/api/storage/v1/types.go VolumeAttachment."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: VolumeAttachmentSpec = field(default_factory=VolumeAttachmentSpec)
    # {"attached": bool, "attach_error": str}
    status: dict = field(default_factory=dict)

    kind = "VolumeAttachment"

    @staticmethod
    def expected_name(pv_name: str, node_name: str) -> str:
        """Deterministic, COLLISION-FREE name per (volume, node) pair —
        hashed like the reference's csi-<sha> (a readable join would
        collide: pv 'data-1'+node 'a' vs pv 'data'+node '1-a')."""
        import hashlib

        digest = hashlib.sha1(
            f"{pv_name}\x00{node_name}".encode()
        ).hexdigest()[:16]
        return f"attach-{digest}"
