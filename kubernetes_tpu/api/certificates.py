"""certificates.k8s.io — CertificateSigningRequest.

Reference: staging/src/k8s.io/api/certificates/v1/types.go + the signing
controllers in pkg/controller/certificates/ (approver, signer). A client
(kubeadm join's kubelet bootstrap) submits a PEM CSR naming a signer;
an approval controller adds the Approved condition; the signing controller
mints the certificate from the cluster CA into status. Cluster-scoped.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta

# the signers the reference's signing controller handles
# (pkg/controller/certificates/signer/signer.go)
KUBELET_CLIENT_SIGNER = "kubernetes.io/kube-apiserver-client-kubelet"
CLIENT_SIGNER = "kubernetes.io/kube-apiserver-client"

CONDITION_APPROVED = "Approved"
CONDITION_DENIED = "Denied"


@dataclass
class CSRSpec:
    request: str = ""  # PEM-encoded PKCS#10 CSR
    signer_name: str = KUBELET_CLIENT_SIGNER
    usages: tuple[str, ...] = ("digital signature", "client auth")
    username: str = ""  # requestor identity (set by the server on create)


@dataclass
class CertificateSigningRequest:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: CSRSpec = field(default_factory=CSRSpec)
    # {"certificate": PEM, "conditions": [{"type": ..., "reason": ...}]}
    status: dict = field(default_factory=dict)

    kind = "CertificateSigningRequest"

    def condition(self, ctype: str) -> dict | None:
        for c in self.status.get("conditions", ()):
            if c.get("type") == ctype:
                return c
        return None

    @property
    def approved(self) -> bool:
        return self.condition(CONDITION_APPROVED) is not None

    @property
    def denied(self) -> bool:
        return self.condition(CONDITION_DENIED) is not None
