"""Resource quantity parsing, canonicalized for TPU plane units.

Reference: staging/src/k8s.io/apimachinery/pkg/api/resource (Quantity). We do
not keep an arbitrary-precision Quantity around: every quantity is parsed once
into an integer in its resource's canonical *plane unit*:

- cpu:               millicores (1 core = 1000)
- memory / storage:  MiB (requests rounded up, capacities rounded down)
- pods / counts:     whole units
- extended/scalar:   whole units (devices), rounded up for requests

This is a deliberate TPU-first divergence from the reference (which carries
int64 byte/milli values everywhere): int32 MiB planes cover 2 PiB per node,
keep all fit/score arithmetic exact in int32 on the VPU, and guarantee the
host path and the device kernels see the *same* numbers.
"""

from __future__ import annotations

from fractions import Fraction
from functools import lru_cache

# Decimal and binary SI suffixes, as in apimachinery's Quantity.
_SUFFIX: dict[str, Fraction] = {
    "": Fraction(1),
    "m": Fraction(1, 1000),
    "k": Fraction(10**3),
    "M": Fraction(10**6),
    "G": Fraction(10**9),
    "T": Fraction(10**12),
    "P": Fraction(10**15),
    "E": Fraction(10**18),
    "Ki": Fraction(2**10),
    "Mi": Fraction(2**20),
    "Gi": Fraction(2**30),
    "Ti": Fraction(2**40),
    "Pi": Fraction(2**50),
    "Ei": Fraction(2**60),
}

_MIB = Fraction(2**20)


@lru_cache(maxsize=4096)
def _parse(s: str | int | float) -> Fraction:
    """Memoized: clusters use a handful of distinct quantity strings across
    millions of parses (every PodInfo/NodeInfo build); Fraction results are
    immutable so sharing is safe."""
    if isinstance(s, (int, float)):
        return Fraction(s).limit_denominator(10**9)
    s = s.strip()
    if not s:
        raise ValueError("empty quantity")
    # split numeric part from suffix
    i = len(s)
    while i > 0 and not (s[i - 1].isdigit() or s[i - 1] == "."):
        i -= 1
    num, suffix = s[:i], s[i:]
    if suffix.startswith("e") or suffix.startswith("E"):
        # scientific notation like 1e3
        return Fraction(float(s))
    if suffix not in _SUFFIX:
        raise ValueError(f"unknown quantity suffix {suffix!r} in {s!r}")
    if not num:
        raise ValueError(f"no digits in quantity {s!r}")
    return Fraction(num) * _SUFFIX[suffix]


def parse_quantity(s: str | int | float) -> Fraction:
    """Parse a k8s-style quantity string into an exact Fraction of base units."""
    return _parse(s)


def parse_cpu(s: str | int | float) -> int:
    """CPU quantity -> millicores (rounded up; '100m' -> 100, '2' -> 2000)."""
    v = _parse(s) * 1000
    return -((-v.numerator) // v.denominator)  # ceil


def parse_mem_mib(s: str | int | float, *, floor: bool = False) -> int:
    """Memory/storage quantity -> MiB.

    Requests round *up* (a pod asking for 100M=95.37MiB occupies 96MiB) and
    capacities round *down*, so the plane-unit arithmetic is conservative in
    both directions.
    """
    v = _parse(s) / _MIB
    if floor:
        return v.numerator // v.denominator
    return -((-v.numerator) // v.denominator)


def parse_count(s: str | int | float, *, floor: bool = False) -> int:
    """Whole-unit quantity (pods, devices). Requests ceil, capacities floor."""
    v = _parse(s)
    if floor:
        return v.numerator // v.denominator
    return -((-v.numerator) // v.denominator)
