"""scheduler_perf-compatible workload harness.

Reference: test/integration/scheduler_perf/scheduler_perf.go (opcodes :65-79,
runner :690-738), executor.go (WorkloadExecutor:54, runOp:76), util.go
(1 Hz throughput sampler :68,459-603, DataItem JSON :200-285). The YAML
schema is the reference's: a list of test cases, each with a workloadTemplate
(list of ops with $param substitution) and workloads ({name, labels,
featureGates, params, threshold}).

Differences: the control plane is in-process (our store stands in for
apiserver+etcd exactly like the reference runs them in-process), and the
throughput sampler derives its 1-second windows from per-pod bind timestamps
instead of a polling goroutine — same windows, no sampling thread jitter.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from pathlib import Path

import yaml

from ..api.meta import ObjectMeta
from ..api.types import GangPolicy, PodGroup, PodGroupSpec
from ..scheduler import Profile, Scheduler
from ..scheduler.metrics import SchedulerMetrics
from ..store.store import MODIFIED, Store
from .templates import node_from_manifest, pod_from_manifest

DEFAULT_POD_TEMPLATE = {
    "spec": {
        "containers": [
            {"name": "pause", "image": "registry.k8s.io/pause:3.10",
             "resources": {"requests": {"cpu": "100m", "memory": "50Mi"}}}
        ]
    }
}
DEFAULT_NODE_TEMPLATE: dict = {}


def _resolve(value, params: dict):
    """$param substitution (scheduler_perf.go countParam semantics)."""
    if isinstance(value, str) and value.startswith("$"):
        return params[value[1:]]
    return value


@dataclass
class DataItem:
    """util.go DataItem — one measured series for perf-dash."""

    data: dict[str, float]
    unit: str
    labels: dict[str, str] = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"data": self.data, "unit": self.unit, "labels": self.labels}


@dataclass
class WorkloadResult:
    name: str
    data_items: list[DataItem]
    threshold: float | None
    passed: bool
    scheduled: int
    duration_s: float

    @property
    def throughput(self) -> float:
        for item in self.data_items:
            if item.unit == "pods/s":
                return item.data.get("Average", 0.0)
        return 0.0


class ThroughputCollector:
    """Windowed pods/s from bind timestamps (util.go collector semantics:
    1-second windows over the measurement phase, then
    Average/Perc50/90/95/99 over the window series), plus the pod-scheduling
    SLI latency series (create→bind per pod; the in-process analogue of
    scheduler_pod_scheduling_sli_duration_seconds, metrics.go:312, collected
    per workload at util.go:364-457)."""

    def __init__(self, store: Store, namespace_filter: str | None = None):
        self.store = store
        self.bind_times: dict[str, float] = {}
        self.create_times: dict[str, float] = {}
        self._watch = None

    def start(self) -> None:
        # watch from the CURRENT revision: replaying the full log would pull
        # pre-measurement init pods into the throughput span and SLI series
        self._watch = self.store.watch("Pod", from_revision=self.store.revision)

    def pump(self) -> None:
        if self._watch is None:
            return
        from ..store.store import ADDED

        for ev in self._watch.drain():
            pod = ev.obj
            if ev.type == ADDED and not pod.spec.node_name:
                self.create_times.setdefault(pod.meta.key, ev.ts)
            elif ev.type == MODIFIED and pod.spec.node_name:
                # ev.ts is the store write time — the true bind instant, not
                # the (batched) drain time
                self.bind_times.setdefault(pod.meta.key, ev.ts)

    def sli_latency(self) -> DataItem:
        lats = sorted(
            self.bind_times[k] - t0
            for k, t0 in self.create_times.items()
            if k in self.bind_times
        )

        def perc(q: float) -> float:
            if not lats:
                return 0.0
            return lats[min(int(q * len(lats)), len(lats) - 1)]

        avg = sum(lats) / len(lats) if lats else 0.0
        return DataItem(
            {
                "Average": round(avg, 4),
                "Perc50": round(perc(0.50), 4),
                "Perc90": round(perc(0.90), 4),
                "Perc95": round(perc(0.95), 4),
                "Perc99": round(perc(0.99), 4),
            },
            "seconds",
            labels={"Metric": "scheduler_pod_scheduling_sli_duration_seconds"},
        )

    def stop(self) -> list[DataItem]:
        self.pump()
        if self._watch is not None:
            self._watch.stop()
        sli = self.sli_latency()
        times = sorted(self.bind_times.values())
        if len(times) < 2:
            return [DataItem({"Average": 0.0}, "pods/s"), sli]
        start, end = times[0], times[-1]
        total = len(times)
        span = max(end - start, 1e-6)
        # 1-second windows (partial last window scaled)
        windows: list[float] = []
        w_start = start
        while w_start < end:
            w_end = min(w_start + 1.0, end)
            n = sum(1 for t in times if w_start <= t < w_end) if w_end > w_start else 0
            if w_end - w_start > 1e-6:
                windows.append(n / (w_end - w_start))
            w_start = w_end
        windows.sort()

        def perc(q: float) -> float:
            if not windows:
                return 0.0
            idx = min(int(q * len(windows)), len(windows) - 1)
            return windows[idx]

        return [
            DataItem(
                {
                    "Average": round(total / span, 2),
                    "Perc50": round(perc(0.50), 2),
                    "Perc90": round(perc(0.90), 2),
                    "Perc95": round(perc(0.95), 2),
                    "Perc99": round(perc(0.99), 2),
                },
                "pods/s",
            ),
            sli,
        ]


class WorkloadExecutor:
    """executor.go WorkloadExecutor — interprets one workload's op list."""

    def __init__(self, test_case: dict, workload: dict, backend: str = "host",
                 wave_size: int = 0):
        self.test_case = test_case
        self.workload = workload
        self.params = dict(workload.get("params", {}))
        self.feature_gates = dict(test_case.get("featureGates", {}))
        self.feature_gates.update(workload.get("featureGates", {}))
        self.backend = backend
        self.store = Store()
        self.metrics = SchedulerMetrics()
        self.scheduler = Scheduler(
            self.store,
            profiles=[Profile(
                backend=backend,
                wave_size=wave_size if backend == "tpu" else 0,
            )],
            feature_gates=self.feature_gates,
            metrics=self.metrics,
            async_api_calls=self.feature_gates.get("SchedulerAsyncAPICalls", False),
            # KubeSchedulerConfiguration.Parallelism is deployment tuning
            # (reference default 16 assumes 16 cores); on this 1-core bench
            # box 16 dispatcher workers just fight the scheduling thread
            # for the GIL + store lock
            parallelism=int(os.environ.get("BENCH_PARALLELISM", "2")),
        )
        self.scheduler.start()
        self.collector = ThroughputCollector(self.store)
        self._collecting = False
        self._node_seq = 0
        self._pod_seq = 0
        self._measured = 0
        self.data_items: list[DataItem] = []
        base = test_case.get("_base_dir", ".")
        self.pod_template = self._load_template(
            test_case.get("defaultPodTemplatePath"), base, DEFAULT_POD_TEMPLATE
        )
        self.node_template = self._load_template(
            test_case.get("defaultNodeTemplatePath"), base, DEFAULT_NODE_TEMPLATE
        )

    @staticmethod
    def _load_template(path: str | None, base: str, default: dict) -> dict:
        if not path:
            return default
        p = Path(base) / path
        return yaml.safe_load(p.read_text())

    # -- opcodes (scheduler_perf.go:65-79) -----------------------------------

    def run(self) -> WorkloadResult:
        t0 = time.perf_counter()
        for op in self.test_case.get("workloadTemplate", []):
            self._run_op(op)
        self._barrier()
        duration = time.perf_counter() - t0
        if self._collecting:
            self._stop_collecting()
        threshold = self.workload.get("threshold")
        result = WorkloadResult(
            name=f"{self.test_case['name']}/{self.workload['name']}",
            data_items=self.data_items,
            threshold=threshold,
            passed=True,
            scheduled=sum(1 for p in self.store.pods() if p.spec.node_name),
            duration_s=duration,
        )
        if threshold is not None and result.throughput < threshold:
            result.passed = False
        if self.scheduler.api_dispatcher is not None:
            self.scheduler.api_dispatcher.close()
        return result

    def _run_op(self, op: dict) -> None:
        opcode = op["opcode"]
        fn = getattr(self, f"_op_{opcode}", None)
        if fn is None:
            raise ValueError(f"unknown opcode {opcode}")
        fn(op)

    def _count(self, op: dict) -> int:
        if "countParam" in op:
            return int(_resolve(op["countParam"], self.params))
        return int(op.get("count", 0))

    def _op_createNodes(self, op: dict) -> None:
        template = op.get("nodeTemplate", self.node_template)
        if isinstance(template, str):
            template = self._load_template(
                template, self.test_case.get("_base_dir", "."), DEFAULT_NODE_TEMPLATE
            )
        n = self._count(op)
        zones = int(_resolve(op.get("zones", 8), self.params) or 8)
        # csiNodeAllocatable analogue (scheduler_perf nodeAllocatableStrategy
        # :csiNodeAllocatable): every created node also registers a CSINode
        # with the driver's attach limit — what NodeVolumeLimits counts
        csi = op.get("csiNodeDriver")
        for _ in range(n):
            i = self._node_seq
            self._node_seq += 1
            name = f"node-{i}"
            self.store.create(
                node_from_manifest(template, name, zone=f"zone-{i % zones}"),
                copy_return=False,
            )
            if csi:
                from ..api.storage import CSINode, CSINodeDriver

                self.store.create(CSINode(
                    meta=ObjectMeta(name=name, namespace=""),
                    drivers=(CSINodeDriver(
                        name=csi.get("name", "csi.example.com"),
                        allocatable_count=int(csi.get("count", 39)),
                    ),),
                ), copy_return=False)
        self.scheduler.pump()

    def _op_createPods(self, op: dict) -> None:
        template = op.get("podTemplate", self.pod_template)
        if isinstance(template, str):
            template = self._load_template(
                template, self.test_case.get("_base_dir", "."), DEFAULT_POD_TEMPLATE
            )
        n = self._count(op)
        collect = bool(op.get("collectMetrics"))
        if collect and not self._collecting:
            self._start_collecting()
        namespace = op.get("namespace", "default")
        pvc_t = op.get("persistentVolumeClaimTemplate")
        pv_t = op.get("persistentVolumeTemplate")
        claims_spec = op.get("resourceClaimTemplate")  # DRA per-pod claims
        for _ in range(n):
            i = self._pod_seq
            self._pod_seq += 1
            pod = pod_from_manifest(template, f"pod-{i}", namespace)
            if pvc_t is not None:
                self._attach_volume(pod, i, pvc_t, pv_t, namespace)
            if claims_spec is not None:
                self._attach_claim(pod, i, claims_spec, namespace)
            self.store.create(pod, copy_return=False)
        if collect:
            self._measured += n
        # steady-state scheduling after each creation op (the reference's
        # scheduler runs continuously; barrier waits for completion)
        self._barrier(wait_all=bool(op.get("skipWaitToCompletion")) is False)

    def _attach_volume(self, pod, i: int, pvc_t: dict, pv_t: dict | None,
                       namespace: str) -> None:
        """Per-pod PVC (+ optional pre-provisioned PV), mirroring the
        reference's persistentVolumeClaimTemplatePath support."""
        from ..api.storage import (
            PersistentVolume,
            PersistentVolumeClaim,
            PersistentVolumeClaimSpec,
            PersistentVolumeSpec,
            Volume,
        )

        claim_name = f"claim-{i}"
        sc = pvc_t.get("storageClassName", "")
        if sc and self.store.try_get("StorageClass", sc) is None:
            from ..api.storage import (
                BINDING_WAIT_FOR_FIRST_CONSUMER,
                StorageClass,
            )

            self.store.create(StorageClass(
                meta=ObjectMeta(name=sc, namespace=""),
                provisioner=pvc_t.get("provisioner", "kubernetes.io/no-provisioner"),
                volume_binding_mode=BINDING_WAIT_FOR_FIRST_CONSUMER,
            ))
        pv_name = f"pv-{i}"
        # bound: true = the immediate-binding shape (reference pvc.yaml's
        # pv.kubernetes.io/bind-completed annotation + executor pre-binding
        # claim <-> volume): pods arrive with their claims already Bound
        bound = bool(pvc_t.get("bound"))
        if pv_t is not None:
            self.store.create(PersistentVolume(
                meta=ObjectMeta(name=pv_name, namespace=""),
                spec=PersistentVolumeSpec(
                    capacity=dict(pv_t.get("capacity", {"storage": "10Gi"})),
                    access_modes=tuple(pv_t.get("accessModes", ("ReadWriteOnce",))),
                    storage_class_name=sc,
                    csi_driver=pv_t.get("csiDriver", ""),
                    claim_ref=f"{namespace}/{claim_name}" if bound else "",
                ),
            ))
        pvc = PersistentVolumeClaim(
            meta=ObjectMeta(name=claim_name, namespace=namespace),
            spec=PersistentVolumeClaimSpec(
                access_modes=tuple(pvc_t.get("accessModes", ("ReadWriteOnce",))),
                storage_class_name=sc,
                request=dict(pvc_t.get("request", {"storage": "5Gi"})),
                volume_name=pv_name if bound and pv_t is not None else "",
            ),
        )
        if bound:
            from ..api.storage import CLAIM_BOUND

            pvc.status.phase = CLAIM_BOUND
        self.store.create(pvc)
        pod.spec.volumes = tuple(pod.spec.volumes) + (
            Volume(name="data", persistent_volume_claim=claim_name),
        )

    def _attach_claim(self, pod, i: int, claims_spec: dict, namespace: str) -> None:
        """Per-pod ResourceClaim (reference: claim templates generated by the
        resourceclaim controller; the harness creates them directly)."""
        from ..api.dra import (
            DeviceRequest,
            PodResourceClaim,
            ResourceClaim,
            ResourceClaimSpec,
        )

        from ..api.dra import DeviceSelector

        name = f"rclaim-{i}"
        cel = claims_spec.get("celSelector", "")
        self.store.create(ResourceClaim(
            meta=ObjectMeta(name=name, namespace=namespace),
            spec=ResourceClaimSpec(requests=(
                DeviceRequest(
                    name="req",
                    device_class_name=claims_spec.get("deviceClassName", ""),
                    count=int(claims_spec.get("count", 1)),
                    selectors=(DeviceSelector(cel=cel),) if cel else (),
                ),
            )),
        ))
        pod.spec.resource_claims = (
            PodResourceClaim(name=name, resource_claim_name=name),
        )

    def _op_createResourceSlices(self, op: dict) -> None:
        """DRA inventory: one slice per existing node (scheduler_perf
        createResourceDriver analogue)."""
        from ..api.dra import Device, ResourceSlice

        per_node = int(_resolve(op.get("devicesPerNode", 4), self.params))
        driver = op.get("driver", "perf.example.com")
        for node in self.store.nodes():
            self.store.create(ResourceSlice(
                meta=ObjectMeta(name=f"slice-{node.meta.name}", namespace=""),
                node_name=node.meta.name,
                driver=driver,
                devices=tuple(
                    Device(name=f"dev-{j}", attributes={"index": str(j)})
                    for j in range(per_node)
                ),
            ))
        self.scheduler.pump()

    def _op_createPodGroups(self, op: dict) -> None:
        """Gang workloads: one PodGroup + minCount member pods per group.
        `topologyKey` (+ `topologyMode`, default Required) adds a KEP-5732
        topology constraint so the gang must pack into one domain —
        createNodes labels nodes `topology.kubernetes.io/zone` round-robin
        over its `zones` param."""
        from ..api.types import SchedulingConstraints, TopologyConstraint

        n = self._count(op)
        size = int(_resolve(op.get("podsPerGroup", 2), self.params))
        template = op.get("podTemplate", self.pod_template)
        topo_key = op.get("topologyKey")
        constraints = SchedulingConstraints()
        if topo_key:
            constraints = SchedulingConstraints(topology=(
                TopologyConstraint(key=str(topo_key),
                                   mode=str(op.get("topologyMode",
                                                   "Required"))),
            ))
        if op.get("collectMetrics") and not self._collecting:
            self._start_collecting()
        if op.get("collectMetrics"):
            self._measured += n * size
        for g in range(n):
            name = f"group-{g}-{self._pod_seq}"
            self.store.create(
                PodGroup(
                    meta=ObjectMeta(name=name),
                    spec=PodGroupSpec(policy=GangPolicy(min_count=size),
                                      constraints=constraints),
                )
            )
            for _ in range(size):
                i = self._pod_seq
                self._pod_seq += 1
                pod = pod_from_manifest(template, f"pod-{i}")
                from ..api.types import SchedulingGroup

                pod.spec.scheduling_group = SchedulingGroup(pod_group_name=name)
                self.store.create(pod, copy_return=False)
        self._barrier()

    def _op_createDaemonSetPods(self, op: dict) -> None:
        """SchedulingDaemonset shape (misc/performance-config.yaml:146-160):
        one pod per existing node, pinned by required node affinity on
        metadata.name — the scheduler places them (daemon controller
        delegation), exercising the NodeAffinity single-node fast path."""
        from ..api.types import (
            Affinity,
            NodeAffinity,
            NodeSelector,
            NodeSelectorRequirement,
            NodeSelectorTerm,
        )

        template = op.get("podTemplate", self.pod_template)
        if isinstance(template, str):
            template = self._load_template(
                template, self.test_case.get("_base_dir", "."), DEFAULT_POD_TEMPLATE
            )
        collect = bool(op.get("collectMetrics"))
        if collect and not self._collecting:
            self._start_collecting()
        n = 0
        for node in self.store.nodes():
            i = self._pod_seq
            self._pod_seq += 1
            pod = pod_from_manifest(template, f"ds-pod-{i}", "default")
            pod.spec.affinity = Affinity(node_affinity=NodeAffinity(
                required=NodeSelector(terms=(NodeSelectorTerm(
                    match_fields=(NodeSelectorRequirement(
                        key="metadata.name", operator="In",
                        values=(node.meta.name,),
                    ),),
                ),)),
            ))
            self.store.create(pod, copy_return=False)
            n += 1
        if collect:
            self._measured += n
        self._barrier()

    def _op_churn(self, op: dict) -> None:
        """churn op: delete + recreate pods to stress event handling."""
        n = self._count(op) or 10
        deleted = self._delete_scheduled(n)
        template = op.get("podTemplate", self.pod_template)
        for _ in range(deleted):
            i = self._pod_seq
            self._pod_seq += 1
            self.store.create(pod_from_manifest(template, f"churn-pod-{i}"))
        self._barrier()

    def _delete_scheduled(self, n: int, selector: dict | None = None) -> int:
        """Delete up to n SCHEDULED pods matching selector; returns count.
        Shared by churn and deletePods — deleting pending pods frees
        nothing and shrinks the measured set."""
        from ..api.labels import labels_subset

        pods = [
            p for p in self.store.pods()
            if p.spec.node_name and labels_subset(selector or {}, p.meta.labels)
        ]
        if n:
            pods = pods[:n]
        for p in pods:
            self.store.delete("Pod", p.meta.key)
        self.scheduler.pump()
        return len(pods)

    def _op_deletePods(self, op: dict) -> None:
        """deletePods op (scheduler_perf.go): delete pods matching a label
        selector (or the oldest N scheduled pods), driving the queueing-hint
        requeue path — deletes free resources, AssignedPodDelete events
        must un-block pending pods."""
        self._delete_scheduled(self._count(op) or 0,
                               op.get("labelSelector") or {})

    def _op_barrier(self, op: dict) -> None:
        self._barrier()

    def _op_sleep(self, op: dict) -> None:
        time.sleep(float(op.get("duration", 0.01)))

    def _op_startCollectingMetrics(self, op: dict) -> None:
        self._start_collecting()

    def _op_stopCollectingMetrics(self, op: dict) -> None:
        self._stop_collecting()

    # -- helpers -------------------------------------------------------------

    def _barrier(self, wait_all: bool = True,
                 timeout: float | None = None) -> None:
        """operations.go barrier:498-537 — wait until every pending pod got a
        scheduling attempt and bindings landed. Pods parked in the backoffQ
        still count as pending (their expiry is wall-clock): the barrier
        rides through backoff windows instead of declaring the queue drained
        the moment activeQ goes empty."""
        if timeout is None:
            # reference-scale barriers legitimately run for minutes (20k
            # victims at a few hundred pods/s); scale the guard with the
            # backlog instead of shipping a fixed 30s that only fits the
            # integration-test shapes. Pump FIRST: just-created pods sit in
            # informer buffers, not the queue — sampling before the pump
            # would always read ~0 and floor the timeout
            self.scheduler.pump()
            active, backoff, unsched = self.scheduler.queue.pending_pods()
            timeout = max(60.0, 2.0 * (active + backoff + unsched))
        deadline = time.monotonic() + timeout
        prof = self.scheduler.loop.phase_profile
        while True:
            self.scheduler.schedule_pending()
            t0 = time.perf_counter()
            self.collector.pump()
            if not wait_all:
                prof["harness"] += time.perf_counter() - t0
                return  # skipWaitToCompletion: one pass, no drain
            active, backoff, _unsched = self.scheduler.queue.pending_pods()
            prof["harness"] += time.perf_counter() - t0
            if active == 0 and backoff == 0:
                return
            if time.monotonic() >= deadline:
                # the reference barrier FAILS the run on timeout
                # (operations.go); returning quietly would ship hangs
                raise TimeoutError(
                    f"barrier: {active} active + {backoff} backoff pods "
                    f"still pending after {timeout}s"
                )
            time.sleep(0.02)

    def _start_collecting(self) -> None:
        self._collecting = True
        # snapshot phase/exec counters so the bench can attribute the
        # MEASURED span alone (init-phase costs excluded); the flight
        # recorder owns the stopwatches (loop.phase_profile aliases its
        # phase_totals), so these snapshots ARE recorder-sourced
        rec = self.scheduler.flight_recorder
        self.profile_at_start = rec.phase_snapshot()
        self.wave_profile_at_start = rec.wave_snapshot()
        d = self.scheduler.api_dispatcher
        self.exec_seconds_at_start = d.exec_seconds if d is not None else 0.0
        self.collect_started_at = time.perf_counter()
        self.collector.start()

    def _stop_collecting(self) -> None:
        self._collecting = False
        # end-of-measurement snapshot (pairs with _start_collecting's):
        # profile deltas must cover the same span the wall clock does
        rec = self.scheduler.flight_recorder
        self.profile_at_stop = rec.phase_snapshot()
        self.wave_profile_at_stop = rec.wave_snapshot()
        d = self.scheduler.api_dispatcher
        self.exec_seconds_at_stop = d.exec_seconds if d is not None else 0.0
        self.collect_stopped_at = time.perf_counter()
        self.data_items.extend(self.collector.stop())


def load_config(path: str | Path) -> list[dict]:
    path = Path(path)
    cases = yaml.safe_load(path.read_text())
    for case in cases:
        case["_base_dir"] = str(path.parent)
    return cases


def run_workloads(
    config_path: str | Path,
    labels: set[str] | None = None,
    backend: str = "host",
    name_filter: str | None = None,
    wave_size: int = 0,
) -> list[WorkloadResult]:
    """Run every workload matching the label selector (CI behavior: pick by
    labels like integration-test/short/performance)."""
    results = []
    for case in load_config(config_path):
        for workload in case.get("workloads", []):
            wl_labels = set(workload.get("labels", []))
            if labels is not None and not (labels & wl_labels):
                continue
            full = f"{case['name']}/{workload['name']}"
            if name_filter and name_filter not in full:
                continue
            executor = WorkloadExecutor(case, workload, backend=backend,
                                        wave_size=wave_size)
            results.append(executor.run())
    return results


def main(argv: list[str] | None = None) -> int:
    import argparse

    from ..utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()

    parser = argparse.ArgumentParser(description="scheduler_perf harness")
    parser.add_argument("configs", nargs="+", help="performance-config YAMLs")
    parser.add_argument("--labels", default="integration-test",
                        help="comma-separated label selector")
    parser.add_argument("--backend", default="host", choices=["host", "tpu"])
    parser.add_argument("--filter", default=None, help="substring name filter")
    parser.add_argument("--wave", type=int, default=0,
                        help="batched wave size (tpu backend only)")
    args = parser.parse_args(argv)
    labels = set(args.labels.split(",")) if args.labels else None
    all_ok = True
    for config in args.configs:
        for result in run_workloads(config, labels, args.backend, args.filter,
                                    wave_size=args.wave):
            status = "ok" if result.passed else "BELOW THRESHOLD"
            print(json.dumps({
                "workload": result.name,
                "throughput": result.throughput,
                "scheduled": result.scheduled,
                "duration_s": round(result.duration_s, 2),
                "threshold": result.threshold,
                "status": status,
                "dataItems": [d.as_dict() for d in result.data_items],
            }))
            all_ok = all_ok and result.passed
    return 0 if all_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
