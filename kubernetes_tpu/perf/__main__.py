from .harness import main

raise SystemExit(main())
