"""scheduler_perf-compatible performance harness (SURVEY.md §4 tier 4)."""

from .harness import (
    DataItem,
    ThroughputCollector,
    WorkloadExecutor,
    WorkloadResult,
    load_config,
    run_workloads,
)

__all__ = [
    "DataItem", "ThroughputCollector", "WorkloadExecutor", "WorkloadResult",
    "load_config", "run_workloads",
]
