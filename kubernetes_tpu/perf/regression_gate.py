"""Mechanical perf-regression gate over BENCH artifacts (`make bench-gate`).

Diffs the newest two bench artifacts per family (`BENCH_*` and
`MULTICHIP_BENCH_*`, gated independently) in the repo root — or two
explicit paths — row-by-row and FAILS (exit 1) when any throughput or SLI
row regressed by more than the tolerance (default 10%):

- throughput rows (unit "pods/s..."): regression = new < old * 0.9
- latency keys  (sli_p50_s, sli_p99_s, trace_p50_s, trace_p99_s):
  regression = new > old * 1.1
- device keys   (upload_bytes_per_wave, compile_count,
  warm_compile_count): lower is better — growth past the tolerance means
  host->device transfer crept back in, a kernel started recompiling per
  wave (a recompile storm), or a warm restart stopped being compile-free
- SLI pass flags (sli_p50_ok, sli_p99_ok): true -> false is a regression
  outright — a blown target never hides inside the tolerance band

When both rows carry a host_calibration_score (perf/calibrate.py stamps
one into every artifact row), wall-clock comparisons are NORMALIZED by
the score ratio before the tolerance check: throughput is scaled to the
old host's speed (new * old_score/new_score), latency the other way
(new * new_score/old_score). Device keys (bytes, compile counts) are
host-independent and never normalized. A score drift beyond 25% between
the artifacts is FLAGGED in the output — flagged, never failed: drift
means the hosts differ, not that the code regressed.

When a row regresses and both artifacts carry the pod latency ledger's
"segments" breakdown, the gate names the segment whose p50 delta explains
the regression — the first question of any perf triage, answered
mechanically. If the rows carry the stall profiler's per-reason columns
(stall_*_s), the gate also names the stall reason whose attributed
seconds grew the most.

Artifacts come in three shapes, all accepted:
- a raw JSON line (bench.py stdout saved to a file)
- JSONL, one row per line (bench_suite.py stdout)
- the round-runner wrapper {"n", "cmd", "rc", "tail"} where the real rows
  are the JSON lines embedded in "tail" (the BENCH_r*.json files)

Rows are matched by their "metric" name; only metrics present in BOTH
artifacts are compared (a newly added row can't regress against nothing).
"""

from __future__ import annotations

import glob
import json
import os
import sys

TOLERANCE = 0.10
LATENCY_KEYS = ("sli_p50_s", "sli_p99_s", "trace_p50_s", "trace_p99_s")
# device telemetry rows (devicetelemetry.py bench_columns): lower is better.
# warm_compile_count (warm_restart_bench.py) sits at 0 in every healthy
# artifact, so ANY growth exceeds the relative tolerance — the gate fails
# the moment a warm restart compiles anything
DEVICE_KEYS = ("upload_bytes_per_wave", "compile_count", "warm_compile_count")
OK_KEYS = ("sli_p50_ok", "sli_p99_ok")
# artifact families gated independently: single-device rounds (BENCH_*)
# and the sharded-mesh node sweep (MULTICHIP_BENCH_*; bench_multichip.py
# --nodes-sweep). The BENCH_* glob cannot match MULTICHIP_BENCH_* names —
# glob patterns anchor at the start of the basename — so each family
# diffs only against its own history.
FAMILIES = ("BENCH", "MULTICHIP_BENCH")


def _rows_from_obj(obj: object) -> list[dict]:
    """Pull bench rows out of one parsed JSON object (row or wrapper)."""
    rows: list[dict] = []
    if not isinstance(obj, dict):
        return rows
    if "metric" in obj:
        rows.append(obj)
    tail = obj.get("tail")
    if isinstance(tail, str):
        rows.extend(_rows_from_text(tail))
    return rows


def _rows_from_text(text: str) -> list[dict]:
    rows: list[dict] = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rows.extend(_rows_from_obj(json.loads(line)))
        except json.JSONDecodeError:
            continue
    return rows


def load_rows(path: str) -> dict[str, dict]:
    """{metric: row} from an artifact in any of the three shapes."""
    with open(path) as f:
        text = f.read()
    rows = _rows_from_text(text)
    if not rows:
        # maybe one pretty-printed JSON object spanning lines
        try:
            rows = _rows_from_obj(json.loads(text))
        except json.JSONDecodeError:
            pass
    out: dict[str, dict] = {}
    for row in rows:
        out[str(row["metric"])] = row  # later rows win (retry supersedes)
    return out


def newest_artifacts(root: str = ".", family: str = "BENCH") -> list[str]:
    """One family's artifacts, newest first by mtime (name as the
    tiebreak — a fresh checkout stamps every artifact with the same
    mtime, and the round-numbered names order correctly)."""
    paths = [p for pat in (f"{family}_*.json", f"{family}_*.jsonl")
             for p in glob.glob(os.path.join(root, pat))]
    return sorted(paths, key=lambda p: (os.path.getmtime(p), p),
                  reverse=True)


def _num(row: dict, key: str):
    v = row.get(key)
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) else None


def _explain(old: dict, new: dict) -> str | None:
    """Name the ledger segment whose p50 grew the most between the runs."""
    so, sn = old.get("segments"), new.get("segments")
    if not isinstance(so, dict) or not isinstance(sn, dict):
        return None
    worst, worst_delta = None, 0.0
    for seg, q in sn.items():
        if not isinstance(q, dict) or seg not in so:
            continue
        np50, op50 = q.get("p50"), so[seg].get("p50")
        if isinstance(np50, (int, float)) and isinstance(op50, (int, float)):
            delta = np50 - op50
            if delta > worst_delta:
                worst, worst_delta = seg, delta
    if worst is None:
        return None
    return (f"segment '{worst}' explains it: p50 "
            f"{so[worst]['p50']:.4f}s -> {sn[worst]['p50']:.4f}s "
            f"(+{worst_delta:.4f}s)")


def _explain_stalls(old: dict, new: dict) -> str | None:
    """Name the stall reason whose attributed seconds grew the most.

    Both rows must carry the stall profiler's per-reason stall_<reason>_s
    columns (stallprofiler.bench_columns); falls back to the new row's
    stall_dominant when no per-reason delta stands out.
    """
    worst, worst_delta = None, 0.0
    for key, nv in new.items():
        if not (key.startswith("stall_") and key.endswith("_s")
                and key != "stall_total_s"):
            continue
        ov = _num(old, key)
        if ov is None or not isinstance(nv, (int, float)):
            continue
        delta = nv - ov
        if delta > worst_delta:
            worst, worst_delta = key[len("stall_"):-len("_s")], delta
    if worst is not None:
        return (f"stall '{worst}' grew the most "
                f"(+{worst_delta:.4f}s attributed)")
    dom = new.get("stall_dominant")
    if isinstance(dom, str) and dom:
        return f"dominant stall in the new run: '{dom}'"
    return None


def _cal_scores(old: dict, new: dict) -> tuple[float, float] | None:
    """(old_score, new_score) when BOTH rows are calibration-stamped."""
    ov, nv = _num(old, "host_calibration_score"), _num(new, "host_calibration_score")
    if ov is not None and nv is not None and ov > 0 and nv > 0:
        return ov, nv
    return None


def compare(old_rows: dict[str, dict], new_rows: dict[str, dict],
            tolerance: float = TOLERANCE,
            notes: list[str] | None = None) -> list[str]:
    """Regression messages (empty = gate passes).

    `notes`, when given, collects non-failing observations: calibration
    drift flags and which rows were compared under normalization.
    """
    from .calibrate import CALIBRATION_DRIFT_FLAG, drift_ratio

    failures: list[str] = []
    drift_noted = False
    for metric in sorted(set(old_rows) & set(new_rows)):
        old, new = old_rows[metric], new_rows[metric]
        cal = _cal_scores(old, new)
        if (cal is not None and notes is not None and not drift_noted
                and drift_ratio(cal[0], cal[1]) > CALIBRATION_DRIFT_FLAG):
            notes.append(
                f"CALIBRATION DRIFT host_calibration_score "
                f"{cal[0]:g} -> {cal[1]:g} "
                f"({(cal[1] / cal[0] - 1) * 100:+.1f}%, flag threshold "
                f"{CALIBRATION_DRIFT_FLAG:.0%}): the hosts differ; "
                f"wall-clock rows compared calibration-normalized")
            drift_noted = True
        # (key, old, new, normalized new, higher_better, unit suffix)
        checks: list[tuple[str, float, float, float, bool, str]] = []
        unit = str(old.get("unit", ""))
        if unit.startswith("pods/s"):
            ov, nv = _num(old, "value"), _num(new, "value")
            if ov is not None and nv is not None:
                # throughput scales WITH host speed: express the new number
                # at the old host's speed before judging it
                adj = nv * cal[0] / cal[1] if cal else nv
                checks.append(("value", ov, nv, adj, True, ""))
        for key in LATENCY_KEYS:
            ov, nv = _num(old, key), _num(new, key)
            if ov is not None and nv is not None:
                # latency scales AGAINST host speed
                adj = nv * cal[1] / cal[0] if cal else nv
                checks.append((key, ov, nv, adj, False, "s"))
        for key in DEVICE_KEYS:
            ov, nv = _num(old, key), _num(new, key)
            if ov is not None and nv is not None:
                # bytes / compile counts are host-independent: never adjust
                checks.append((key, ov, nv, nv, False, ""))
        for key, ov, nv, adj, higher_better, suf in checks:
            if higher_better:
                bad = adj < ov * (1.0 - tolerance)
            else:
                bad = adj > ov * (1.0 + tolerance) and adj - ov > 1e-9
            arrow = f"{ov:g}{suf} -> {nv:g}{suf}" + (
                f" ({(nv / ov - 1) * 100:+.1f}%)" if ov else "")
            if adj != nv:
                arrow += f" [normalized {adj:g}{suf}]"
            if bad:
                msg = f"{metric}.{key}: {arrow} exceeds {tolerance:.0%} tolerance"
                for why in (_explain(old, new), _explain_stalls(old, new)):
                    if why:
                        msg += f"; {why}"
                failures.append(msg)
        for key in OK_KEYS:
            if old.get(key) is True and new.get(key) is False:
                msg = f"{metric}.{key}: SLI target newly blown (true -> false)"
                for why in (_explain(old, new), _explain_stalls(old, new)):
                    if why:
                        msg += f"; {why}"
                failures.append(msg)
    return failures


def run_gate(old_path: str, new_path: str,
             tolerance: float = TOLERANCE) -> int:
    old_rows, new_rows = load_rows(old_path), load_rows(new_path)
    common = sorted(set(old_rows) & set(new_rows))
    if not common:
        print(f"bench-gate: no common metrics between {old_path} and "
              f"{new_path}; nothing to compare (pass)")
        return 0
    notes: list[str] = []
    failures = compare(old_rows, new_rows, tolerance, notes=notes)
    for note in notes:
        print(f"bench-gate: FLAG {note}")
    if failures:
        print(f"bench-gate: FAIL ({new_path} vs {old_path}, "
              f"{len(common)} common rows)")
        for msg in failures:
            print(f"  REGRESSION {msg}")
        return 1
    print(f"bench-gate: PASS ({new_path} vs {old_path}, "
          f"{len(common)} common rows within {tolerance:.0%})")
    return 0


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.perf.regression_gate",
        description="Fail on >tolerance regression between two BENCH "
                    "artifacts (newest two in the repo root by default)",
    )
    parser.add_argument("old", nargs="?", help="baseline artifact")
    parser.add_argument("new", nargs="?", help="candidate artifact")
    parser.add_argument("--tolerance", type=float, default=TOLERANCE)
    parser.add_argument("--root", default=".",
                        help="where to look for BENCH_* artifacts")
    args = parser.parse_args(argv)

    old_path, new_path = args.old, args.new
    if old_path is not None and new_path is not None:
        return run_gate(old_path, new_path, tolerance=args.tolerance)
    rc = 0
    for family in FAMILIES:
        arts = newest_artifacts(args.root, family=family)
        if len(arts) < 2:
            print(f"bench-gate: fewer than two {family}_* artifacts "
                  "found; nothing to compare (pass)")
            continue
        rc = max(rc, run_gate(arts[1], arts[0], tolerance=args.tolerance))
    return rc


if __name__ == "__main__":
    sys.exit(main())
