"""Standing fleet scale-out bench row: single scheduler vs fleet-of-2.

The active-active fleet (README "Scheduler fleet") is a capacity claim,
so it gets a standing bench row: the SAME 128-pod workload is scheduled
once by a single member and once by a 2-member fleet with statically
pinned shards (no lease churn — this row measures scheduling capacity,
not election overhead; the chaos fleet soak owns the churn story).

Both phases run in ONE process, so the two fleet members are driven
interleaved on one thread and the GIL would hide any wall-clock win.
The row therefore reports the scale-OUT projection the deployment
actually sees (one member per process/host): per-member BUSY seconds —
time spent inside `schedule_pending`, the only work a real member's
process would do — are accumulated separately, and the fleet's
aggregate throughput is total_pods / max(member busy seconds): the
critical-path member bounds the fleet's wall time. The single phase is
measured with the identical busy-seconds stopwatch, so the drive loop's
bookkeeping cancels out of the speedup.

The workload's pod names are chosen so the content hash splits them
64/64 across the two shards (the split is stable: blake2b, not
builtin hash()); `shard_balance` in the row keeps the split honest.
The speedup floor is 1.7x — below that, per-wave fixed costs or an
ownership-gate bug are eating the second member. The store's bind path
doubles as the double-bind oracle, same as the chaos soaks: any key
bound twice fails the row outright regardless of throughput.
"""

from __future__ import annotations

import time

SPEEDUP_FLOOR = 1.7


def _drain(schedulers, store, total: int, budget_s: float = 300.0):
    """Round-robin schedule_pending until every pod is bound; returns
    per-scheduler busy seconds (time inside schedule_pending only)."""
    busy = [0.0] * len(schedulers)
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        for i, s in enumerate(schedulers):
            t0 = time.monotonic()
            s.schedule_pending()
            busy[i] += time.monotonic() - t0
        if sum(1 for p in store.pods() if p.spec.node_name) >= total:
            break
    return busy


def run_fleet_bench(nodes: int = 16, pods: int = 128, wave_size: int = 8,
                    seed: int = 0) -> dict:
    """One single-member phase, one static fleet-of-2 phase over fresh
    stores; returns the bench row dict (never raises on a perf miss —
    `pass` carries the verdict)."""
    from ..scheduler import Profile, Scheduler
    from ..scheduler.fleet import FleetMember, shard_of
    from ..store.store import Store
    from ..testing import make_node, make_pod

    def build_store():
        store = Store()
        for i in range(nodes):
            store.create(make_node(f"fbn{i}", cpu="16", mem="32Gi",
                                   zone=f"z{i % 4}"))
        return store

    def build_scheduler(store):
        s = Scheduler(store,
                      profiles=[Profile(backend="tpu",
                                        wave_size=wave_size)],
                      seed=seed, warm_start=True)
        return s

    def traffic(store):
        # "sb-<i>" hashes 64/64 across 2 shards (see module docstring)
        for i in range(pods):
            store.create(make_pod(f"sb-{i}", cpu="100m", mem="64Mi"))

    # -- phase 1: single member --------------------------------------------
    store_a = build_store()
    single = build_scheduler(store_a)
    single.start()
    traffic(store_a)
    busy_single = _drain([single], store_a, pods)[0]
    bound_single = sum(1 for p in store_a.pods() if p.spec.node_name)
    single.informers.stop_all()

    # -- phase 2: fleet of 2, statically pinned shards ---------------------
    store_b = build_store()
    bind_ledger: dict[str, int] = {}
    orig_bind_pods, orig_bind_pod = store_b.bind_pods, store_b.bind_pod

    def ledgered_bind_pods(bindings):
        out = orig_bind_pods(bindings)
        for (key, _node), status in zip(bindings, out):
            if status == "bound":
                bind_ledger[key] = bind_ledger.get(key, 0) + 1
        return out

    def ledgered_bind_pod(key, node_name):
        obj = orig_bind_pod(key, node_name)
        bind_ledger[key] = bind_ledger.get(key, 0) + 1
        return obj

    store_b.bind_pods = ledgered_bind_pods
    store_b.bind_pod = ledgered_bind_pod

    members = []
    for i in range(2):
        m = FleetMember(build_scheduler(store_b), 2, f"bench-{i}",
                        static_shards={i})
        m.start()
        members.append(m)
    traffic(store_b)
    busy_fleet = _drain([m.scheduler for m in members], store_b, pods)
    bound_fleet = sum(1 for p in store_b.pods() if p.spec.node_name)
    double_binds = sum(1 for n in bind_ledger.values() if n > 1)
    for m in members:
        m.scheduler.informers.stop_all()

    balance = [0, 0]
    for i in range(pods):
        balance[shard_of("default", f"sb-{i}", 2)] += 1

    single_pods_s = pods / busy_single if busy_single > 0 else 0.0
    critical_path_s = max(busy_fleet)
    fleet_pods_s = pods / critical_path_s if critical_path_s > 0 else 0.0
    speedup = fleet_pods_s / single_pods_s if single_pods_s > 0 else 0.0
    ok = (speedup >= SPEEDUP_FLOOR
          and bound_single == pods and bound_fleet == pods
          and double_binds == 0)
    return {
        "metric": "fleet_scaleout_2x",
        "value": round(fleet_pods_s, 1),
        "unit": "pods/s (fleet-of-2 aggregate, busy-seconds projection)",
        "pass": ok,
        "speedup": round(speedup, 2),
        "speedup_floor": SPEEDUP_FLOOR,
        "vs_floor": round(speedup / SPEEDUP_FLOOR, 2),
        "single_pods_s": round(single_pods_s, 1),
        "member_busy_s": [round(b, 4) for b in busy_fleet],
        "single_busy_s": round(busy_single, 4),
        "shard_balance": balance,
        "double_binds": double_binds,
        "scheduled": bound_fleet,
        "nodes": nodes,
        "pods": pods,
        "wave_size": wave_size,
        "seed": seed,
    }


if __name__ == "__main__":
    import json

    from ..utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()
    print(json.dumps(run_fleet_bench()), flush=True)
