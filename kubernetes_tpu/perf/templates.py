"""Manifest-to-object converters for the perf harness.

Reference: test/integration/scheduler_perf uses real k8s YAML manifests as
pod/node templates (templates/pod-default.yaml etc.). This parses the
scheduling-relevant subset of that manifest shape into our API objects.
"""

from __future__ import annotations

from ..api.labels import LabelSelector
from ..api.meta import ObjectMeta
from ..api.types import (
    Affinity,
    Container,
    ContainerPort,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)


def _selector_terms(terms: list) -> tuple[NodeSelectorTerm, ...]:
    out = []
    for t in terms or []:
        out.append(
            NodeSelectorTerm(
                match_expressions=tuple(
                    NodeSelectorRequirement(
                        e["key"], e.get("operator", "In"), tuple(e.get("values", ()))
                    )
                    for e in t.get("matchExpressions", [])
                ),
                match_fields=tuple(
                    NodeSelectorRequirement(
                        e["key"], e.get("operator", "In"), tuple(e.get("values", ()))
                    )
                    for e in t.get("matchFields", [])
                ),
            )
        )
    return tuple(out)


def _label_selector(sel: dict | None) -> LabelSelector | None:
    if not sel:
        return None
    return LabelSelector.of(dict(sel.get("matchLabels", {})))


def _pod_affinity_terms(terms: list) -> tuple[PodAffinityTerm, ...]:
    return tuple(
        PodAffinityTerm(
            label_selector=_label_selector(t.get("labelSelector")),
            topology_key=t.get("topologyKey", ""),
            namespaces=tuple(t.get("namespaces", ())),
        )
        for t in terms or []
    )


def pod_from_manifest(manifest: dict, name: str, namespace: str = "default") -> Pod:
    """Build a Pod from a (subset) k8s manifest dict; `name` overrides
    metadata.name (the harness generates unique names per instance)."""
    meta_m = manifest.get("metadata", {})
    spec_m = manifest.get("spec", {})
    containers = []
    for c in spec_m.get("containers", [{}]):
        req = dict(c.get("resources", {}).get("requests", {}))
        ports = tuple(
            ContainerPort(
                container_port=p.get("containerPort", p.get("hostPort", 0)),
                host_port=p.get("hostPort", 0),
                protocol=p.get("protocol", "TCP"),
            )
            for p in c.get("ports", [])
        )
        containers.append(
            Container(name=c.get("name", "c"), image=c.get("image", ""),
                      requests=req, ports=ports)
        )
    affinity = None
    aff_m = spec_m.get("affinity", {})
    if aff_m:
        node_aff = None
        na = aff_m.get("nodeAffinity", {})
        if na:
            req = na.get("requiredDuringSchedulingIgnoredDuringExecution")
            required = (
                NodeSelector(terms=_selector_terms(req.get("nodeSelectorTerms")))
                if req
                else None
            )
            preferred = tuple(
                PreferredSchedulingTerm(
                    weight=p.get("weight", 1),
                    preference=_selector_terms([p.get("preference", {})])[0],
                )
                for p in na.get("preferredDuringSchedulingIgnoredDuringExecution", [])
            )
            node_aff = NodeAffinity(required=required, preferred=preferred)
        pod_aff = None
        pa = aff_m.get("podAffinity", {})
        if pa:
            pod_aff = PodAffinity(
                required=_pod_affinity_terms(
                    pa.get("requiredDuringSchedulingIgnoredDuringExecution")
                )
            )
        anti = None
        paa = aff_m.get("podAntiAffinity", {})
        if paa:
            anti = PodAntiAffinity(
                required=_pod_affinity_terms(
                    paa.get("requiredDuringSchedulingIgnoredDuringExecution")
                )
            )
        affinity = Affinity(
            node_affinity=node_aff, pod_affinity=pod_aff, pod_anti_affinity=anti
        )
    spread = tuple(
        TopologySpreadConstraint(
            max_skew=t.get("maxSkew", 1),
            topology_key=t["topologyKey"],
            when_unsatisfiable=t.get("whenUnsatisfiable", "DoNotSchedule"),
            label_selector=_label_selector(t.get("labelSelector")),
            min_domains=t.get("minDomains"),
        )
        for t in spec_m.get("topologySpreadConstraints", [])
    )
    tolerations = tuple(
        Toleration(
            key=t.get("key", ""), operator=t.get("operator", "Equal"),
            value=t.get("value", ""), effect=t.get("effect", ""),
        )
        for t in spec_m.get("tolerations", [])
    )
    return Pod(
        meta=ObjectMeta(
            name=name, namespace=namespace,
            labels=dict(meta_m.get("labels", {})),
            annotations=dict(meta_m.get("annotations", {})),
        ),
        spec=PodSpec(
            containers=containers,
            node_selector=dict(spec_m.get("nodeSelector", {})),
            affinity=affinity,
            tolerations=tolerations,
            topology_spread_constraints=spread,
            priority=spec_m.get("priority", 0),
            priority_class_name=spec_m.get("priorityClassName", ""),
        ),
    )


def node_from_manifest(manifest: dict, name: str, zone: str | None = None) -> Node:
    meta_m = manifest.get("metadata", {})
    status_m = manifest.get("status", {})
    spec_m = manifest.get("spec", {})
    labels = dict(meta_m.get("labels", {}))
    labels.setdefault("kubernetes.io/hostname", name)
    if zone is not None:
        labels["topology.kubernetes.io/zone"] = zone
    alloc = dict(
        status_m.get("allocatable")
        or {"cpu": "32", "memory": "64Gi", "pods": 110, "ephemeral-storage": "100Gi"}
    )
    taints = tuple(
        Taint(key=t["key"], value=t.get("value", ""), effect=t.get("effect", "NoSchedule"))
        for t in spec_m.get("taints", [])
    )
    return Node(
        meta=ObjectMeta(name=name, namespace="", labels=labels),
        spec=NodeSpec(unschedulable=spec_m.get("unschedulable", False), taints=taints),
        status=NodeStatus(
            capacity=dict(alloc), allocatable=alloc,
            declared_features=tuple(status_m.get("declaredFeatures", ())),
        ),
    )
