"""Arrival-trace SLI bench: replay a seeded ArrivalTrace through the REAL
scheduler loop and report the pod-scheduling SLI in VIRTUAL time.

The headline bench (bench.py) batch-dumps its pods, so its SLI is mostly
drain time. This mode replays the production load shape instead: a seeded
`testing.chaos.ArrivalTrace` ("poisson" | "burst" | "diurnal") feeds pods
into the store on a virtual tick clock, and each tick runs exactly ONE
bounded wave (`schedule_wave(wave_size)`) — fixed scheduler capacity per
virtual second, so backlog forms under bursts and the latency distribution
reflects load-vs-capacity, not host speed.

Per-pod SLI = (virtual time the bind was observed) − (trace arrival time).
Virtual time makes the headline numbers DETERMINISTIC: same seed + shape →
bit-identical trace_p50_s / trace_p99_s / sli_*_ok rows, on any machine
(`DETERMINISTIC_KEYS` below is the contract the determinism test and the
regression gate rely on). The pod latency ledger's wall-clock segment
breakdown (informer / queue_wait / kernel / bind_*) rides along under
"segments" as machine-speed diagnostics — the gate uses it to EXPLAIN a
regression, never to fail one run against another machine's clock.

Quantiles use the same inverted-CDF estimator as the ledger
(`podlatency.StreamingQuantile`), so bench rows and /metrics gauges agree.
"""

from __future__ import annotations

import json
import os
import sys

# SLI targets shared with bench.py (kept literal here so the trace mode
# has no import-order dependency on the repo-root script)
SLI_P50_TARGET_S = 4.0
SLI_P99_TARGET_S = 20.0

SHAPES = ("poisson", "burst", "diurnal")

# the fields two same-seed runs must reproduce bit-identically; everything
# else in the row (segments, wall_s) is wall-clock diagnostics
DETERMINISTIC_KEYS = (
    "metric", "value", "unit", "trace_p50_s", "trace_p99_s",
    "sli_p50_ok", "sli_p99_ok", "sli_p50_target_s", "sli_p99_target_s",
    "seed", "shape", "pods", "scheduled", "ticks",
)

# bounds the drain phase after the last arrival; generous (10k ticks = 1000
# virtual seconds at the default tick) but finite, so a scheduling bug
# yields a truthful scheduled < pods row instead of a hang
MAX_DRAIN_TICKS = 10_000


def run_trace_bench(shape: str = "poisson", seed: int = 7,
                    pods: int = 2000, nodes: int = 64,
                    wave_size: int = 16, tick_s: float = 0.1,
                    max_wave: int | None = None) -> dict:
    """Replay the trace; return one bench row (see module docstring).

    Baseline capacity is wave_size/tick_s pods per virtual second (160/s
    at the defaults) against the trace's base rate of 120/s — modest
    headroom, so burst/diurnal peaks queue and the SLI has a real tail.
    The adaptive wave-size controller works WITHIN a per-tick cap of
    `max_wave` (default wave_size*8): under a light tail it runs small
    pow2 waves, under a burst backlog it grows toward the cap — the
    load-adaptive batching this bench exists to measure. Queue depth is
    deterministic in virtual time, so the sized waves (and every
    DETERMINISTIC_KEYS field) stay bit-identical across same-seed runs.
    """
    if shape not in SHAPES:
        raise ValueError(f"shape must be one of {SHAPES}, got {shape!r}")
    if max_wave is None:
        max_wave = wave_size * 8
    from ..scheduler import Profile, Scheduler
    from ..scheduler.metrics import SchedulerMetrics
    from ..scheduler.tpu.podlatency import StreamingQuantile
    from ..store.store import Store
    from ..testing.chaos import ArrivalTrace
    from ..testing.wrappers import make_node, make_pod
    from .calibrate import host_calibration_score

    # calibrate BEFORE the workload touches the box (and before any jax
    # work heats it) — the score rides into the row at the end
    calibration = host_calibration_score()

    store = Store()
    for i in range(nodes):
        store.create(make_node(f"tb{i}", cpu="16", mem="32Gi",
                               zone=f"z{i % 4}"))
    metrics = SchedulerMetrics()
    # SYNC mode on purpose: no dispatcher threads, no wall-clock races —
    # the only clock the headline numbers see is the virtual tick counter
    sched = Scheduler(
        store,
        profiles=[Profile(backend="tpu", wave_size=wave_size)],
        metrics=metrics,
        seed=seed,
    )
    sched.start()

    trace = ArrivalTrace(seed=seed, pods=pods, shape=shape)
    arrivals = trace.arrivals()
    arrival_at = {}   # pod key -> trace arrival (virtual s)
    bound_at = {}     # pod key -> bind observation (virtual s)
    pending: set[str] = set()

    created = 0
    tick = 0
    total_ticks = int(arrivals[-1] / tick_s) + 1

    def run_tick(virtual_now: float) -> None:
        nonlocal created
        while created < len(arrivals) and arrivals[created] <= virtual_now:
            pod = make_pod(f"trace-{created}", cpu="100m", mem="64Mi")
            store.create(pod)
            arrival_at[pod.meta.key] = arrivals[created]
            pending.add(pod.meta.key)
            created += 1
        sched.pump()
        # one capped wave per tick: the adaptive controller sizes the wave
        # from queue depth, up to max_wave of virtual capacity per tick
        sched.loop.schedule_wave(max_wave, timeout=0.0)
        sched.pump()
        for pod in store.pods():
            key = pod.meta.key
            if key in pending and pod.spec.node_name:
                pending.discard(key)
                bound_at[key] = virtual_now

    for tick in range(total_ticks):
        run_tick(tick * tick_s)
    # drain: keep ticking (arrivals exhausted) until every pod is bound;
    # an empty queue flushes the in-flight wave pipeline
    drain = 0
    while pending and drain < MAX_DRAIN_TICKS:
        tick += 1
        drain += 1
        run_tick(tick * tick_s)
    sched.loop.wait_for_bindings()
    sched.pump()
    if sched.api_dispatcher is not None:
        sched.api_dispatcher.close()

    est = StreamingQuantile(window=max(len(bound_at), 1))
    for key, t_bound in bound_at.items():
        est.add(max(t_bound - arrival_at[key], 0.0))
    p50 = round(est.quantile(0.50), 4) if est.n else None
    p99 = round(est.quantile(0.99), 4) if est.n else None

    ledger = sched.flight_recorder.pod_ledger
    row = {
        "metric": f"trace_sli_{shape}",
        "value": p50,
        "unit": "s (virtual p50)",
        "trace_p50_s": p50,
        "trace_p99_s": p99,
        "sli_p50_target_s": SLI_P50_TARGET_S,
        "sli_p50_ok": p50 is not None and p50 <= SLI_P50_TARGET_S,
        "sli_p99_target_s": SLI_P99_TARGET_S,
        "sli_p99_ok": p99 is not None and p99 <= SLI_P99_TARGET_S,
        "seed": seed,
        "shape": shape,
        "pods": pods,
        "scheduled": len(bound_at),
        "ticks": tick + 1,
        "tick_s": tick_s,
        "wave_size": wave_size,
        "wave_cap": max_wave,
        "nodes": nodes,
        # streaming-waves telemetry (diagnostic: the overlap ratio weights
        # by wall-clock prep seconds, so it is machine-dependent; the
        # histogram's pad buckets come from deterministic queue depths)
        "pipeline_depth": sched.loop.pipeline_depth,
        "pipeline_overlap_ratio":
            sched.flight_recorder.pipeline_overlap_ratio(),
        "wave_size_hist": sched.flight_recorder.wave_size_histogram(),
        # wall-clock decomposition from the pod latency ledger: which
        # segment the virtual latency was spent in (diagnostic, NOT part
        # of the deterministic contract — machine-speed dependent)
        "segments": ledger.segment_quantiles(),
        "ledger_completed": ledger.completed_total,
        "ledger_dropped_open": ledger.dropped_open,
    }
    # device telemetry columns (diagnostic, not in DETERMINISTIC_KEYS:
    # compile/upload accounting can shift with kernel-shape tuning without
    # changing any scheduling decision — the gate bounds them at ±10%)
    row.update(sched.flight_recorder.device_telemetry.bench_columns(
        sched.flight_recorder.phase_snapshot().get("waves", 0)))
    # stall attribution columns (wall-clock diagnostics — NEVER added to
    # DETERMINISTIC_KEYS): when the overlap ratio collapses, stall_dominant
    # names the guilty reason right in the bench row
    row.update(sched.flight_recorder.stall_profiler.bench_columns())
    # host calibration score (measured at bench start): the gate
    # normalizes cross-box comparisons by this (perf/calibrate.py)
    row["host_calibration_score"] = calibration
    return row


def _force_cpu() -> None:
    """Trace mode always runs on CPU: the numbers are virtual-time, so an
    accelerator adds nondeterminism (device init) and no fidelity."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def _smoke() -> int:
    """make bench-smoke: a tiny 200-pod poisson trace through the full
    path, asserting the standing row keys exist and the regression gate
    passes when an artifact is compared against itself."""
    import tempfile

    from .regression_gate import run_gate

    row = run_trace_bench(shape="poisson", seed=7, pods=200)
    device_keys = ("upload_bytes_per_wave", "compile_count",
                   "mem_watermark_bytes")
    stall_keys = ("stall_dominant", "stall_coverage_p50", "stall_total_s",
                  "host_calibration_score")
    missing = [k for k in DETERMINISTIC_KEYS + ("segments",) + device_keys
               + stall_keys if k not in row]
    if missing:
        print(json.dumps({"smoke": "FAIL", "missing_keys": missing}))
        return 1
    if (row["stall_coverage_p50"] or 0.0) < 0.95:
        print(json.dumps({"smoke": "FAIL",
                          "error": "stall attribution covers "
                                   f"{row['stall_coverage_p50']!r} < 0.95 "
                                   "of per-wave wall time"}))
        return 1
    if not (row["upload_bytes_per_wave"] > 0 and row["compile_count"] > 0
            and row["mem_watermark_bytes"] > 0):
        print(json.dumps({"smoke": "FAIL",
                          "error": "device telemetry reported zero "
                                   "upload/compile/watermark — the backend "
                                   "seams are not routing through it"}))
        return 1
    if row["scheduled"] != row["pods"]:
        print(json.dumps({"smoke": "FAIL",
                          "error": f"only {row['scheduled']}/{row['pods']} "
                                   "pods scheduled"}))
        return 1
    overlap = row["pipeline_overlap_ratio"]
    if row["pipeline_depth"] > 1 and not (overlap and overlap > 0):
        print(json.dumps({"smoke": "FAIL",
                          "error": "pipeline enabled but overlap ratio is "
                                   f"{overlap!r} — host prep is not hiding "
                                   "under device waves"}))
        return 1
    with tempfile.TemporaryDirectory() as td:
        art = os.path.join(td, "BENCH_smoke.json")
        with open(art, "w") as f:
            f.write(json.dumps(row) + "\n")
        rc = run_gate(art, art)  # self-diff must be regression-free
    print(json.dumps({"smoke": "PASS" if rc == 0 else "FAIL",
                      "gate_self_rc": rc,
                      "trace_p50_s": row["trace_p50_s"],
                      "trace_p99_s": row["trace_p99_s"],
                      "sli_p50_ok": row["sli_p50_ok"],
                      "sli_p99_ok": row["sli_p99_ok"]}))
    return rc


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.perf.trace_bench",
        description="Arrival-trace SLI bench (virtual-time, deterministic)",
    )
    parser.add_argument("--trace", choices=SHAPES, default="poisson",
                        help="arrival rate curve (default poisson)")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--pods", type=int, default=2000)
    parser.add_argument("--nodes", type=int, default=64)
    parser.add_argument("--wave-size", type=int, default=16)
    parser.add_argument("--tick-s", type=float, default=0.1)
    parser.add_argument("--smoke", action="store_true",
                        help="200-pod CI smoke: key assertions + gate "
                             "self-diff (make bench-smoke)")
    args = parser.parse_args(argv)

    _force_cpu()
    if args.smoke:
        return _smoke()
    row = run_trace_bench(shape=args.trace, seed=args.seed, pods=args.pods,
                          nodes=args.nodes, wave_size=args.wave_size,
                          tick_s=args.tick_s)
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
