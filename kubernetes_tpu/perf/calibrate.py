"""Host calibration: a fixed, seeded micro-benchmark scoring the box.

ROADMAP 6(a): the same bench workload reads 993-1185 pods/s at identical
device columns across runs — host box drift, not a scheduler regression —
and every such delta costs a human judgment call at gate time. Following
the MLPerf TPU-pod methodology (normalize measurements across hosts
before comparing them), every bench artifact row is stamped with a
`host_calibration_score` measured at bench start, and
`perf/regression_gate.py` normalizes throughput/latency comparisons by
the score ratio, flagging (not failing) rows whose calibration drifted
more than CALIBRATION_DRIFT_FLAG.

The workload is deliberately boring and dependency-light: a seeded
pure-Python pass (sort / dict churn / arithmetic — the interpreter-bound
half of the scheduler's host path) plus a seeded numpy pass (matmul /
argsort — the vectorized half). No jax, no device, no network; a few ms per
repeat, best-of-N so scheduler noise on the box reads as the slow
outliers it is. Scores are relative: 1.0 is the reference box that
anchored _REFERENCE_SECONDS, >1 is faster, <1 is slower.

`wall_budget()` is the test-suite hook (tier-1 `test_scale_churn`): a
wall-clock bound calibrated on a fast box scales UP on a slower one
instead of flaking, and never scales down below the authored bound.
"""

from __future__ import annotations

import time

# Wall seconds one _microbench_once() pass takes on the reference box
# (the box that anchored the BENCH_r10 artifact row). score =
# _REFERENCE_SECONDS / measured, so the reference box scores ~1.0.
_REFERENCE_SECONDS = 0.0031

# calibration drift beyond this ratio gets FLAGGED (never failed) by the
# regression gate — past it, normalized comparisons carry real error bars
CALIBRATION_DRIFT_FLAG = 0.25

_SEED = 20260807
_PY_N = 12_000
_NP_DIM = 128

_cached_score: float | None = None


def _microbench_once(seed: int = _SEED) -> float:
    """One seeded pass; returns its wall seconds (perf_counter).

    Input data comes from a Knuth multiplicative hash, not the random
    module — scrambled enough that the sort does real work, with no rng
    stream anywhere near the scheduler's seeded tie-break (RNG01)."""
    data = [((i + seed) * 2654435761) & ((1 << 30) - 1)
            for i in range(_PY_N)]
    t0 = time.perf_counter()
    # interpreter-bound half: sort, dict churn, arithmetic
    data.sort()
    table: dict[int, int] = {}
    acc = 0
    for i, v in enumerate(data):
        table[v & 0x3FF] = i
        acc += v % 97
    acc += sum(table.values())
    # vectorized half: seeded matmul + argsort (numpy ships in the image;
    # no jax — calibration must run before any device touch)
    import numpy as np

    arr = np.random.default_rng(seed).random((_NP_DIM, _NP_DIM))
    for _ in range(4):
        arr = arr @ arr
        arr /= np.max(arr)
    order = np.argsort(arr, axis=None)
    acc += int(order[0]) + int(arr[0, 0] * 0)
    dt = time.perf_counter() - t0
    assert acc != 0  # keep the work observable
    return dt


def host_calibration_score(repeats: int = 3, refresh: bool = False) -> float:
    """Best-of-`repeats` calibration score for this host (cached per
    process — bench drivers stamp many rows from one measurement)."""
    global _cached_score
    if _cached_score is not None and not refresh:
        return _cached_score
    best = min(_microbench_once() for _ in range(max(1, repeats)))
    _cached_score = round(_REFERENCE_SECONDS / best, 4) if best > 0 else 1.0
    return _cached_score


def stamp(row: dict, score: float | None = None) -> dict:
    """Stamp `host_calibration_score` into a bench artifact row (in
    place, returned for chaining)."""
    row["host_calibration_score"] = (
        score if score is not None else host_calibration_score()
    )
    return row


def wall_budget(budget_s: float, score: float | None = None) -> float:
    """Scale an authored wall-clock budget by measured host speed: a
    slower box (score < 1) gets proportionally more time; a faster box
    keeps the authored bound (budgets never tighten below what a human
    signed off on)."""
    s = host_calibration_score() if score is None else score
    return budget_s / min(max(s, 1e-6), 1.0)


def drift_ratio(old_score: float, new_score: float) -> float:
    """Relative calibration drift between two artifact rows' scores."""
    if not old_score or not new_score:
        return 0.0
    return abs(new_score - old_score) / old_score


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.perf.calibrate",
        description="Host calibration micro-benchmark",
    )
    parser.add_argument("--repeats", type=int, default=3)
    args = parser.parse_args(argv)
    score = host_calibration_score(repeats=args.repeats, refresh=True)
    print(json.dumps({
        "host_calibration_score": score,
        "reference_seconds": _REFERENCE_SECONDS,
        "budget_example_5s": round(wall_budget(5.0, score), 3),
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
