"""Standing WarmRestart bench row: cold vs warm restart over one store.

The AOT warm-restart contract (README "Restart & recovery") is a perf
claim, so it gets a standing bench row: incarnation A cold-starts on an
empty cluster, pays its compiles, and binds traffic; incarnation B comes
up over the SAME occupied store with `warm_start=True`, pre-lowers in its
`warmup` phase, and must re-enter service compile-free —
`compile_count_since_warm() == 0` after real traffic. The row records
both incarnations' compile counts and time-to-first-bind; the suite fails
(and `make bench-gate` guards the artifact history via the
`warm_compile_count` lower-is-better key) the moment a warm restart
compiles anything.

Sized like the chaos restart soak (16 nodes, wave 8): the contract is
shape-coverage, not throughput — any post-warm compile is a bug at any
scale.
"""

from __future__ import annotations

import time


def _first_bind_s(sched, store, name: str) -> float:
    """Wall time for one pod to go queue → bound (the service re-entry
    latency the restart runbook quotes)."""
    from ..testing import make_pod

    store.create(make_pod(name, cpu="100m", mem="64Mi"))
    t0 = time.monotonic()
    sched.schedule_pending()
    dt = time.monotonic() - t0
    assert store.get("Pod", f"default/{name}").spec.node_name, name
    return dt


def run_warm_restart_bench(nodes: int = 16, pods: int = 48,
                           wave_size: int = 8, seed: int = 0) -> dict:
    """One cold incarnation, one warm restart over the same store;
    returns the bench row dict (never raises on a perf miss — `pass`
    carries the verdict)."""
    from ..scheduler import Profile, Scheduler
    from ..testing import make_node, make_pod
    from ..store.store import Store

    store = Store()
    for i in range(nodes):
        store.create(make_node(f"wr{i}", cpu="16", mem="32Gi",
                               zone=f"z{i % 4}"))

    def incarnation():
        s = Scheduler(store,
                      profiles=[Profile(backend="tpu",
                                        wave_size=wave_size)],
                      seed=seed, warm_start=True)
        t0 = time.monotonic()
        s.start()
        return s, time.monotonic() - t0

    def traffic(s, prefix):
        for i in range(pods):
            store.create(make_pod(f"{prefix}-{i}", cpu="100m", mem="64Mi"))
        s.schedule_pending()

    # incarnation A: cold store, cold jit caches (modulo the persistent
    # disk cache) — pays the tracing + lowering bill once
    a, cold_start_s = incarnation()
    tele_a = a.flight_recorder.device_telemetry
    cold_first_bind_s = _first_bind_s(a, store, "cold-first")
    traffic(a, "cold")
    cold_compiles = tele_a.compile_count()

    # crash: no drain, no flush — the corpse only stops consuming events
    a.informers.stop_all()

    # incarnation B: warm restart over the occupied store
    b, warm_start_s = incarnation()
    tele_b = b.flight_recorder.device_telemetry
    warm_first_bind_s = _first_bind_s(b, store, "warm-first")
    traffic(b, "warm")
    warm_compiles = tele_b.compile_count_since_warm()
    warmup_s = b.flight_recorder.phase_snapshot().get("warmup", 0.0)

    bound = sum(1 for p in store.pods() if p.spec.node_name)
    ok = warm_compiles == 0 and bound == 2 * pods + 2
    return {
        "metric": "warm_restart",
        "value": round(warm_first_bind_s, 4),
        "unit": "s (restart to first bind)",
        "pass": ok,
        "warm_compile_count": warm_compiles,
        "cold_compile_count": cold_compiles,
        "cold_first_bind_s": round(cold_first_bind_s, 4),
        "warm_first_bind_s": round(warm_first_bind_s, 4),
        "cold_start_s": round(cold_start_s, 4),
        "warm_start_s": round(warm_start_s, 4),
        "warmup_s": round(warmup_s, 4),
        "scheduled": bound,
        "nodes": nodes,
        "pods_per_incarnation": pods,
        "wave_size": wave_size,
        "seed": seed,
    }


if __name__ == "__main__":
    import json

    from ..utils.jaxcache import enable_persistent_cache

    enable_persistent_cache()
    print(json.dumps(run_warm_restart_bench()), flush=True)
