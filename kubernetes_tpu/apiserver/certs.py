"""Serving certificates: kubeadm's cert phase, openssl-binary form.

Reference: kubeadm's `init` generates a self-signed CA and an apiserver
serving certificate with localhost SANs (cmd/kubeadm/app/phases/certs);
the apiserver serves TLS with it and clients verify against the CA from
their kubeconfig. Here one self-signed certificate plays both roles (it
IS its own CA), generated with the system openssl binary — no third-party
Python crypto dependency.
"""

from __future__ import annotations

import os
import subprocess
import tempfile


def generate_self_signed(common_name: str = "kube-apiserver",
                         directory: str | None = None,
                         days: int = 365) -> tuple[str, str]:
    """(cert_path, key_path) for a self-signed serving cert with
    localhost/127.0.0.1 SANs. The cert doubles as the client's CA."""
    directory = directory or tempfile.mkdtemp(prefix="kube-tpu-certs-")
    cert = os.path.join(directory, "apiserver.crt")
    key = os.path.join(directory, "apiserver.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", key, "-out", cert, "-days", str(days),
            "-subj", f"/CN={common_name}",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True, capture_output=True,
    )
    os.chmod(key, 0o600)
    return cert, key
