"""Serving certificates: kubeadm's cert phase, openssl-binary form.

Reference: kubeadm's `init` generates a self-signed CA and an apiserver
serving certificate with localhost SANs (cmd/kubeadm/app/phases/certs);
the apiserver serves TLS with it and clients verify against the CA from
their kubeconfig. Here one self-signed certificate plays both roles (it
IS its own CA), generated with the system openssl binary — no third-party
Python crypto dependency.
"""

from __future__ import annotations

import os
import subprocess
import tempfile


def generate_self_signed(common_name: str = "kube-apiserver",
                         directory: str | None = None,
                         days: int = 365) -> tuple[str, str]:
    """(cert_path, key_path) for a self-signed serving cert with
    localhost/127.0.0.1 SANs. The cert doubles as the client's CA."""
    directory = directory or tempfile.mkdtemp(prefix="kube-tpu-certs-")
    cert = os.path.join(directory, "apiserver.crt")
    key = os.path.join(directory, "apiserver.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
            "-keyout", key, "-out", cert, "-days", str(days),
            "-subj", f"/CN={common_name}",
            "-addext", "subjectAltName=DNS:localhost,IP:127.0.0.1",
        ],
        check=True, capture_output=True,
    )
    os.chmod(key, 0o600)
    return cert, key


def new_key_and_csr(common_name: str, org: str = "",
                    directory: str | None = None) -> tuple[str, str]:
    """(key_path, csr_pem): a fresh RSA key + PKCS#10 CSR — what kubeadm
    join's kubelet bootstrap generates before submitting a
    CertificateSigningRequest (node identities use
    CN=system:node:<name>, O=system:nodes)."""
    directory = directory or tempfile.mkdtemp(prefix="kube-tpu-csr-")
    key = os.path.join(directory, "client.key")
    csr = os.path.join(directory, "client.csr")
    subj = f"/CN={common_name}" + (f"/O={org}" if org else "")
    subprocess.run(
        ["openssl", "req", "-new", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", csr, "-subj", subj],
        check=True, capture_output=True,
    )
    os.chmod(key, 0o600)
    with open(csr) as f:
        return key, f.read()


def sign_csr(csr_pem: str, ca_cert: str, ca_key: str,
             days: int = 365) -> str:
    """Certificate PEM for a CSR, signed by the cluster CA (the signing
    controller's openssl-binary form of
    pkg/controller/certificates/signer)."""
    with tempfile.TemporaryDirectory(prefix="kube-tpu-sign-") as d:
        csr_path = os.path.join(d, "req.csr")
        out_path = os.path.join(d, "out.crt")
        with open(csr_path, "w") as f:
            f.write(csr_pem)
        subprocess.run(
            ["openssl", "x509", "-req", "-in", csr_path,
             "-CA", ca_cert, "-CAkey", ca_key, "-CAcreateserial",
             "-out", out_path, "-days", str(days)],
            check=True, capture_output=True,
        )
        with open(out_path) as f:
            return f.read()


def verify_cert_chain(cert_pem: str, ca_cert: str) -> bool:
    """Does this certificate chain to the CA? (openssl verify)."""
    with tempfile.TemporaryDirectory(prefix="kube-tpu-verify-") as d:
        path = os.path.join(d, "check.crt")
        with open(path, "w") as f:
            f.write(cert_pem)
        out = subprocess.run(
            ["openssl", "verify", "-CAfile", ca_cert, path],
            capture_output=True,
        )
        return out.returncode == 0
