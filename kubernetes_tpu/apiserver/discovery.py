"""Discovery + OpenAPI documents, generated from the kind registry.

Reference: the apiserver serves /api and /apis group/version discovery
(APIResourceList — what kubectl uses to map kinds to endpoints) and
/openapi/v2|v3 schemas generated from the Go types. Here both documents are
reflected from the registered dataclasses: the kind registry is the
runtime.Scheme, so the discovery surface always matches what the server
actually decodes.
"""

from __future__ import annotations

import dataclasses
import types
import typing
from typing import get_args, get_origin

from ..api.serialization import kind_class

# kinds that are cluster-scoped (namespace "" convention)
CLUSTER_SCOPED = {"Node", "Namespace", "CSINode", "PodGroup", "ClusterRole",
                  "ClusterRoleBinding", "PriorityClass", "ResourceSlice",
                  "DeviceClass", "StorageClass", "PersistentVolume",
                  "CustomResourceDefinition",
                  "ValidatingWebhookConfiguration",
                  "MutatingWebhookConfiguration",
                  "ValidatingAdmissionPolicy",
                  "ValidatingAdmissionPolicyBinding",
                  "APIService", "VolumeAttachment",
                  "CertificateSigningRequest"}

_VERBS = ["create", "delete", "get", "list", "update", "watch"]


def all_kinds() -> list[str]:
    from ..api import serialization

    serialization._register_all()
    return sorted(serialization._KINDS)


def api_versions() -> dict:
    """GET /api — metav1.APIVersions."""
    return {"kind": "APIVersions", "versions": ["v1"]}


def api_resource_list() -> dict:
    """GET /api/v1 — metav1.APIResourceList."""
    return {
        "kind": "APIResourceList",
        "groupVersion": "v1",
        "resources": [
            {
                "name": kind,
                "kind": kind,
                "namespaced": kind not in CLUSTER_SCOPED,
                "verbs": list(_VERBS),
            }
            for kind in all_kinds()
        ],
    }


def _schema_for(tp, defs: dict, seen: set) -> dict:
    origin = get_origin(tp)
    if tp is type(None):
        return {}
    if tp in (int,):
        return {"type": "integer"}
    if tp in (float,):
        return {"type": "number"}
    if tp in (bool,):
        return {"type": "boolean"}
    if tp in (str,):
        return {"type": "string"}
    if origin in (list, tuple, set):
        args = [a for a in get_args(tp) if a is not Ellipsis]
        item = _schema_for(args[0], defs, seen) if args else {}
        return {"type": "array", "items": item}
    if origin is dict:
        args = get_args(tp)
        val = _schema_for(args[1], defs, seen) if len(args) == 2 else {}
        return {"type": "object", "additionalProperties": val}
    if origin is typing.Union or origin is types.UnionType:
        # both typing.Optional[X] and PEP-604 `X | None` spellings
        non_none = [a for a in get_args(tp) if a is not type(None)]
        if len(non_none) == 1:
            return _schema_for(non_none[0], defs, seen)
        return {}
    if isinstance(tp, type) and dataclasses.is_dataclass(tp):
        name = tp.__name__
        if name not in seen:
            seen.add(name)
            defs[name] = _dataclass_schema(tp, defs, seen)
        return {"$ref": f"#/definitions/{name}"}
    return {}


def _dataclass_schema(cls, defs: dict, seen: set) -> dict:
    try:
        hints = typing.get_type_hints(cls)
    except Exception:  # noqa: BLE001 - unresolvable forward ref
        hints = {}
    props = {}
    for f in dataclasses.fields(cls):
        props[f.name] = _schema_for(hints.get(f.name, str), defs, seen)
    return {"type": "object", "properties": props}


def openapi_v2() -> dict:
    """GET /openapi/v2 — a swagger doc with definitions per kind and the
    standard CRUD paths (enough for schema-aware clients and docs)."""
    defs: dict = {}
    seen: set = set()
    for kind in all_kinds():
        _schema_for(kind_class(kind), defs, seen)
    paths = {}
    for kind in all_kinds():
        paths[f"/api/v1/{kind}"] = {
            "get": {"summary": f"list {kind}",
                    "responses": {"200": {"description": "OK"}}},
            "post": {"summary": f"create a {kind}",
                     "responses": {"201": {"description": "Created"}}},
        }
        paths[f"/api/v1/{kind}/{{name}}"] = {
            "get": {"summary": f"read a {kind}",
                    "responses": {"200": {"description": "OK"}}},
            "put": {"summary": f"replace a {kind}",
                    "responses": {"200": {"description": "OK"}}},
            "delete": {"summary": f"delete a {kind}",
                       "responses": {"200": {"description": "OK"}}},
        }
    return {
        "swagger": "2.0",
        "info": {"title": "kubernetes-tpu", "version": "v1.36.0-tpu"},
        "paths": paths,
        "definitions": defs,
    }
