"""Authentication + RBAC authorization for the API server.

Reference: the generic server's handler chain runs authentication (bearer
tokens among others — staging/src/k8s.io/apiserver/pkg/authentication),
then authorization (RBAC evaluator —
plugin/pkg/auth/authorizer/rbac/rbac.go) before any handler. This module
provides both stages: a static-token authenticator (the token-file
authenticator's model) and an RBAC authorizer that evaluates store-resident
Role/ClusterRole bindings per request attribute tuple
(user, verb, resource, namespace).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..api.rbac import ClusterRole, Role

SYSTEM_MASTERS = "system:masters"
AUTHENTICATED = "system:authenticated"
UNAUTHENTICATED = "system:unauthenticated"
ANONYMOUS = "system:anonymous"


@dataclass(frozen=True)
class User:
    """authentication.k8s.io UserInfo subset."""

    name: str
    groups: tuple[str, ...] = ()


class AuthenticationError(Exception):
    """Invalid credentials (401; distinct from no credentials)."""


class ServiceAccountIssuer:
    """HMAC-signed ServiceAccount tokens (pkg/serviceaccount's
    JWTTokenGenerator role, symmetric-key form): the TokenRequest
    subresource mints them, authentication verifies signature + expiry and
    — like the reference — that the account still exists, so deleting a
    ServiceAccount revokes its tokens."""

    def __init__(self, store, key: bytes | None = None,
                 clock=None):
        import secrets as _secrets
        import time as _time

        self.store = store
        self.key = key or _secrets.token_bytes(32)
        self._now = clock or _time.time

    @staticmethod
    def _b64(data: bytes) -> str:
        import base64

        return base64.urlsafe_b64encode(data).rstrip(b"=").decode()

    def _sign(self, payload: str) -> str:
        import hashlib
        import hmac as _hmac

        return self._b64(_hmac.new(self.key, payload.encode(),
                                   hashlib.sha256).digest())

    def issue(self, namespace: str, name: str,
              expiration_seconds: int = 3600) -> str:
        import json

        from ..api.rbac import service_account_username

        # a delete racing the TokenRequest must fail the request (store
        # NotFoundError), not mint an instance-unbound token that would
        # survive recreate
        sa = self.store.get("ServiceAccount", f"{namespace}/{name}")
        payload = self._b64(json.dumps({
            "sub": service_account_username(namespace, name),
            "ns": namespace, "name": name,
            # the token binds to the account INSTANCE: delete + recreate
            # must not resurrect previously minted tokens
            # (pkg/serviceaccount claims carry the UID the same way)
            "uid": sa.meta.uid,
            "exp": self._now() + expiration_seconds,
        }, sort_keys=True).encode())
        return f"sa.{payload}.{self._sign(payload)}"

    def authenticate(self, token: str) -> User | None:
        """User for a valid SA token, None when the token isn't ours
        (callers fall through to other authenticators)."""
        import base64
        import hmac as _hmac
        import json

        if not token.startswith("sa."):
            return None
        try:
            _, payload, sig = token.split(".", 2)
        except ValueError:
            return None
        if not _hmac.compare_digest(sig, self._sign(payload)):
            raise AuthenticationError("invalid service account token")
        claims = json.loads(
            base64.urlsafe_b64decode(payload + "=" * (-len(payload) % 4))
        )
        if claims["exp"] < self._now():
            raise AuthenticationError("service account token expired")
        key = f'{claims["ns"]}/{claims["name"]}'
        sa = self.store.try_get("ServiceAccount", key)
        if sa is None:
            raise AuthenticationError(
                "service account has been deleted"
            )
        if sa.meta.uid != claims.get("uid"):
            # covers both a stale uid AND an empty/absent uid claim — a
            # token that can't prove its instance binding is rejected
            raise AuthenticationError(
                "service account token predates the current account "
                "instance"
            )
        return User(claims["sub"], (
            "system:serviceaccounts",
            f'system:serviceaccounts:{claims["ns"]}',
            AUTHENTICATED,
        ))


class TokenAuthenticator:
    """Static bearer-token table (the --token-auth-file model), optionally
    chained with a ServiceAccountIssuer (the authenticator union the
    reference builds in its authn chain).

    authenticate() returns the token's user, the anonymous user when no
    credentials are presented (anonymous-auth=true semantics), and raises
    AuthenticationError for a credential that doesn't resolve — presenting a
    bad token must not silently degrade to anonymous."""

    def __init__(self, tokens: dict[str, User] | None = None,
                 sa_issuer: "ServiceAccountIssuer | None" = None):
        self._tokens = dict(tokens or {})
        self.sa_issuer = sa_issuer

    def add_token(self, token: str, user: User) -> None:
        self._tokens[token] = user

    def authenticate(self, authorization_header: str | None) -> User:
        if not authorization_header:
            return User(ANONYMOUS, (UNAUTHENTICATED,))
        scheme, _, credential = authorization_header.partition(" ")
        if scheme.lower() != "bearer" or not credential:
            raise AuthenticationError("unsupported authorization scheme")
        credential = credential.strip()
        user = self._tokens.get(credential)
        if user is None and self.sa_issuer is not None:
            user = self.sa_issuer.authenticate(credential)
        if user is None:
            raise AuthenticationError("unknown bearer token")
        if AUTHENTICATED not in user.groups:
            user = User(user.name, user.groups + (AUTHENTICATED,))
        return user


@dataclass(frozen=True)
class Attributes:
    """The authorization request tuple (authorizer.AttributesRecord)."""

    user: User
    verb: str  # get|list|watch|create|update|delete
    resource: str  # kind name
    namespace: str = ""


class RBACAuthorizer:
    """Evaluates RBAC objects from the store per request.

    Walk order mirrors rbac.go VisitRulesFor: cluster-role bindings grant
    cluster-wide; role bindings grant within their namespace (the referenced
    role may be a Role in that namespace or a ClusterRole scoped down).
    system:masters short-circuits (the superuser group the reference
    hard-codes in bootstrap policy)."""

    def __init__(self, store):
        self.store = store

    def authorize(self, attrs: Attributes) -> bool:
        if SYSTEM_MASTERS in attrs.user.groups:
            return True
        for crb in self.store.iter_kind("ClusterRoleBinding"):
            if not any(s.matches(attrs.user) for s in crb.subjects):
                continue
            role = self.store.try_get("ClusterRole", crb.role_ref.name)
            if role and self._rules_allow(role, attrs):
                return True
        if attrs.namespace:
            for rb in self.store.iter_kind("RoleBinding"):
                if rb.meta.namespace != attrs.namespace:
                    continue
                if not any(s.matches(attrs.user) for s in rb.subjects):
                    continue
                role = self._resolve_role(rb)
                if role and self._rules_allow(role, attrs):
                    return True
        return False

    def _resolve_role(self, rb) -> Role | ClusterRole | None:
        if rb.role_ref.kind == "ClusterRole":
            return self.store.try_get("ClusterRole", rb.role_ref.name)
        return self.store.try_get(
            "Role", f"{rb.meta.namespace}/{rb.role_ref.name}"
        )

    @staticmethod
    def _rules_allow(role, attrs: Attributes) -> bool:
        return any(r.matches(attrs.verb, attrs.resource) for r in role.rules)


def bootstrap_policy() -> list:
    """The default cluster roles the reference installs at startup
    (plugin/pkg/auth/authorizer/rbac/bootstrappolicy): admin/edit/view here
    reduced to the roles our components use."""
    from ..api.meta import ObjectMeta
    from ..api.rbac import ClusterRoleBinding, PolicyRule, RoleRef, Subject

    from ..apiserver.discovery import all_kinds

    # the reference's "view" aggregate explicitly EXCLUDES secrets
    # (bootstrappolicy/policy.go: view omits secrets "to avoid escalation");
    # enumerate readable kinds from the scheme so Secret can never ride a
    # wildcard into the any-authenticated-user grant
    # "Pod/log" is the read subresource the server authorizes separately
    # (upstream's view clusterrole includes pods/log explicitly)
    viewable = tuple(k for k in all_kinds() if k != "Secret") + ("Pod/log",)
    return [
        ClusterRole(meta=ObjectMeta(name="cluster-admin", namespace=""),
                    rules=(PolicyRule(("*",), ("*",)),)),
        ClusterRole(meta=ObjectMeta(name="view", namespace=""),
                    rules=(PolicyRule(("get", "list", "watch"), viewable),)),
        ClusterRoleBinding(
            meta=ObjectMeta(name="system:authenticated-view", namespace=""),
            subjects=(Subject("Group", AUTHENTICATED),),
            role_ref=RoleRef("ClusterRole", "view"),
        ),
    ]
