"""Server-side apply subset: managedFields tracking + conflict detection.

Reference: staging/src/k8s.io/apiserver/pkg/endpoints/handlers/fieldmanager
— every applied configuration records the field set it owns in
metadata.managedFields; a second applier touching a field owned by a
different manager gets a 409 conflict naming the owner, unless it forces
(which transfers ownership); fields a manager previously owned but dropped
from its configuration are REMOVED from the object (the semantic that
distinguishes apply from a merge patch).

Associative lists (structured-merge-diff listType=map): the list fields
the reference keys — containers/initContainers/volumes/env by `name`,
ports by `container_port`, tolerations by `key` — merge BY ELEMENT.  An
element's paths are rooted at `<list>/k=<key-value>` (the fieldsV1
`k:{...}` convention), so two appliers owning different containers of one
pod never conflict, and dropping an element removes it without touching
its siblings.  Merge-key leaves (`.../k=X/name`) are element identity, not
content: co-owning them is never a conflict (every applier of an element
must state its key).

Subset notes (vs the reference's full set-theoretic fieldsV1):
- a list field is treated as keyed only when every element is a dict
  carrying the key field (a CRD's free-form `ports: [80, 443]` stays
  atomic); keys are matched by FIELD NAME, the reference's effective
  patchMergeKey convention
- ownership is tracked for Apply operations; plain updates don't record
  per-field ownership (their writes win CAS like any update)
- the wire trigger is the `fieldManager` query parameter on PATCH (the
  reference keys on the application/apply-patch+yaml content type; this
  server's content type is owned by the json/cbor wire negotiation)
"""

from __future__ import annotations

# identity/system metadata never owned by an applier
_META_SYSTEM = {"name", "namespace", "uid", "resource_version", "generation",
                "creation_timestamp", "deletion_timestamp", "managed_fields"}

# list FIELD NAME -> merge key (the reference's patchMergeKey tags:
# staging/src/k8s.io/api/core/v1/types.go Container/Volume/EnvVar `name`,
# ContainerPort `containerPort`, Toleration `key`)
_LIST_FIELD_KEYS = {
    "containers": "name",
    "init_containers": "name",
    "volumes": "name",
    "env": "name",
    "ports": "container_port",
    "tolerations": "key",
}


class ApplyConflict(Exception):
    def __init__(self, conflicts: list[tuple[str, str]]):
        self.conflicts = conflicts
        msg = "; ".join(
            f'field "{path}" is owned by manager {mgr!r}'
            for path, mgr in conflicts
        )
        super().__init__(
            f"Apply failed with {len(conflicts)} conflict(s): {msg}"
        )


def _escape(key: str) -> str:
    """RFC 6901 token escaping — map keys routinely contain '.' and '/'
    (app.kubernetes.io/name), so neither can be the raw separator."""
    return key.replace("~", "~0").replace("/", "~1")


def _unescape(token: str) -> str:
    return token.replace("~1", "/").replace("~0", "~")


def _list_key_field(field_name: str, value) -> str | None:
    """The merge key for a list VALUE under `field_name`, or None when the
    list is atomic (unknown field, empty, or elements without the key)."""
    key = _LIST_FIELD_KEYS.get(field_name)
    if key is None or not isinstance(value, (list, tuple)) or not value:
        return None
    if all(isinstance(e, dict) and e.get(key) is not None for e in value):
        return key
    return None


def field_paths(doc: dict, prefix: str = "") -> set[str]:
    """'/'-joined, RFC 6901-escaped leaf paths of an applied configuration.
    Keyed-list elements contribute their leaves under `<list>/k=<value>`;
    atomic lists are leaves; identity/system metadata and the kind tag are
    excluded."""
    out: set[str] = set()
    for k, v in doc.items():
        if prefix == "" and k in ("kind", "apiVersion"):
            continue
        if prefix == "meta" and k in _META_SYSTEM:
            continue
        path = f"{prefix}/{_escape(k)}" if prefix else _escape(k)
        if isinstance(v, dict) and v:
            # dict recursion stays here so the meta-system exclusions apply
            out |= field_paths(v, path)
        else:
            out |= _value_paths(k, v, path)
    return out


def _value_paths(field_name: str, v, path: str) -> set[str]:
    if isinstance(v, dict):
        if not v:
            return {path}
        out: set[str] = set()
        for k2, v2 in v.items():
            out |= _value_paths(k2, v2, f"{path}/{_escape(k2)}")
        return out
    key = _list_key_field(field_name, v)
    if key is not None:
        out = set()
        for e in v:
            ep = f"{path}/k={_escape(str(e[key]))}"
            sub: set[str] = set()
            for k2, v2 in e.items():
                sub |= _value_paths(k2, v2, f"{ep}/{_escape(k2)}")
            out |= sub or {ep}
        return out
    return {path}


def _walk(doc, parts: list[str], field_name: str = ""):
    """Walk escaped path segments over dicts and keyed lists; returns the
    node or a _MISSING sentinel."""
    node = doc
    for p in parts:
        if p.startswith("k=") and isinstance(node, (list, tuple)):
            kf = _LIST_FIELD_KEYS.get(field_name)
            want = _unescape(p[2:])
            node = next(
                (e for e in node
                 if isinstance(e, dict) and str(e.get(kf)) == want),
                _MISSING,
            )
        elif isinstance(node, dict):
            k = _unescape(p)
            node = node[k] if k in node else _MISSING
            field_name = k
        else:
            return _MISSING
        if node is _MISSING:
            return _MISSING
    return node


_MISSING = object()


def _get_path(doc: dict, path: str) -> tuple:
    """(value, present) at an RFC 6901-escaped '/' path (k= aware)."""
    node = _walk(doc, path.split("/"))
    return (None, False) if node is _MISSING else (node, True)


def _delete_path(doc: dict, path: str) -> None:
    parts = path.split("/")
    # parent field name for keyed-element resolution of the LEAF
    parent_field = ""
    for p in reversed(parts[:-1]):
        if not p.startswith("k="):
            parent_field = _unescape(p)
            break
    node = _walk(doc, parts[:-1])
    if node is _MISSING:
        return
    leaf = parts[-1]
    if leaf.startswith("k=") and isinstance(node, list):
        kf = _LIST_FIELD_KEYS.get(parent_field)
        want = _unescape(leaf[2:])
        node[:] = [e for e in node
                   if not (isinstance(e, dict) and str(e.get(kf)) == want)]
    elif isinstance(node, dict):
        node.pop(_unescape(leaf), None)


def _merge(base, delta, field_name: str = ""):
    """Recursive merge: dicts merge per key, keyed lists merge per element
    (base order kept, new elements appended in applied order), everything
    else replaces (atomic)."""
    key = _list_key_field(field_name, delta)
    if (key is not None and isinstance(base, (list, tuple))
            and all(isinstance(e, dict) and e.get(key) is not None
                    for e in base)):
        # STRINGIFIED keys, exactly like field_paths/_walk build k= paths:
        # a YAML-quoted "80" and an int 80 must address the same element
        # for ownership tracking and merging alike
        delta_by_key = {str(e[key]): e for e in delta}
        base_keys = {str(b[key]) for b in base}
        out = [
            _merge(b, delta_by_key[str(b[key])])
            if str(b[key]) in delta_by_key else b
            for b in base
        ]
        out.extend(e for e in delta if str(e[key]) not in base_keys)
        return out
    if not isinstance(delta, dict) or not isinstance(base, dict):
        return delta
    out = dict(base)
    for k, v in delta.items():
        out[k] = _merge(out.get(k), v, k)
    return out


def _is_merge_key_leaf(path: str) -> bool:
    """Is this path a keyed element's identity field (`.../<list>/k=X/<kf>`)?
    Identity is shared by every applier of the element — never contested."""
    parts = path.split("/")
    if len(parts) < 3 or not parts[-2].startswith("k="):
        return False
    kf = _LIST_FIELD_KEYS.get(_unescape(parts[-3]))
    return kf is not None and _unescape(parts[-1]) == kf


def _element_prefixes(path: str) -> list[str]:
    """Every keyed-element prefix along a path (`a/b/k=X` for each k=)."""
    parts = path.split("/")
    return ["/".join(parts[: j + 1])
            for j, p in enumerate(parts) if p.startswith("k=")]


def apply_doc(stored: dict | None, applied: dict, manager: str,
              force: bool = False) -> dict:
    """FieldManager.Apply: returns the merged wire document with updated
    metadata.managed_fields; raises ApplyConflict on unforced conflicts."""
    new_paths = field_paths(applied)
    meta = (stored or {}).get("meta") or {}
    mf: list[dict] = [dict(e) for e in (meta.get("managed_fields") or ())]

    # Prefix (ancestor/descendant) overlap is a conflict only when it would
    # CLOBBER — an atomic (non-dict) value replacing the other side's
    # subtree.  An empty-map leaf over another's children merges harmlessly
    # (and is how a manager retreats from a map while others keep children).
    # Only atomic new paths can clobber downward, so the prefix scan is
    # restricted to them; exact matches use a set intersection so the
    # common (no-overlap) case stays O(n).
    atomic_new = sorted(
        p for p in new_paths
        if not isinstance(_get_path(applied, p)[0], dict)
    )
    new_sorted = sorted(new_paths)

    def _stored_atomic(o: str) -> bool:
        val, ok = _get_path(stored or {}, o)
        return ok and not isinstance(val, dict)

    conflicts: list[tuple[str, str]] = []
    contested: dict[int, set[str]] = {}
    for i, entry in enumerate(mf):
        if entry.get("manager") == manager:
            continue
        owned = set(entry.get("fields") or ())
        pairs = [(p, p) for p in new_paths & owned
                 if not _is_merge_key_leaf(p)]
        # downward clobber: an atomic new value replaces o's whole subtree
        pairs += [(p, o) for p in atomic_new for o in owned
                  if o.startswith(p + "/")]
        # upward clobber: any new path landing UNDER an owned atomic value
        # replaces it with a dict (includes empty-map leaves)
        pairs += [(p, o) for p in new_sorted for o in owned
                  if p.startswith(o + "/") and _stored_atomic(o)]
        if pairs:
            contested[i] = {o for _, o in pairs}
            seen: set[str] = set()
            for p, _ in sorted(pairs):
                if p not in seen:
                    seen.add(p)
                    conflicts.append((p, entry["manager"]))
    if conflicts:
        if not force:
            raise ApplyConflict(conflicts)
        # force: ownership of the contested fields transfers to us
        for i, hit in contested.items():
            mf[i]["fields"] = sorted(
                set(mf[i].get("fields") or ()) - hit
            )

    prev = next((e for e in mf
                 if e.get("manager") == manager
                 and e.get("operation") == "Apply"), None)
    merged = _merge(dict(stored or {}), applied)

    # fields we owned but dropped from the configuration are removed —
    # unless some other manager still owns them or anything UNDER them
    # (an empty-dict leaf like "spec/affinity" must not take another
    # manager's "spec/affinity/zone" down with it)
    if prev is not None:
        others: set[str] = set()
        for entry in mf:
            if entry is not prev:
                others |= set(entry.get("fields") or ())
        # our own new paths are protected too: reshaping an owned atomic
        # path into a dict ("spec/affinity": "none" -> {"zone": ...}) drops
        # the old leaf from our set while the new config lives UNDER it —
        # deleting the ancestor would wipe the configuration just applied
        protected = others | new_paths
        emptied: set[str] = set()
        for path in sorted(set(prev.get("fields") or ()) - new_paths):
            subtree = path + "/"
            if path in protected or any(
                o.startswith(subtree) for o in protected
            ):
                continue
            if _is_merge_key_leaf(path):
                # the element's identity survives as long as ANY manager
                # keeps content in the element; with nothing protected the
                # WHOLE element goes (dropping just the key first would
                # make the element unaddressable for later deletions)
                elem = path.rsplit("/", 1)[0]
                if any(o.startswith(elem + "/") for o in protected):
                    continue
                _delete_path(merged, elem)
                continue
            _delete_path(merged, path)
            emptied.update(_element_prefixes(path))
        # only the SPECIFIC elements whose leaves we just deleted are
        # swept when fully emptied — a user's literal {} in an atomic list
        # is data, not debris (deepest first so nested empties collapse)
        for ep in sorted(emptied, key=len, reverse=True):
            val, ok = _get_path(merged, ep)
            if ok and val == {}:
                _delete_path(merged, ep)

    mf = [e for e in mf
          if not (e.get("manager") == manager
                  and e.get("operation") == "Apply")]
    mf = [e for e in mf if e.get("fields")]  # drop fully-transferred entries
    mf.append({"manager": manager, "operation": "Apply",
               "fields": sorted(new_paths)})
    merged.setdefault("meta", {})["managed_fields"] = mf
    return merged
