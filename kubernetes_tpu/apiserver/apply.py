"""Server-side apply subset: managedFields tracking + conflict detection.

Reference: staging/src/k8s.io/apiserver/pkg/endpoints/handlers/fieldmanager
— every applied configuration records the field set it owns in
metadata.managedFields; a second applier touching a field owned by a
different manager gets a 409 conflict naming the owner, unless it forces
(which transfers ownership); fields a manager previously owned but dropped
from its configuration are REMOVED from the object (the semantic that
distinguishes apply from a merge patch).

Subset notes (vs the reference's full set-theoretic fieldsV1):
- field sets are dotted leaf paths; list-valued fields are atomic (no
  associative-list merge keys), matching the reference's treatment of
  atomic lists
- ownership is tracked for Apply operations; plain updates don't record
  per-field ownership (their writes win CAS like any update)
- the wire trigger is the `fieldManager` query parameter on PATCH (the
  reference keys on the application/apply-patch+yaml content type; this
  server's content type is owned by the json/cbor wire negotiation)
"""

from __future__ import annotations

# identity/system metadata never owned by an applier
_META_SYSTEM = {"name", "namespace", "uid", "resource_version", "generation",
                "creation_timestamp", "deletion_timestamp", "managed_fields"}


class ApplyConflict(Exception):
    def __init__(self, conflicts: list[tuple[str, str]]):
        self.conflicts = conflicts
        msg = "; ".join(
            f'field "{path}" is owned by manager {mgr!r}'
            for path, mgr in conflicts
        )
        super().__init__(
            f"Apply failed with {len(conflicts)} conflict(s): {msg}"
        )


def _escape(key: str) -> str:
    """RFC 6901 token escaping — map keys routinely contain '.' and '/'
    (app.kubernetes.io/name), so neither can be the raw separator."""
    return key.replace("~", "~0").replace("/", "~1")


def _unescape(token: str) -> str:
    return token.replace("~1", "/").replace("~0", "~")


def field_paths(doc: dict, prefix: str = "") -> set[str]:
    """'/'-joined, RFC 6901-escaped leaf paths of an applied configuration;
    lists are atomic leaves, identity/system metadata and the kind tag are
    excluded."""
    out: set[str] = set()
    for k, v in doc.items():
        if prefix == "" and k in ("kind", "apiVersion"):
            continue
        if prefix == "meta" and k in _META_SYSTEM:
            continue
        path = f"{prefix}/{_escape(k)}" if prefix else _escape(k)
        if isinstance(v, dict) and v:
            out |= field_paths(v, path)
        else:
            out.add(path)
    return out


def _get_path(doc: dict, path: str) -> tuple:
    """(value, present) at an RFC 6901-escaped '/' path."""
    node = doc
    for t in path.split("/"):
        if not isinstance(node, dict):
            return None, False
        k = _unescape(t)
        if k not in node:
            return None, False
        node = node[k]
    return node, True


def _delete_path(doc: dict, path: str) -> None:
    parts = [_unescape(t) for t in path.split("/")]
    node = doc
    for p in parts[:-1]:
        node = node.get(p)
        if not isinstance(node, dict):
            return
    node.pop(parts[-1], None)


def _merge(base, delta):
    """Recursive dict merge; scalars and lists replace (atomic)."""
    if not isinstance(delta, dict) or not isinstance(base, dict):
        return delta
    out = dict(base)
    for k, v in delta.items():
        out[k] = _merge(out.get(k), v)
    return out


def apply_doc(stored: dict | None, applied: dict, manager: str,
              force: bool = False) -> dict:
    """FieldManager.Apply: returns the merged wire document with updated
    metadata.managed_fields; raises ApplyConflict on unforced conflicts."""
    new_paths = field_paths(applied)
    meta = (stored or {}).get("meta") or {}
    mf: list[dict] = [dict(e) for e in (meta.get("managed_fields") or ())]

    # Prefix (ancestor/descendant) overlap is a conflict only when it would
    # CLOBBER — an atomic (non-dict) value replacing the other side's
    # subtree.  An empty-map leaf over another's children merges harmlessly
    # (and is how a manager retreats from a map while others keep children).
    # Only atomic new paths can clobber downward, so the prefix scan is
    # restricted to them; exact matches use a set intersection so the
    # common (no-overlap) case stays O(n).
    atomic_new = sorted(
        p for p in new_paths
        if not isinstance(_get_path(applied, p)[0], dict)
    )
    new_sorted = sorted(new_paths)

    def _stored_atomic(o: str) -> bool:
        val, ok = _get_path(stored or {}, o)
        return ok and not isinstance(val, dict)

    conflicts: list[tuple[str, str]] = []
    contested: dict[int, set[str]] = {}
    for i, entry in enumerate(mf):
        if entry.get("manager") == manager:
            continue
        owned = set(entry.get("fields") or ())
        pairs = [(p, p) for p in new_paths & owned]
        # downward clobber: an atomic new value replaces o's whole subtree
        pairs += [(p, o) for p in atomic_new for o in owned
                  if o.startswith(p + "/")]
        # upward clobber: any new path landing UNDER an owned atomic value
        # replaces it with a dict (includes empty-map leaves)
        pairs += [(p, o) for p in new_sorted for o in owned
                  if p.startswith(o + "/") and _stored_atomic(o)]
        if pairs:
            contested[i] = {o for _, o in pairs}
            seen: set[str] = set()
            for p, _ in sorted(pairs):
                if p not in seen:
                    seen.add(p)
                    conflicts.append((p, entry["manager"]))
    if conflicts:
        if not force:
            raise ApplyConflict(conflicts)
        # force: ownership of the contested fields transfers to us
        for i, hit in contested.items():
            mf[i]["fields"] = sorted(
                set(mf[i].get("fields") or ()) - hit
            )

    prev = next((e for e in mf
                 if e.get("manager") == manager
                 and e.get("operation") == "Apply"), None)
    merged = _merge(dict(stored or {}), applied)

    # fields we owned but dropped from the configuration are removed —
    # unless some other manager still owns them or anything UNDER them
    # (an empty-dict leaf like "spec/affinity" must not take another
    # manager's "spec/affinity/zone" down with it)
    if prev is not None:
        others: set[str] = set()
        for entry in mf:
            if entry is not prev:
                others |= set(entry.get("fields") or ())
        # our own new paths are protected too: reshaping an owned atomic
        # path into a dict ("spec/affinity": "none" -> {"zone": ...}) drops
        # the old leaf from our set while the new config lives UNDER it —
        # deleting the ancestor would wipe the configuration just applied
        protected = others | new_paths
        for path in sorted(set(prev.get("fields") or ()) - new_paths):
            subtree = path + "/"
            if path not in protected and not any(
                o.startswith(subtree) for o in protected
            ):
                _delete_path(merged, path)

    mf = [e for e in mf
          if not (e.get("manager") == manager
                  and e.get("operation") == "Apply")]
    mf = [e for e in mf if e.get("fields")]  # drop fully-transferred entries
    mf.append({"manager": manager, "operation": "Apply",
               "fields": sorted(new_paths)})
    merged.setdefault("meta", {})["managed_fields"] = mf
    return merged
