"""API server layer (cmd/kube-apiserver + staging apiserver equivalent)."""

from .server import AdmissionError, APIServer

__all__ = ["APIServer", "AdmissionError"]
