"""In-tree admission plugins.

Reference: the apiserver's admission chain (mutating then validating,
staging/src/k8s.io/apiserver/pkg/admission) runs compiled-in plugins per
request. The two that matter for scheduling parity:

- priority (plugin/pkg/admission/priority): resolve a pod's
  priorityClassName to its numeric priority at create time (or apply the
  global-default class); reject unknown class names.
- namespace lifecycle (plugin/pkg/admission/namespace/lifecycle): refuse
  creates into a terminating or missing namespace.

Admission functions follow the server's AdmissionFn contract:
fn(operation, obj) raising AdmissionError to reject.
"""

from __future__ import annotations

from .server import AdmissionError


def cluster_scope_admission():
    """Mutating: cluster-scoped kinds carry no namespace. The ObjectMeta
    default ("default") would otherwise key a PriorityClass at
    "default/critical" where by-name lookups never find it — the apiserver
    strips namespace from cluster-scoped resources."""
    from .discovery import CLUSTER_SCOPED

    def admit(operation: str, obj) -> None:
        if operation == "CREATE" and getattr(obj, "kind", "") in CLUSTER_SCOPED:
            obj.meta.namespace = ""

    return admit


def priority_admission(store):
    """Mutating: pod.spec.priority from PriorityClass (admission.go)."""

    def admit(operation: str, obj) -> None:
        if operation != "CREATE" or getattr(obj, "kind", "") != "Pod":
            return
        name = obj.spec.priority_class_name
        if name:
            pc = store.try_get("PriorityClass", name)
            if pc is None:
                raise AdmissionError(
                    f"no PriorityClass with name {name} was found", code=422
                )
            obj.spec.priority = pc.value
            obj.spec.preemption_policy = pc.preemption_policy
            return
        if obj.spec.priority == 0:
            for pc in store.iter_kind("PriorityClass"):
                if pc.global_default:
                    obj.spec.priority = pc.value
                    obj.spec.priority_class_name = pc.meta.name
                    obj.spec.preemption_policy = pc.preemption_policy
                    return

    return admit


def namespace_lifecycle_admission(store):
    """Validating: no creates into terminating/missing namespaces. A
    namespace that was never created as an object is treated as implicit
    (tests and single-tenant flows create pods without namespace objects);
    only an EXISTING namespace in Terminating phase rejects."""

    def admit(operation: str, obj) -> None:
        if operation != "CREATE":
            return
        ns_name = getattr(obj.meta, "namespace", "")
        if not ns_name:
            return
        ns = store.try_get("Namespace", ns_name)
        if ns is not None and (ns.phase == "Terminating"
                               or ns.meta.deletion_timestamp is not None):
            raise AdmissionError(
                f"namespace {ns_name} is terminating: no new objects",
                code=403,
            )

    return admit


def default_admission_chain(store) -> list:
    """The plugins every control plane enables (mutating before
    validating, as the reference orders its chain)."""
    from ..controllers.quota import quota_admission

    return [cluster_scope_admission(), priority_admission(store),
            namespace_lifecycle_admission(store), quota_admission(store)]
