"""In-tree admission plugins.

Reference: the apiserver's admission chain (mutating then validating,
staging/src/k8s.io/apiserver/pkg/admission) runs compiled-in plugins per
request. The two that matter for scheduling parity:

- priority (plugin/pkg/admission/priority): resolve a pod's
  priorityClassName to its numeric priority at create time (or apply the
  global-default class); reject unknown class names.
- namespace lifecycle (plugin/pkg/admission/namespace/lifecycle): refuse
  creates into a terminating or missing namespace.

Admission functions follow the server's AdmissionFn contract:
fn(operation, obj) raising AdmissionError to reject.
"""

from __future__ import annotations

from .server import AdmissionError


def cluster_scope_admission():
    """Mutating: cluster-scoped kinds carry no namespace. The ObjectMeta
    default ("default") would otherwise key a PriorityClass at
    "default/critical" where by-name lookups never find it — the apiserver
    strips namespace from cluster-scoped resources."""
    from .discovery import CLUSTER_SCOPED

    def admit(operation: str, obj) -> None:
        if operation == "CREATE" and getattr(obj, "kind", "") in CLUSTER_SCOPED:
            obj.meta.namespace = ""

    return admit


def priority_admission(store):
    """Mutating: pod.spec.priority from PriorityClass (admission.go)."""

    def admit(operation: str, obj) -> None:
        if operation != "CREATE" or getattr(obj, "kind", "") != "Pod":
            return
        name = obj.spec.priority_class_name
        if name:
            pc = store.try_get("PriorityClass", name)
            if pc is None:
                raise AdmissionError(
                    f"no PriorityClass with name {name} was found", code=422
                )
            obj.spec.priority = pc.value
            obj.spec.preemption_policy = pc.preemption_policy
            return
        if obj.spec.priority == 0:
            for pc in store.iter_kind("PriorityClass"):
                if pc.global_default:
                    obj.spec.priority = pc.value
                    obj.spec.priority_class_name = pc.meta.name
                    obj.spec.preemption_policy = pc.preemption_policy
                    return

    return admit


def namespace_lifecycle_admission(store):
    """Validating: no creates into terminating/missing namespaces. A
    namespace that was never created as an object is treated as implicit
    (tests and single-tenant flows create pods without namespace objects);
    only an EXISTING namespace in Terminating phase rejects."""

    def admit(operation: str, obj) -> None:
        if operation != "CREATE":
            return
        ns_name = getattr(obj.meta, "namespace", "")
        if not ns_name:
            return
        ns = store.try_get("Namespace", ns_name)
        if ns is not None and (ns.phase == "Terminating"
                               or ns.meta.deletion_timestamp is not None):
            raise AdmissionError(
                f"namespace {ns_name} is terminating: no new objects",
                code=403,
            )

    return admit


def service_account_admission(store):
    """plugin/pkg/admission/serviceaccount (DefaultServiceAccount subset):
    default pod.spec.serviceAccountName to "default"; a pod naming a
    NON-default account that doesn't exist is rejected (the default one is
    created asynchronously by the ServiceAccount controller, so it is not
    required to exist yet — documented divergence from the reference,
    which waits for it)."""

    def admit(operation: str, obj) -> None:
        if getattr(obj, "kind", "") != "Pod":
            return
        if operation == "UPDATE":
            # pod identity is immutable (the reference's validation): an
            # update must not retarget serviceAccountName, and clearing it
            # must not erase the identity either — an empty field carries
            # the stored value forward
            stored = store.try_get("Pod", obj.meta.key)
            if stored is None:
                return
            if not obj.spec.service_account_name:
                obj.spec.service_account_name = \
                    stored.spec.service_account_name
                return
            if (stored.spec.service_account_name
                    and obj.spec.service_account_name
                    != stored.spec.service_account_name):
                raise AdmissionError(
                    "pod spec.serviceAccountName is immutable", code=422,
                )
            return
        if operation != "CREATE":
            return
        if not obj.spec.service_account_name:
            obj.spec.service_account_name = "default"
            return
        if obj.spec.service_account_name == "default":
            return
        key = f"{obj.meta.namespace}/{obj.spec.service_account_name}"
        if store.try_get("ServiceAccount", key) is None:
            raise AdmissionError(
                f"pod references service account {key} which does not "
                "exist", code=422,
            )

    return admit


def crd_admission(store):
    """apiextensions-apiserver in admission-plugin form: a
    CustomResourceDefinition CREATE validates + establishes the kind in the
    scheme (Established condition); CREATE/UPDATE of any registered custom
    kind validates the instance's spec against the CRD's structural schema
    (apiextensions pkg/apiserver/validation)."""
    from ..api.extensions import (
        CustomObject,
        validate_custom_kind,
        validate_schema,
    )

    def admit(operation: str, obj) -> None:
        if (operation == "UPDATE"
                and getattr(obj, "kind", "") == "CustomResourceDefinition"):
            try:
                validate_custom_kind(obj)
            except ValueError as e:
                raise AdmissionError(str(e), code=422)
            stored = store.try_get("CustomResourceDefinition", obj.meta.key)
            if stored is not None:
                # apiextensions: names.kind and scope are immutable — a
                # kind rename would orphan served instances and desync the
                # scheme registration
                if stored.spec.names.kind != obj.spec.names.kind:
                    raise AdmissionError(
                        "spec.names.kind is immutable", code=422)
                if stored.spec.scope != obj.spec.scope:
                    raise AdmissionError("spec.scope is immutable", code=422)
            return
        if (operation == "CREATE"
                and getattr(obj, "kind", "") == "CustomResourceDefinition"):
            try:
                validate_custom_kind(obj)
            except ValueError as e:
                raise AdmissionError(str(e), code=422)
            kind = obj.spec.names.kind
            if any(c.spec.names.kind == kind
                   and c.meta.key != obj.meta.key
                   for c in store.list_refs("CustomResourceDefinition")):
                raise AdmissionError(
                    f"kind {kind!r} is already served by another "
                    "CustomResourceDefinition", code=409)
            # registration itself happens in the server AFTER the create
            # commits — admission must be side-effect free on rejection
            obj.status["conditions"] = [
                {"type": "Established", "status": "True"}
            ]
            return
        if operation in ("CREATE", "UPDATE") and isinstance(obj, CustomObject):
            # read-only scan (list_refs): iter_kind deepcopies every CRD,
            # which puts O(CRDs) copies on the custom-object write path
            crd = next(
                (c for c in store.list_refs("CustomResourceDefinition")
                 if c.spec.names.kind == obj.kind), None,
            )
            if crd is None:
                # kind registered but its CRD is gone (deleted mid-flight)
                raise AdmissionError(
                    f"no established CustomResourceDefinition for kind "
                    f"{obj.kind!r}", code=404,
                )
            errs = validate_schema(obj.spec, crd.spec.schema)
            if errs:
                raise AdmissionError(
                    f"{obj.kind} {obj.meta.key} is invalid: "
                    + "; ".join(errs), code=422,
                )

    return admit


def cel_policy_admission(store):
    """ValidatingAdmissionPolicy (staging/src/k8s.io/apiserver/pkg/
    admission/plugin/policy/validating): CEL expressions over `object` /
    `oldObject` / `request`, evaluated for every bound policy whose match
    rules cover the request. A false expression rejects with the
    validation's message; an evaluation ERROR honors failurePolicy (Fail →
    reject, Ignore → skip), mirroring the reference's error policy. No
    webhook server involved — the policy engine runs in-process."""
    from ..api.serialization import encode
    from ..utils.cel import CELError, compile_expression

    _EXEMPT = {"ValidatingAdmissionPolicy", "ValidatingAdmissionPolicyBinding"}

    def admit(operation: str, obj) -> None:
        kind = getattr(obj, "kind", "")
        if kind in _EXEMPT:
            return
        bindings = store.list_refs("ValidatingAdmissionPolicyBinding")
        if not bindings:
            return
        ctx = None
        for b in bindings:
            if b.namespaces and getattr(obj.meta, "namespace", "") not in b.namespaces:
                continue
            policy = store.try_get("ValidatingAdmissionPolicy",
                                   b.policy_name)
            if policy is None:
                continue
            if not any(r.matches(operation, kind)
                       for r in policy.spec.match_rules):
                continue
            if ctx is None:
                old = store.try_get(kind, obj.meta.key) \
                    if operation == "UPDATE" else None
                ctx = {
                    "object": encode(obj),
                    "oldObject": encode(old) if old is not None else None,
                    "request": {"operation": operation, "kind": kind},
                }
            for v in policy.spec.validations:
                try:
                    ok = bool(compile_expression(v.expression)(ctx))
                except (CELError, TypeError, KeyError, ValueError) as e:
                    if policy.spec.failure_policy == "Ignore":
                        continue
                    raise AdmissionError(
                        f"ValidatingAdmissionPolicy {policy.meta.name!r} "
                        f"expression error: {e}", code=500,
                    )
                if not ok:
                    raise AdmissionError(
                        f"ValidatingAdmissionPolicy {policy.meta.name!r} "
                        "denied the request: "
                        + (v.message or f"failed expression: {v.expression}"),
                        code=403,
                    )

    return admit


class _WebhookCallError(Exception):
    """Transport failure OR malformed AdmissionReview response — both are
    webhook FAILURES that honor failurePolicy (the reference classifies an
    unparseable response as an error, never as a denial)."""


def _call_webhook(wh, payload: bytes) -> dict:
    """POST one AdmissionReview to a webhook; returns the validated
    `response` dict. Shared by the mutating and validating dispatchers so
    transport/response handling cannot drift between them."""
    import json as _json
    from urllib import request as _urlreq
    from urllib.error import URLError

    try:
        req = _urlreq.Request(
            wh.url, data=payload, method="POST",
            headers={"Content-Type": "application/json"},
        )
        with _urlreq.urlopen(req, timeout=wh.timeout_s) as r:
            resp = _json.loads(r.read())
    except (URLError, OSError, ValueError) as e:
        raise _WebhookCallError(f"call failed: {e}")
    if not isinstance(resp, dict) or not isinstance(
        resp.get("response"), dict
    ):
        raise _WebhookCallError(
            "malformed AdmissionReview response (missing 'response')"
        )
    return resp["response"]


def _admission_review_payload(operation: str, kind: str, obj) -> bytes:
    import json as _json

    from ..api.serialization import encode

    return _json.dumps({
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {"operation": operation, "kind": kind,
                    "object": encode(obj)},
    }).encode()


def mutating_webhook_admission(store):
    """Out-of-process MUTATING admission (staging/src/k8s.io/apiserver/pkg/
    admission/plugin/webhook/mutating): runs before the validating phase;
    an allowed response with patchType=JSONPatch applies a base64 RFC 6902
    patch to the object's wire form, and the mutated object is what every
    later plugin (and the store) sees."""
    import base64 as _b64
    import dataclasses as _dc
    import json as _json

    from ..api.extensions import apply_json_patch
    from ..api.serialization import decode, encode

    _EXEMPT = {"MutatingWebhookConfiguration",
               "ValidatingWebhookConfiguration"}

    def admit(operation: str, obj) -> None:
        kind = getattr(obj, "kind", "")
        if kind in _EXEMPT:
            return
        for cfg in store.list_refs("MutatingWebhookConfiguration"):
            for wh in cfg.webhooks:
                if not any(r.matches(operation, kind) for r in wh.rules):
                    continue
                try:
                    # re-encode per webhook: each sees its predecessors'
                    # patches (the reference's sequential mutating dispatch)
                    result = _call_webhook(
                        wh, _admission_review_payload(operation, kind, obj)
                    )
                except _WebhookCallError as e:
                    if wh.failure_policy == "Ignore":
                        continue
                    raise AdmissionError(
                        f"mutating webhook {wh.name!r} {e}", code=500,
                    )
                if not result.get("allowed", False):
                    msg = (result.get("status") or {}).get("message", "denied")
                    raise AdmissionError(
                        f"mutating webhook {wh.name!r} denied the request: "
                        f"{msg}", code=403,
                    )
                if result.get("patch"):
                    if result.get("patchType", "JSONPatch") != "JSONPatch":
                        raise AdmissionError(
                            f"mutating webhook {wh.name!r}: unsupported "
                            f"patchType {result.get('patchType')!r}", code=500,
                        )
                    try:
                        original = encode(obj)
                        patch = _json.loads(_b64.b64decode(result["patch"]))
                        patched = apply_json_patch(original, patch)
                        # identity AND system metadata are not a webhook's
                        # to change (the reference rejects such patches):
                        # uid/resourceVersion/managedFields forgeries would
                        # break GC identity, CAS, and SSA ownership
                        patched.setdefault("meta", {})
                        patched["kind"] = kind
                        patched["meta"]["name"] = obj.meta.name
                        patched["meta"]["namespace"] = obj.meta.namespace
                        orig_meta = original.get("meta", {})
                        for sysf in ("uid", "resource_version", "generation",
                                     "creation_timestamp",
                                     "deletion_timestamp", "managed_fields"):
                            if sysf in orig_meta:
                                patched["meta"][sysf] = orig_meta[sysf]
                            else:
                                patched["meta"].pop(sysf, None)
                        mutated = decode(patched)
                    except (ValueError, TypeError, KeyError, IndexError,
                            AttributeError) as e:
                        raise AdmissionError(
                            f"mutating webhook {wh.name!r} returned an "
                            f"unusable patch: {e}", code=500,
                        )
                    # mutate IN PLACE: later chain plugins and the store
                    # hold this object reference
                    for f in _dc.fields(obj):
                        setattr(obj, f.name, getattr(mutated, f.name))

    return admit


def webhook_admission(store):
    """Out-of-process validating admission
    (staging/src/k8s.io/apiserver/pkg/admission/plugin/webhook): each
    matching webhook gets an AdmissionReview POST; allowed=false rejects
    the request, call failures honor failurePolicy (Fail → reject,
    Ignore → skip). Webhook configurations themselves are exempt so a
    broken webhook can always be fixed (the reference's bootstrap
    safeguard)."""

    def admit(operation: str, obj) -> None:
        kind = getattr(obj, "kind", "")
        if kind in ("ValidatingWebhookConfiguration",
                    "MutatingWebhookConfiguration"):
            return
        payload = None
        for cfg in store.iter_kind("ValidatingWebhookConfiguration"):
            for wh in cfg.webhooks:
                if not any(r.matches(operation, kind) for r in wh.rules):
                    continue
                if payload is None:
                    payload = _admission_review_payload(operation, kind, obj)
                try:
                    result = _call_webhook(wh, payload)
                except _WebhookCallError as e:
                    if wh.failure_policy == "Ignore":
                        continue
                    raise AdmissionError(
                        f"admission webhook {wh.name!r} {e}", code=500,
                    )
                if not result.get("allowed", False):
                    msg = (result.get("status") or {}).get("message", "denied")
                    raise AdmissionError(
                        f"admission webhook {wh.name!r} denied the request: "
                        f"{msg}", code=403,
                    )

    return admit


def default_admission_chain(store) -> list:
    """The plugins every control plane enables, in the reference's order:
    built-in mutators → MutatingAdmissionWebhook (last mutator) →
    built-in validators → ValidatingAdmissionPolicy (CEL) →
    ValidatingAdmissionWebhook (cmd/kube-apiserver admission ordering)."""
    from ..controllers.quota import quota_admission

    return [cluster_scope_admission(), priority_admission(store),
            namespace_lifecycle_admission(store),
            service_account_admission(store),
            mutating_webhook_admission(store),
            crd_admission(store),
            quota_admission(store),
            cel_policy_admission(store),
            webhook_admission(store)]
