"""The aggregation layer: /apis/<group>/<version> proxying + the metrics
delegate (the repo's own first aggregated API).

Reference: staging/src/k8s.io/kube-aggregator — proxy_handler.go forwards
the verbatim request to the APIService's backing service and streams the
response back; apiserver availability is surfaced as the Available
condition; /apis discovery merges every registered group
(apiservice_controller + handler_apis.go).

The metrics delegate mirrors metrics-server's surface
(/apis/metrics.k8s.io/v1beta1 nodes + pods, the canonical aggregated API):
usage here is the request-based accounting our in-memory CRI tracks — the
point is the aggregation CONTRACT (an out-of-process group mounted through
the main server), not cadvisor parity.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib import request as _urlreq
from urllib.error import HTTPError, URLError


def find_apiservice(store, group: str, version: str):
    """The APIService covering group/version, by the reference's
    '<version>.<group>' naming convention (falls back to a field scan so a
    misnamed object still resolves)."""
    svc = store.try_get("APIService", f"{version}.{group}")
    if svc is not None:
        return svc
    for svc in store.list_refs("APIService"):
        if svc.spec.group == group and svc.spec.version == version:
            return svc
    return None


def proxy_request(svc, method: str, path: str, query: str, body: bytes,
                  content_type: str, user: str = "",
                  timeout_s: float = 10.0):
    """Forward one request to the delegate; returns (code, ctype, body).
    Raises URLError/OSError for unreachable delegates (callers map that to
    503 + the Available=False condition, aggregator semantics)."""
    url = svc.spec.service_url.rstrip("/") + path
    if query:
        url += f"?{query}"
    headers = {"Content-Type": content_type or "application/json"}
    if user:
        # the reference forwards authenticated identity via X-Remote-User
        # (request header authn on the delegate side)
        headers["X-Remote-User"] = user
    req = _urlreq.Request(url, data=body if body else None, method=method,
                          headers=headers)
    try:
        with _urlreq.urlopen(req, timeout=timeout_s) as r:
            return (r.status, r.headers.get("Content-Type",
                                            "application/json"), r.read())
    except HTTPError as e:
        # delegate answered with an error status: proxy it verbatim
        return (e.code, e.headers.get("Content-Type", "application/json"),
                e.read())


def api_group_list(store) -> dict:
    """GET /apis — metav1.APIGroupList merged from registered APIServices
    (handler_apis.go)."""
    groups: dict[str, list[str]] = {}
    for svc in store.list_refs("APIService"):
        groups.setdefault(svc.spec.group, []).append(svc.spec.version)
    return {
        "kind": "APIGroupList",
        "groups": [
            {
                "name": g,
                "versions": [
                    {"groupVersion": f"{g}/{v}", "version": v}
                    for v in sorted(vs)
                ],
                "preferredVersion": {
                    "groupVersion": f"{g}/{sorted(vs)[0]}",
                    "version": sorted(vs)[0],
                },
            }
            for g, vs in sorted(groups.items())
        ],
    }


def set_available_condition(store, svc, available: bool, message: str) -> None:
    """Surface delegate reachability as the Available condition
    (apiservice status controller). Best-effort: a CAS race just means a
    fresher writer won."""
    want = "True" if available else "False"
    try:
        # cheap unchanged check first — this runs per proxied request
        ref = next((s for s in store.list_refs("APIService")
                    if s.meta.key == svc.meta.key), None)
        if ref is None:
            return
        conds = ref.status.get("conditions") or []
        if any(c.get("type") == "Available" and c.get("status") == want
               for c in conds):
            return
        cur = store.try_get("APIService", svc.meta.key)
        if cur is None:
            return
        cur.status["conditions"] = [{
            "type": "Available",
            "status": want,
            "message": message,
        }]
        store.update(cur, check_version=False)
    except Exception:  # noqa: BLE001 - status is advisory
        pass


# -- the metrics delegate ----------------------------------------------------

METRICS_GROUP = "metrics.k8s.io"
METRICS_VERSION = "v1beta1"


class MetricsAPIServer:
    """An out-of-process-style aggregated API server (metrics-server's
    role): its own HTTP listener serving the metrics.k8s.io/v1beta1 group,
    reading cluster state from the store. Mounted into the main server by
    creating an APIService pointing at `url`."""

    def __init__(self, store):
        self.store = store
        self._httpd: ThreadingHTTPServer | None = None

    # usage source: the kubelet-published PodMetrics objects (the SAME
    # pipeline the HPA consumes — one truth for both surfaces); pods whose
    # kubelet hasn't published yet fall back to request-based accounting
    # so fresh clusters still report something deterministic
    def _usage_of(self, pod, names) -> tuple[int, int]:
        """(milli-CPU, MiB) for one scheduled pod."""
        from ..scheduler.nodeinfo import PodInfo

        pm = self.store.try_get("PodMetrics", pod.meta.key)
        if pm is not None:
            return pm.cpu_usage_milli, pm.memory_usage_bytes >> 20
        pi = PodInfo(pod, names)
        return pi.request.v[0], pi.request.v[1]

    def node_metrics(self) -> dict:
        from ..api.resource import ResourceNames

        names = ResourceNames()
        usage: dict[str, list] = {}
        for pod in self.store.list_refs("Pod"):
            node = pod.spec.node_name
            if not node:
                continue
            cpu, mem = self._usage_of(pod, names)
            u = usage.setdefault(node, [0, 0])
            u[0] += cpu
            u[1] += mem
        items = []
        for node in self.store.list_refs("Node"):
            u = usage.get(node.meta.name, [0, 0])
            items.append({
                "metadata": {"name": node.meta.name},
                "usage": {"cpu": f"{u[0]}m", "memory": f"{u[1]}Mi"},
            })
        return {"kind": "NodeMetricsList",
                "apiVersion": f"{METRICS_GROUP}/{METRICS_VERSION}",
                "items": items}

    def pod_metrics(self, namespace: str = "") -> dict:
        from ..api.resource import ResourceNames

        names = ResourceNames()
        items = []
        for pod in self.store.list_refs("Pod"):
            if not pod.spec.node_name:
                continue
            if namespace and pod.meta.namespace != namespace:
                continue
            cpu, mem = self._usage_of(pod, names)
            items.append({
                "metadata": {"name": pod.meta.name,
                             "namespace": pod.meta.namespace},
                "containers": [{
                    "name": c.name,
                    "usage": {"cpu": f"{cpu}m", "memory": f"{mem}Mi"},
                } for c in pod.spec.containers],
            })
        return {"kind": "PodMetricsList",
                "apiVersion": f"{METRICS_GROUP}/{METRICS_VERSION}",
                "items": items}

    def resource_list(self) -> dict:
        return {
            "kind": "APIResourceList",
            "groupVersion": f"{METRICS_GROUP}/{METRICS_VERSION}",
            "resources": [
                {"name": "nodes", "kind": "NodeMetrics", "namespaced": False,
                 "verbs": ["get", "list"]},
                {"name": "pods", "kind": "PodMetrics", "namespaced": True,
                 "verbs": ["get", "list"]},
            ],
        }

    def serve(self, port: int = 0) -> None:
        delegate = self

        class Handler(BaseHTTPRequestHandler):
            def _json(self, code: int, doc: dict) -> None:
                data = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _not_found(self):
                self._json(404, {"kind": "Status", "status": "Failure",
                                 "reason": "NotFound", "code": 404})

            def _one_of(self, doc: dict, name: str, ns: str = "") -> None:
                for item in doc["items"]:
                    m = item["metadata"]
                    if m["name"] == name and (not ns
                                              or m.get("namespace") == ns):
                        self._json(200, item)
                        return
                self._not_found()

            def do_GET(self):
                base = f"/apis/{METRICS_GROUP}/{METRICS_VERSION}"
                path = self.path.split("?", 1)[0].rstrip("/")
                if not path.startswith(base):
                    self._not_found()
                    return
                rest = [p for p in path[len(base):].split("/") if p]
                # metrics-server surface: nodes[/name], pods[/name],
                # namespaces/<ns>/pods[/<name>]
                if not rest:
                    self._json(200, delegate.resource_list())
                elif rest[0] == "nodes":
                    if len(rest) == 1:
                        self._json(200, delegate.node_metrics())
                    else:
                        self._one_of(delegate.node_metrics(), rest[1])
                elif rest[0] == "pods":
                    if len(rest) == 1:
                        self._json(200, delegate.pod_metrics())
                    else:
                        self._one_of(delegate.pod_metrics(), rest[1])
                elif rest[0] == "namespaces" and len(rest) >= 3 \
                        and rest[2] == "pods":
                    ns = rest[1]
                    if len(rest) == 3:
                        self._json(200, delegate.pod_metrics(namespace=ns))
                    else:
                        self._one_of(delegate.pod_metrics(namespace=ns),
                                     rest[3], ns)
                else:
                    self._not_found()

            def log_message(self, *a):  # noqa: N802
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()

    @property
    def url(self) -> str:
        assert self._httpd is not None
        return f"http://127.0.0.1:{self._httpd.server_port}"

    def shutdown(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()


def register_metrics_apiservice(store, delegate: MetricsAPIServer):
    """Create the APIService mounting the metrics delegate (what
    metrics-server's manifest does)."""
    from ..api.meta import ObjectMeta
    from ..api.registration import APIService, APIServiceSpec

    svc = APIService(
        meta=ObjectMeta(
            name=APIService.expected_name(METRICS_GROUP, METRICS_VERSION),
            namespace="",
        ),
        spec=APIServiceSpec(group=METRICS_GROUP, version=METRICS_VERSION,
                            service_url=delegate.url),
    )
    if store.try_get("APIService", svc.meta.key) is None:
        store.create(svc)
    return svc
