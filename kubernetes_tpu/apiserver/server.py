"""API server: REST + watch streaming over the versioned store.

Reference: cmd/kube-apiserver + staging/src/k8s.io/apiserver — the server
chain (CreateServerChain, cmd/kube-apiserver/app/server.go:176) collapses to
one handler here because aggregation/apiextensions don't apply; what is
preserved is the resource REST contract every component programs against:

  GET    /api/v1/{kind}                          list (+ ?watch=1&resourceVersion=N)
  GET    /api/v1/{kind}/{key...}                 get
  POST   /api/v1/{kind}                          create
  PUT    /api/v1/{kind}/{key...}                 update (resourceVersion CAS -> 409)
  DELETE /api/v1/{kind}/{key...}                 delete
  POST   /api/v1/{kind}/{key...}/binding         pod binding subresource

Watch responses stream JSON lines ({"type": ADDED|MODIFIED|DELETED,
"object": ...}) exactly like the reference's watch event frames. The etcd3
storage.Interface role is played by store.Store; the watch cache is the
store's per-kind event fan-out.

An admission-plugin chain runs on create/update (mutating + validating),
mirroring the generic server's handler chain (authn/authz are pluggable
no-ops by default — in-tree clients are trusted the way localhost:8080
insecure serving was).
"""

from __future__ import annotations

import json
import re
import threading
import time as _time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable
from urllib.parse import parse_qs, urlparse

from ..api.serialization import decode, encode, kind_class
from ..store.store import (
    AlreadyExistsError,
    CompactedError,
    ConflictError,
    NotFoundError,
    Store,
)

# admission: fn(operation, obj) -> None | raises AdmissionError
AdmissionFn = Callable[[str, object], None]

# field-selector paths the reference supports per resource, generalized:
# dotted attribute walk over the object (metadata.* maps to meta.*)
_FIELD_ALIASES = {
    "metadata.name": ("meta", "name"),
    "metadata.namespace": ("meta", "namespace"),
    "spec.nodeName": ("spec", "node_name"),
    "spec.schedulerName": ("spec", "scheduler_name"),
    "status.phase": ("status", "phase"),
}


_LABEL_TOKEN = r"[A-Za-z0-9]([-A-Za-z0-9_./]*[A-Za-z0-9])?"


_LABEL_TOKEN_RE = re.compile(f"^{_LABEL_TOKEN}$")

# sentinel user for insecure serving (no authenticator configured): the
# whole authn/authz chain is off, every request is trusted
_TRUSTED = object()


class AuditLog:
    """The audit stage of the handler chain (staging/.../apiserver/pkg/
    audit): one entry per request — who did what to which resource with
    what outcome — kept in a bounded ring and streamed to an optional sink
    (the audit-webhook/log-backend role)."""

    def __init__(self, capacity: int = 1024, sink=None):
        import collections

        self.entries = collections.deque(maxlen=capacity)
        self.sink = sink
        self._lock = threading.Lock()

    def record(self, user: str, verb: str, resource: str, key: str,
               code: int) -> None:
        entry = {"user": user, "verb": verb, "resource": resource,
                 "key": key, "code": code, "ts": _time.time()}
        with self._lock:
            self.entries.append(entry)
        if self.sink is not None:
            self.sink(entry)

    def find(self, **match) -> list[dict]:
        with self._lock:
            return [e for e in self.entries
                    if all(e.get(k) == v for k, v in match.items())]


def parse_label_selector(expr: str) -> list[tuple[str, str, str]]:
    """'k=v,k2!=v2,k3' → [(key, op, value)]; op ∈ {'=', '!=', 'exists'}.

    Strict on syntax: the set-based forms ('k in (a,b)', gt/lt) the
    reference ALSO accepts are not implemented here — they raise
    ValueError (→ 400) rather than silently matching nothing."""
    token = _LABEL_TOKEN_RE
    out = []
    for part in expr.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            k, _, v = part.partition("!=")
            op = "!="
        elif "=" in part:
            k, _, v = part.partition("==" if "==" in part else "=")
            v = v.lstrip("=")
            op = "="
        else:
            k, v, op = part, "", "exists"
        k, v = k.strip(), v.strip()
        if not token.match(k) or (v and not token.match(v)):
            raise ValueError(f"unsupported label selector part {part!r}")
        out.append((k, op, v))
    return out


def matches_label_selector(obj, sel: list[tuple[str, str, str]]) -> bool:
    labels = getattr(obj.meta, "labels", {}) or {}
    for k, op, v in sel:
        if op == "exists":
            if k not in labels:
                return False
        elif op == "=":
            if labels.get(k) != v:
                return False
        elif labels.get(k) == v:  # !=
            return False
    return True


def parse_field_selector(expr: str) -> list[tuple[tuple[str, ...], bool, str]]:
    """'spec.nodeName=n1,metadata.name!=x' → [(attr path, negated, value)].
    Unknown fields raise ValueError (the reference 400s them). Parsed ONCE
    per request; matching is pure attribute walks."""
    out = []
    for part in expr.split(","):
        part = part.strip()
        if not part:
            continue
        neg = "!=" in part
        k, _, v = part.partition("!=" if neg else "=")
        path = _FIELD_ALIASES.get(k.strip())
        if path is None:
            raise ValueError(f"unsupported field selector {k.strip()!r}")
        out.append((path, neg, v.strip()))
    return out


def matches_field_selector(obj, sel: list[tuple[tuple[str, ...], bool, str]]) -> bool:
    for path, neg, v in sel:
        cur = obj
        for attr in path:
            cur = getattr(cur, attr, None)
            if cur is None:
                break
        got = "" if cur is None else str(cur)
        if neg:
            if got == v:
                return False
        elif got != v:
            return False
    return True


class AdmissionError(Exception):
    def __init__(self, message: str, code: int = 422):
        super().__init__(message)
        self.code = code


class APIServer:
    def __init__(self, store: Store, admission: list[AdmissionFn] | None = None,
                 authenticator=None, authorizer=None, tracer=None,
                 audit: AuditLog | None = None, metrics=None):
        """authenticator/authorizer None = the chain stage is skipped
        (insecure localhost serving, the in-tree trust model); passing a
        TokenAuthenticator + RBACAuthorizer (apiserver/auth.py) turns on
        the generic server's authn→authz handler-chain stages. tracer (a
        utils.tracing.Tracer) emits one span per request — the request-
        filter spans of component-base/tracing. Every API request is
        audit-logged (who/verb/resource/outcome) to `audit`. metrics (a
        utils.metrics.Registry or any object with expose()) serves its text
        exposition at /metrics next to /debug/pprof/profile — the
        routes.DefaultMetrics + routes.Profiling debug surface."""
        self.store = store
        self.tracer = tracer
        self.metrics = metrics
        self.audit = audit or AuditLog()
        self.admission = list(admission or [])
        self.authenticator = authenticator
        self.authorizer = authorizer
        # versioned-conversion scheme: wire objects carrying an apiVersion
        # other than v1 are converted at the codec boundary (runtime.Scheme)
        from ..api.versioning import default_scheme

        self.scheme = default_scheme()
        # serializes admission+create per namespace: quota admission checks
        # live usage, and with ThreadingHTTPServer two concurrent creates in
        # one namespace could otherwise both pass the check and both commit
        # (the reference serializes via CAS on ResourceQuota status)
        self._create_locks: dict[str, threading.Lock] = {}
        self._create_locks_mu = threading.Lock()
        # re-establish dynamic kinds from a pre-populated store (restart
        # from snapshot: the scheme must serve existing CRDs immediately,
        # as apiextensions does on startup)
        from ..api.extensions import register_custom_kind

        for crd in store.iter_kind("CustomResourceDefinition"):
            register_custom_kind(crd)
        self._http: ThreadingHTTPServer | None = None
        self.port = 0

    # -- request handling ----------------------------------------------------

    def _build_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _wants_cbor(self) -> bool:
                return "application/cbor" in (self.headers.get("Accept") or "")

            def _send_json(self, code: int, payload) -> None:
                """Content-negotiated object response: CBOR when the client
                Accepts it (the binary serializer role of apimachinery's
                protobuf/CBOR formats), JSON otherwise."""
                if self._wants_cbor():
                    from ..api import cbor

                    data = cbor.dumps(payload)
                    ctype = "application/cbor"
                else:
                    data = json.dumps(payload).encode()
                    ctype = "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _error(self, code: int, reason: str, message: str) -> None:
                # metav1.Status error shape
                self._send_json(code, {
                    "kind": "Status", "status": "Failure",
                    "reason": reason, "message": message, "code": code,
                })

            def _route(self):
                parsed = urlparse(self.path)
                parts = [p for p in parsed.path.split("/") if p]
                query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                # /api/v1/{kind}[/{ns or name}[/{name}[/{subresource}]]]
                if len(parts) < 3 or parts[0] != "api" or parts[1] != "v1":
                    return None
                kind = parts[2]
                rest = parts[3:]
                sub = ""
                if rest and rest[-1] in ("binding", "status", "log",
                                         "token"):
                    # subresource only when a full object key PRECEDES the
                    # suffix (ns/name, or bare name for cluster-scoped) —
                    # otherwise a pod literally named "log" is unreachable
                    from .discovery import CLUSTER_SCOPED

                    expect = 1 if kind in CLUSTER_SCOPED else 2
                    if len(rest) == expect + 1:
                        sub = rest[-1]
                        rest = rest[:-1]
                key = "/".join(rest)
                return kind, key, sub, query

            def _read_body(self):
                length = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(length) if length else b""
                if not raw:
                    return {}
                ctype = self.headers.get("Content-Type") or ""
                if "application/cbor" in ctype:
                    from ..api import cbor

                    return cbor.loads(raw)
                return json.loads(raw)

            def _authenticate(self):
                """Run the authn stage; returns the user, or None after
                having sent the 401. A None authenticator means the chain
                is off (insecure serving) — returns the trusted marker."""
                from .auth import AuthenticationError

                if server.authenticator is None:
                    return _TRUSTED
                try:
                    user = server.authenticator.authenticate(
                        self.headers.get("Authorization")
                    )
                    self._audit_user = user.name
                    return user
                except AuthenticationError as e:
                    self._error(401, "Unauthorized", str(e))
                    return None

            def _authorized(self, verb: str, kind: str, key: str,
                            namespace: str | None = None) -> bool:
                """authn → authz chain stages (generic server handler
                chain); sends the 401/403 itself when the request fails.
                namespace overrides the key-derived one (creates carry the
                namespace in the body, not the flat URL)."""
                user = self._authenticate()
                if user is None:
                    return False
                if user is _TRUSTED or server.authorizer is None:
                    return True
                from .auth import Attributes

                if namespace is None:
                    namespace = key.split("/", 1)[0] if "/" in key else ""
                ok = server.authorizer.authorize(
                    Attributes(user=user, verb=verb, resource=kind,
                               namespace=namespace)
                )
                if not ok:
                    self._error(
                        403, "Forbidden",
                        f'user "{user.name}" cannot {verb} resource "{kind}"',
                    )
                return ok

            def _proxy_pod_logs(self, key: str, query: dict) -> None:
                from urllib.request import urlopen

                try:
                    pod = server.store.get("Pod", key)
                except NotFoundError:
                    self._error(404, "NotFound", f"pod {key} not found")
                    return
                if not pod.spec.node_name:
                    self._error(400, "BadRequest", "pod is not scheduled")
                    return
                try:
                    node = server.store.get("Node", pod.spec.node_name)
                except NotFoundError:
                    self._error(404, "NotFound", "pod's node is gone")
                    return
                port = node.status.daemon_endpoint_port
                if not port:
                    self._error(503, "ServiceUnavailable",
                                "node's kubelet endpoint is unknown")
                    return
                container = query.get("container", "")
                ns, name = key.split("/", 1)
                url = (f"http://127.0.0.1:{port}/containerLogs/"
                       f"{ns}/{name}/{container}")
                if query.get("tailLines"):
                    url += f"?tailLines={query['tailLines']}"
                try:
                    with urlopen(url, timeout=10) as resp:
                        body = resp.read()
                        code = resp.status
                except Exception as e:  # noqa: BLE001 - proxied verbatim
                    import urllib.error

                    if isinstance(e, urllib.error.HTTPError):
                        body, code = e.read(), e.code
                    else:
                        self._error(502, "BadGateway", f"kubelet: {e}")
                        return
                self.send_response(code)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _handle_aggregated(self) -> None:
                """The aggregation layer (kube-aggregator's role, the FIRST
                server in the reference's delegation chain): everything
                under /apis/ resolves through APIService objects — group
                discovery is merged here, resource requests proxy verbatim
                to the registered delegate, and delegate reachability is
                surfaced as the Available condition (503 when down)."""
                from urllib.error import URLError

                from . import aggregator
                from .auth import ANONYMOUS

                # drain the request body FIRST: every early-exit response
                # below would otherwise desync a keep-alive connection
                # (unread body bytes parse as the next request line)
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                user = self._authenticate()
                if user is None:
                    return
                uname = "" if user is _TRUSTED else user.name
                if user is not _TRUSTED and user.name == ANONYMOUS:
                    self._error(403, "Forbidden",
                                "discovery requires authentication")
                    return
                parsed = urlparse(self.path)
                parts = [p for p in parsed.path.split("/") if p]
                if len(parts) == 1:  # GET /apis
                    self._send_json(
                        200, aggregator.api_group_list(server.store))
                    return
                group = parts[1]
                if len(parts) == 2:  # GET /apis/<group>
                    doc = aggregator.api_group_list(server.store)
                    g = next((x for x in doc["groups"]
                              if x["name"] == group), None)
                    if g is None:
                        self._error(404, "NotFound",
                                    f"no APIService serves group {group!r}")
                        return
                    self._send_json(200, {"kind": "APIGroup", **g})
                    return
                version = parts[2]
                # RBAC runs HERE, before the proxy: the delegate trusts the
                # forwarded identity, so an unauthorized verb must never
                # reach it (verb mapping mirrors the native routes; the
                # resource attribute is the aggregated group)
                verb = {"GET": "get", "POST": "create", "PUT": "update",
                        "PATCH": "patch", "DELETE": "delete"}.get(
                            self.command, self.command.lower())
                if verb == "get" and len(parts) == 4:
                    verb = "list"
                if not self._authorized(verb, group, "/".join(parts[3:])):
                    return
                svc = aggregator.find_apiservice(server.store, group, version)
                if svc is None:
                    self._error(404, "NotFound",
                                f"no APIService for {group}/{version}")
                    return
                if not svc.spec.service_url:
                    self._error(503, "ServiceUnavailable",
                                f"APIService {svc.meta.name} has no service"
                                " reference")
                    return
                try:
                    code, ctype, data = aggregator.proxy_request(
                        svc, self.command, parsed.path, parsed.query, body,
                        self.headers.get("Content-Type", ""), uname,
                    )
                except (URLError, OSError, ValueError) as e:
                    aggregator.set_available_condition(
                        server.store, svc, False, str(e))
                    self._error(503, "ServiceUnavailable",
                                f"APIService {svc.meta.name} is unavailable:"
                                f" {e}")
                    return
                aggregator.set_available_condition(
                    server.store, svc, True, "delegate reachable")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _send_text(self, code: int, text: str,
                           ctype: str = "text/plain") -> None:
                data = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/healthz" or self.path == "/readyz":
                    self._send_json(200, {"status": "ok"})
                    return
                # debug routes (routes.DefaultMetrics / routes.Profiling{}
                # .Install): text exposition + on-demand sampling profile
                if self.path == "/metrics":
                    if server.metrics is None:
                        self._error(404, "NotFound", "no metrics registry")
                        return
                    self._send_text(200, server.metrics.expose(),
                                    "text/plain; version=0.0.4")
                    return
                if self.path.split("?")[0] == "/debug/pprof/profile":
                    from ..utils.pprof import take_profile

                    q = parse_qs(urlparse(self.path).query)
                    try:
                        secs = min(float(q.get("seconds", ["1"])[0]), 30.0)
                    except ValueError:
                        self._error(400, "BadRequest",
                                    "seconds must be a number")
                        return
                    self._send_text(200, take_profile(seconds=secs))
                    return
                if self.path == "/apis" or self.path.startswith("/apis/"):
                    self._handle_aggregated()
                    return
                if self.path == "/version":
                    self._send_json(200, {"gitVersion": "v1.36.0-tpu",
                                          "platform": "tpu"})
                    return
                if self.path in ("/api", "/api/v1", "/openapi/v2"):
                    # discovery requires authentication (the reference
                    # grants system:discovery to authenticated users, not
                    # anonymous); any authenticated user is allowed
                    from .auth import ANONYMOUS

                    user = self._authenticate()
                    if user is None:
                        return
                    if user is not _TRUSTED and user.name == ANONYMOUS:
                        self._error(403, "Forbidden",
                                    "discovery requires authentication")
                        return
                    from . import discovery

                    doc = (discovery.api_versions() if self.path == "/api"
                           else discovery.api_resource_list()
                           if self.path == "/api/v1"
                           else discovery.openapi_v2())
                    self._send_json(200, doc)
                    return
                route = self._route()
                if route is None:
                    self._error(404, "NotFound", "unknown path")
                    return
                kind, key, sub, query = route
                if sub == "log":
                    # pods/log subresource: proxy to the pod's kubelet
                    # (registry/core/pod LogREST → node daemonEndpoints),
                    # gated behind the separate pods/log RBAC resource
                    if kind != "Pod":
                        self._error(404, "NotFound", "log is a pod subresource")
                        return
                    if not self._authorized("get", "Pod/log", key):
                        return
                    self._proxy_pod_logs(key, query)
                    return
                verb = "get" if key else ("watch" if query.get("watch") else "list")
                if not self._authorized(verb, kind, key):
                    return
                try:
                    # both selectors parse (and thus validate) BEFORE any
                    # stream headers go out: bad syntax must 400, not kill
                    # a live watch mid-stream
                    lsel = (parse_label_selector(query["labelSelector"])
                            if "labelSelector" in query else None)
                    fsel = (parse_field_selector(query["fieldSelector"])
                            if "fieldSelector" in query else None)

                    def selected(obj) -> bool:
                        if lsel is not None and not matches_label_selector(obj, lsel):
                            return False
                        return fsel is None or matches_field_selector(obj, fsel)

                    if key:
                        obj = server.store.get(kind, key)
                        want_version = query.get("apiVersion", "")
                        if want_version not in ("", "v1"):
                            self._send_json(
                                200,
                                server.scheme.encode_versioned(
                                    obj, want_version
                                ),
                            )
                            return
                        self._send_json(200, encode(obj))
                    elif query.get("watch"):
                        self._serve_watch(
                            kind, int(query.get("resourceVersion", 0)),
                            selected if (lsel is not None
                                         or fsel is not None) else None,
                        )
                    else:
                        items, rev = server.store.list(kind)
                        self._send_json(200, {
                            "kind": f"{kind}List",
                            "metadata": {"resourceVersion": rev},
                            "items": [encode(o) for o in items if selected(o)],
                        })
                except NotFoundError as e:
                    self._error(404, "NotFound", str(e))
                except CompactedError as e:
                    # etcd compaction → 410 Gone ("Expired"): client relists
                    self._error(410, "Expired", str(e))
                except ValueError as e:
                    self._error(400, "BadRequest", str(e))

            def _serve_watch(self, kind: str, from_revision: int,
                             selected=None) -> None:
                """selected: optional predicate — events whose object
                doesn't match are dropped server-side (the watch-cache
                selector filtering of staging/.../storage/cacher). Selector
                transitions follow cacher semantics exactly: an object
                MODIFIED out of the selector synthesizes DELETED (carrying
                the current object), one MODIFIED into it synthesizes
                ADDED — detected via Event.prev_obj, the PrevObject of
                cacher's watchCacheEvent."""
                watch = server.store.watch(kind, from_revision=from_revision)
                use_cbor = self._wants_cbor()
                if use_cbor:
                    from ..api import cbor
                try:
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "application/cbor-seq" if use_cbor else "application/json",
                    )
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()

                    def write_chunk(data: bytes) -> None:
                        self.wfile.write(f"{len(data):X}\r\n".encode())
                        self.wfile.write(data + b"\r\n")
                        self.wfile.flush()

                    while not watch.stopped:
                        ev = watch.next(timeout=0.5)
                        if ev is None:
                            # heartbeat chunk: a dead client surfaces as a
                            # broken pipe here instead of leaking the handler
                            # thread + store watch forever on quiet kinds
                            write_chunk(b"\x00\x00\x00\x00" if use_cbor else b"\n")
                            continue
                        ev_type = ev.type
                        if selected is not None:
                            curr = selected(ev.obj)
                            # a MODIFIED without prev_obj degrades to a plain
                            # MODIFIED (prev := curr), never a spurious ADDED
                            prev = (selected(ev.prev_obj)
                                    if ev.prev_obj is not None else curr)
                            if ev_type == "MODIFIED" and curr and not prev:
                                ev_type = "ADDED"  # transitioned in
                            elif ev_type == "MODIFIED" and prev and not curr:
                                ev_type = "DELETED"  # transitioned out
                            elif not curr:
                                continue
                        payload = {"type": ev_type, "object": encode(ev.obj),
                                   "revision": ev.revision}
                        if use_cbor:
                            # length-prefixed CBOR frames: binary bodies
                            # aren't newline-delimitable
                            frame = cbor.dumps(payload)
                            write_chunk(len(frame).to_bytes(4, "big") + frame)
                        else:
                            write_chunk(json.dumps(payload).encode() + b"\n")
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    watch.stop()

            def do_POST(self):
                if self.path.startswith("/apis/"):
                    self._handle_aggregated()
                    return
                route = self._route()
                if route is None:
                    self._error(404, "NotFound", "unknown path")
                    return
                kind, key, sub, _ = route
                body = self._read_body()
                if sub == "log":
                    self._error(405, "MethodNotAllowed", "pods/log is GET-only")
                    return
                if sub in ("binding", "token"):
                    # the reference gates binding writes behind the separate
                    # pods/binding resource, NOT plain pod create — a
                    # create-only grant must not mutate existing pods; the
                    # serviceaccounts/token subresource is gated the same way
                    resource = f"{kind}/{sub}"
                else:
                    # authorize against where the object will actually land:
                    # decode applies the namespace default, the raw body may
                    # omit it
                    resource = kind
                from .discovery import CLUSTER_SCOPED

                if kind in CLUSTER_SCOPED:
                    # cluster-scoped creates authorize against namespace ""
                    # so only ClusterRoleBindings can grant them — a
                    # namespaced Role/RoleBinding must never be able to mint
                    # e.g. a ClusterRoleBinding (rbac.go: RoleBindings grant
                    # within their namespace only)
                    ns = ""
                elif key and "/" in key:
                    ns = key.split("/", 1)[0]
                else:
                    # mirror decode's ObjectMeta default ("default") so an
                    # omitted namespace is authorized where the object lands
                    ns = (body.get("meta") or {}).get("namespace", "default")
                if not self._authorized("create", resource, key, namespace=ns):
                    return
                try:
                    if sub == "token":
                        # TokenRequest subresource (authentication.k8s.io
                        # TokenRequest via serviceaccounts/token) — only
                        # the ServiceAccount kind carries it; authz ran
                        # against <kind>/token, so any other kind must 404
                        # rather than mint under the wrong RBAC resource
                        if kind != "ServiceAccount":
                            self._error(404, "NotFound",
                                        f"{kind} has no token subresource")
                            return
                        issuer = getattr(server.authenticator, "sa_issuer",
                                         None) if server.authenticator else None
                        if issuer is None:
                            self._error(400, "BadRequest",
                                        "token issuance not configured")
                            return
                        exp = int(body.get("expirationSeconds", 3600))
                        if exp <= 0:
                            self._error(400, "BadRequest",
                                        "expirationSeconds must be "
                                        "positive")
                            return
                        exp = max(exp, 600)  # the reference floors at 10m
                        ns, _, name = key.partition("/")
                        try:
                            token = issuer.issue(ns, name, exp)
                        except NotFoundError:
                            # issue() itself is the existence check — the
                            # SA is absent (or a delete raced the request)
                            self._error(404, "NotFound",
                                        f"ServiceAccount {key}")
                            return
                        self._send_json(201, {
                            "token": token,
                            "expirationSeconds": exp,
                        })
                        return
                    if sub == "binding":
                        # pods/binding subresource (registry/core/pod BindingREST)
                        pod = server.store.get(kind, key)
                        pod.spec.node_name = body.get("target_node") or body.get(
                            "target", {}
                        ).get("name", "")
                        server.store.update(pod, check_version=False)
                        self._send_json(201, {"status": "Success"})
                        return
                    from ..api.extensions import CustomObject

                    klass = kind_class(kind)
                    if (body.get("apiVersion", "") not in ("", "v1")
                            and not issubclass(klass, CustomObject)):
                        obj = server.scheme.decode_versioned(body)
                        if obj.kind != kind:
                            # authz ran against the URL kind; a body of a
                            # different kind would bypass it
                            self._error(400, "BadRequest",
                                        f"body kind {obj.kind!r} != URL "
                                        f"kind {kind!r}")
                            return
                    else:
                        # custom kinds carry their CRD group's apiVersion;
                        # they decode unversioned (apiextensions serves
                        # them without scheme conversion)
                        obj = decode(body, klass)
                    if key and obj.meta.key != key:
                        self._error(
                            400, "BadRequest",
                            f"body key {obj.meta.key!r} != URL key {key!r}",
                        )
                        return
                    created = self._commit_create(kind, obj)
                    self._send_json(201, encode(created))
                except AdmissionError as e:
                    self._error(e.code, "Invalid", str(e))
                except AlreadyExistsError as e:
                    self._error(409, "AlreadyExists", str(e))
                except NotFoundError as e:
                    self._error(404, "NotFound", str(e))
                except (KeyError, TypeError, ValueError) as e:
                    self._error(400, "BadRequest", f"undecodable body: {e}")

            def do_PATCH(self):
                if self.path.startswith("/apis/"):
                    self._handle_aggregated()
                    return
                """RFC 7386 JSON merge patch against the stored object
                (the reference's application/merge-patch+json strategy:
                objects merge recursively, null deletes a key, anything
                else replaces)."""
                route = self._route()
                if route is None:
                    self._error(404, "NotFound", "unknown path")
                    return
                kind, key, sub, query = route
                patch = self._read_body()
                if sub:
                    self._error(405, "MethodNotAllowed",
                                "subresources are not patchable")
                    return
                if not isinstance(patch, dict):
                    # a non-object root would REPLACE the whole object per
                    # RFC 7386 — never a valid API object
                    self._error(400, "BadRequest", "patch must be an object")
                    return
                if not self._authorized("patch", kind, key):
                    return
                if query.get("fieldManager"):
                    # server-side apply (fieldmanager): managedFields
                    # ownership + conflict detection + dropped-field removal
                    self._server_side_apply(kind, key, patch, query)
                    return

                def merge(base, delta):
                    if not isinstance(delta, dict) or not isinstance(base, dict):
                        return delta
                    out = dict(base)
                    for k, v in delta.items():
                        if v is None:
                            out.pop(k, None)
                        else:
                            out[k] = merge(out.get(k), v)
                    return out

                try:
                    cur = server.store.get(kind, key)
                    merged = merge(encode(cur), patch)
                    obj = decode(merged, kind_class(kind))
                    if obj.meta.key != key:
                        self._error(400, "BadRequest",
                                    "patch may not move the object")
                        return
                    # merge was computed against the live object: write it
                    # back at that revision (a racing writer wins the CAS
                    # and the client retries, apiserver patch semantics)
                    obj.meta.resource_version = cur.meta.resource_version
                    server._admit("UPDATE", obj)
                    updated = server.store.update(obj)
                    self._send_json(200, encode(updated))
                except AdmissionError as e:
                    self._error(e.code, "Invalid", str(e))
                except NotFoundError as e:
                    self._error(404, "NotFound", str(e))
                except ConflictError as e:
                    self._error(409, "Conflict", str(e))
                except (KeyError, TypeError, ValueError, AttributeError) as e:
                    self._error(400, "BadRequest", f"unmergeable patch: {e}")

            def do_PUT(self):
                if self.path.startswith("/apis/"):
                    self._handle_aggregated()
                    return
                route = self._route()
                if route is None:
                    self._error(404, "NotFound", "unknown path")
                    return
                kind, key, sub, query = route
                # body FIRST: an unauthorized PUT must still drain its
                # Content-Length bytes or the next request on this
                # keep-alive connection parses them as a request line
                body = self._read_body()
                if sub == "log":
                    self._error(405, "MethodNotAllowed", "pods/log is GET-only")
                    return
                if not self._authorized("update", kind, key):
                    return
                try:
                    from ..api.extensions import CustomObject

                    klass = kind_class(kind)
                    if (body.get("apiVersion", "") not in ("", "v1")
                            and not issubclass(klass, CustomObject)):
                        obj = server.scheme.decode_versioned(body)
                        if obj.kind != kind:
                            self._error(400, "BadRequest",
                                        f"body kind {obj.kind!r} != URL "
                                        f"kind {kind!r}")
                            return
                    else:
                        # custom kinds decode unversioned whatever group
                        # apiVersion they carry (as in do_POST)
                        obj = decode(body, klass)
                    if obj.meta.key != key:
                        # the authz decision above was made against the URL
                        # key; a body naming a different object would bypass
                        # it (the reference rejects URL/body mismatches)
                        self._error(
                            400, "BadRequest",
                            f"body key {obj.meta.key!r} != URL key {key!r}",
                        )
                        return
                    server._admit("UPDATE", obj)
                    check = query.get("force") != "true"
                    updated = server.store.update(obj, check_version=check)
                    self._send_json(200, encode(updated))
                except AdmissionError as e:
                    self._error(e.code, "Invalid", str(e))
                except ConflictError as e:
                    self._error(409, "Conflict", str(e))
                except NotFoundError as e:
                    self._error(404, "NotFound", str(e))
                except (KeyError, TypeError, ValueError) as e:
                    self._error(400, "BadRequest", f"undecodable body: {e}")

            def _commit_create(self, kind: str, obj):
                """The ONE create sequence (shared by POST and apply-create
                so they can't drift): unserialized admission chain (incl.
                webhook HTTP calls) → per-namespace lock around the quota
                check-and-commit pair (upstream also runs ResourceQuota as
                the last admission plugin) → post-commit CRD establishment
                (an admission denial must not leak scheme state)."""
                server._admit("CREATE", obj)
                with server._create_lock(getattr(obj.meta, "namespace", "")):
                    server._admit_serialized("CREATE", obj)
                    created = server.store.create(obj)
                if kind == "CustomResourceDefinition":
                    from ..api.extensions import register_custom_kind

                    register_custom_kind(created)
                return created

            def _server_side_apply(self, kind: str, key: str, applied: dict,
                                   query: dict) -> None:
                """fieldmanager apply: create-or-merge with ownership
                tracking; 409 names the conflicting manager unless
                force=true transfers the fields."""
                from .apply import ApplyConflict, apply_doc

                manager = query["fieldManager"]
                force = query.get("force") == "true"
                try:
                    cur = server.store.try_get(kind, key)
                    if cur is None and not self._authorized(
                        "create", kind, key
                    ):
                        # apply-create needs the create verb too (upstream
                        # authorizes both); patch alone must not mint
                        # objects. key-derived namespace matches do_POST's
                        # scoping: cluster-scoped keys carry no "/" -> ""
                        return
                    merged = apply_doc(None if cur is None else encode(cur),
                                       applied, manager, force)
                    obj = decode(merged, kind_class(kind))
                    if obj.meta.key != key:
                        self._error(400, "BadRequest",
                                    f"body key {obj.meta.key!r} != URL "
                                    f"key {key!r}")
                        return
                    if cur is None:
                        created = self._commit_create(kind, obj)
                        self._send_json(201, encode(created))
                        return
                    obj.meta.resource_version = cur.meta.resource_version
                    server._admit("UPDATE", obj)
                    updated = server.store.update(obj)
                    self._send_json(200, encode(updated))
                except ApplyConflict as e:
                    # distinct reason: a field-OWNERSHIP conflict needs the
                    # --force-conflicts remedy; a CAS race ("Conflict")
                    # just needs a retry — clients must tell them apart
                    self._error(409, "FieldManagerConflict", str(e))
                except AdmissionError as e:
                    self._error(e.code, "Invalid", str(e))
                except AlreadyExistsError as e:
                    self._error(409, "AlreadyExists", str(e))
                except ConflictError as e:
                    self._error(409, "Conflict", str(e))
                except NotFoundError as e:
                    self._error(404, "NotFound", str(e))
                except (KeyError, TypeError, ValueError, AttributeError) as e:
                    self._error(400, "BadRequest", f"undecodable body: {e}")

            def do_DELETE(self):
                if self.path.startswith("/apis/"):
                    self._handle_aggregated()
                    return
                # drain the body first: DELETE rarely carries one, but
                # unconsumed bytes desync the next keep-alive request
                self._read_body()
                route = self._route()
                if route is None:
                    self._error(404, "NotFound", "unknown path")
                    return
                kind, key, sub, _ = route
                if sub == "log":
                    self._error(405, "MethodNotAllowed", "pods/log is GET-only")
                    return
                if not self._authorized("delete", kind, key):
                    return
                try:
                    deleted = server.store.delete(kind, key)
                    if kind == "CustomResourceDefinition":
                        server._drop_custom_kind(deleted)
                    self._send_json(200, encode(deleted))
                except NotFoundError as e:
                    self._error(404, "NotFound", str(e))

            def log_message(self, *a):
                pass

        _VERB_BY_METHOD = {"POST": "create", "PUT": "update",
                           "PATCH": "patch", "DELETE": "delete"}

        def instrumented(method_fn):
            # request-filter wrapper: one root span per request
            # (component-base/tracing) + one audit entry per API request
            # (the audit stage of the handler chain)
            import functools

            @functools.wraps(method_fn)
            def wrapper(handler_self):
                handler_self._audit_user = "system:unsecured"
                handler_self._audit_code = 0

                def run():
                    return method_fn(handler_self)

                tracer = server.tracer
                try:
                    if tracer is not None and tracer.exporter is not None:
                        path = handler_self.path.split("?")[0]
                        with tracer.span(
                            f"HTTP {handler_self.command} {path}"
                        ):
                            return run()
                    return run()
                finally:
                    route = handler_self._route()
                    if route is not None:
                        kind, key, _sub, query = route
                        method = handler_self.command
                        if method == "GET":
                            # mirror the serving path's precedence exactly
                            # (key wins over ?watch): the audit verb must
                            # match what authz evaluated
                            verb = ("get" if key
                                    else "watch" if query.get("watch")
                                    else "list")
                        else:
                            verb = _VERB_BY_METHOD.get(method, method.lower())
                        server.audit.record(
                            handler_self._audit_user, verb, kind, key,
                            handler_self._audit_code,
                        )

            return wrapper

        _orig_send_response = Handler.send_response

        def send_response(handler_self, code, message=None):
            handler_self._audit_code = code
            return _orig_send_response(handler_self, code, message)

        Handler.send_response = send_response
        for verb in ("do_GET", "do_POST", "do_PUT", "do_PATCH",
                     "do_DELETE"):
            setattr(Handler, verb, instrumented(getattr(Handler, verb)))
        return Handler

    def _admit(self, operation: str, obj) -> None:
        for fn in self.admission:
            if not getattr(fn, "serialize_with_create", False):
                fn(operation, obj)

    def _admit_serialized(self, operation: str, obj) -> None:
        """Plugins that must be atomic with the following store commit
        (quota's check-and-reserve); runs under the per-namespace create
        lock, after the unserialized chain."""
        for fn in self.admission:
            if getattr(fn, "serialize_with_create", False):
                fn(operation, obj)

    def _create_lock(self, namespace: str) -> threading.Lock:
        with self._create_locks_mu:
            return self._create_locks.setdefault(namespace, threading.Lock())

    def _drop_custom_kind(self, crd) -> None:
        """CRD deletion cleanup: delete served instances, then retire the
        kind from the scheme (the apiextensions finalizer's job)."""
        from ..api.extensions import unregister_custom_kind

        kind = crd.spec.names.kind
        for obj in list(self.store.iter_kind(kind)):
            self.store.try_delete(kind, obj.meta.key)
        unregister_custom_kind(kind)

    # -- lifecycle -----------------------------------------------------------

    def serve(self, port: int = 0, tls_cert: str | None = None,
              tls_key: str | None = None) -> int:
        """Plain HTTP by default (insecure localhost, the in-tree trust
        model); with tls_cert/tls_key the listener serves HTTPS (the
        reference's secure serving — generate a pair with
        apiserver/certs.generate_self_signed; the cert doubles as the
        clients' CA)."""
        if bool(tls_cert) != bool(tls_key):
            raise ValueError(
                "tls_cert and tls_key must be provided together — a "
                "half-specified pair must not silently serve plaintext"
            )
        self._http = ThreadingHTTPServer(("127.0.0.1", port), self._build_handler())
        self._http.daemon_threads = True
        self._tls = False
        if tls_cert and tls_key:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert, tls_key)
            self._http.socket = ctx.wrap_socket(
                self._http.socket, server_side=True
            )
            self._tls = True
        t = threading.Thread(target=self._http.serve_forever, daemon=True)
        t.start()
        self.port = self._http.server_port
        return self.port

    @property
    def url(self) -> str:
        scheme = "https" if getattr(self, "_tls", False) else "http"
        return f"{scheme}://127.0.0.1:{self.port}"

    def shutdown(self) -> None:
        if self._http is not None:
            self._http.shutdown()
