"""Whole-program effect inference over the project call graph.

For every function in the `callgraph.ProjectIndex` this pass computes a
direct effect set — host syncs / blocking calls, telemetry, seeded-rng
consumption, lock acquisition, guarded-state writes (the SIG02 / PIPE01 /
GANG01 / CRASH01 / SHARD01 ownership families), device transfers, and
fault-point visits — then propagates the sets over the call graph to a
fixpoint, so `TPUBackend.collect`'s effect set includes everything every
transitively reached helper does, across module boundaries.

Each propagated effect carries provenance: the origin function and line
where the primitive effect happens, plus the first callee it arrived
through, so rules can render a `root -> helper -> leaf` chain in the
finding message instead of a bare "something somewhere blocks".

Sanction semantics (what makes the rules precise rather than noisy):

- ownership-family writes (`SIG02:..`, `PIPE01:..`, ...) are recorded only
  OUTSIDE the family's owning modules, and do not propagate out of a
  function defined in an owning module — calling a sanctioned hook like
  `backend.invalidate_carry()` is the fix, not a violation;
- rng consumption (`rng.randrange()` and friends on a receiver named
  `rng` / `*.rng`) is recorded only outside the sanctioned scheduling-core
  modules and stops propagating at them — entering the core through its
  public API (`collect(fl, rng=...)`) is legal; what RNG01 flags is the
  stream being consumed or advanced out in the open;
- host-sync / telemetry / lock effects propagate unconditionally; their
  rules (EFF01/EFF02, LOCK05) decide relevance from context (traced
  region, held locks), not from where the effect lives.

A write on a line carrying `# kubesched-lint: disable=<family rule>` does
not generate the effect at all: a reviewed, justified suppression kills
the taint at the source instead of re-flagging every transitive caller.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Iterator

from .callgraph import FunctionInfo, ProjectIndex, _dotted
from .carry_coherence import _GUARDED as _SIG02_ATTRS
from .crash_state import SCHEDULER as _CRASH_DECL, _parse_state as _parse_crash_state
from .fleet_state import FLEET as _FLEET_DECL, _parse_state as _parse_fleet_state
from .obs_purity import TELEMETRY_SEGMENTS
from .pipeline_state import _GUARDED as _PIPE01_ATTRS

# effect kinds
HOST_SYNC = "host_sync"
TELEMETRY = "telemetry"
RNG = "rng"
LOCK = "lock"
WRITE = "write"          # detail = "<RULE>:<attr>"
TRANSFER = "transfer"
FAULT = "fault"

# method names that consume or advance a seeded random.Random tie-break
# stream (setstate transplants the position; getstate alone is a read)
RNG_CONSUME = {
    "random", "randrange", "randint", "getrandbits", "shuffle", "sample",
    "choice", "choices", "setstate",
}

# the scheduling-core modules sanctioned to touch the tie-break stream:
# the host algorithm draw, the device backend's clone/advance transplant,
# the gang planner handing the stream to run_gang, and the scheduler
# profile wiring that seeds it
RNG_SANCTIONED = (
    "scheduler/schedule_one.py",
    "scheduler/tpu/backend.py",
    "scheduler/tpu/gangplanner.py",
    "scheduler/scheduler.py",
)

# in-place mutators (union of the ownership checkers' sets)
_MUTATORS = {
    "clear", "update", "add", "discard", "pop", "remove", "append",
    "extend", "insert", "setdefault", "store", "appendleft", "popleft",
}

_GANG01_ATTRS = {
    "gang_placements", "gang_n_constrained", "gang_has_fallback",
    "gang_required", "gang_groups", "gang_pods", "gang_fallback_pods",
    "gang_outcome",
}

_TRANSFER_CALLS = {
    "device_put", "accounted_put", "accounted_fetch", "account_upload",
    "account_fetch",
}

_BACKEND = "scheduler/tpu/backend.py"
_GANGPLANNER = "scheduler/tpu/gangplanner.py"
_SHARD_SEAM_FUNC = "_cold_start_upload"


@dataclasses.dataclass(frozen=True)
class Effect:
    kind: str
    detail: str

    def render(self) -> str:
        return f"{self.kind}:{self.detail}" if self.detail else self.kind


@dataclasses.dataclass
class Provenance:
    origin: str          # qualname whose body performs the effect
    origin_line: int
    via: str | None      # first callee the effect arrived through
    via_line: int        # call-site line (in the carrying function)


class OwnershipFamily:
    """One guarded-state family: rule id, owning modules, guarded attrs."""

    def __init__(self, rule: str, owners: tuple[str, ...],
                 attrs: set[str] | None = None, prefix: str | None = None,
                 exempt: tuple[str, ...] = ()):
        self.rule = rule
        self.owners = owners
        self.attrs = attrs or set()
        self.prefix = prefix
        self.exempt = exempt  # modules neither owning nor checked (decl site)

    def guards(self, attr: str) -> bool:
        return attr in self.attrs or (
            self.prefix is not None and attr.startswith(self.prefix))

    def is_owner(self, path: str) -> bool:
        return any(path.endswith(o) for o in self.owners + self.exempt)


def ownership_families(index: ProjectIndex) -> list[OwnershipFamily]:
    fams = [
        OwnershipFamily("SIG02", (_BACKEND,), set(_SIG02_ATTRS),
                        prefix="_carry"),
        OwnershipFamily("PIPE01", (_BACKEND,), set(_PIPE01_ATTRS)),
        OwnershipFamily("GANG01", (_GANGPLANNER, _BACKEND), _GANG01_ATTRS),
    ]
    decl = index.root / _CRASH_DECL
    if decl.is_file():
        state = _parse_crash_state(decl)
        if state:
            # one family per attribute: owners differ per attr
            for attr, owners in sorted(state.items()):
                fams.append(OwnershipFamily(
                    "CRASH01", tuple(sorted(owners)), {attr},
                    exempt=(_CRASH_DECL,)))
    fleet_decl = index.root / _FLEET_DECL
    if fleet_decl.is_file():
        state = _parse_fleet_state(fleet_decl)
        if state:
            for attr, owners in sorted(state.items()):
                fams.append(OwnershipFamily(
                    "FLEET01", tuple(sorted(owners)), {attr},
                    exempt=(_FLEET_DECL,)))
    return fams


class EffectEngine:
    """Direct effect extraction + fixpoint propagation over the graph."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.families = ownership_families(index)
        # qualname -> {Effect: Provenance}; direct kept separately so
        # rules can distinguish "does it here" from "reaches it"
        self.direct: dict[str, dict[Effect, Provenance]] = {}
        self.effects: dict[str, dict[Effect, Provenance]] = {}
        for fi in index.functions.values():
            self.direct[fi.qualname] = dict(self._direct_effects(fi))
        self._propagate()

    # -- direct effects -------------------------------------------------
    def _suppressed(self, fi: FunctionInfo, line: int, rule: str) -> bool:
        mod = self.index.modules.get(fi.path)
        return mod is not None and rule in mod.suppressions.get(line, ())

    def _direct_effects(
        self, fi: FunctionInfo
    ) -> Iterator[tuple[Effect, Provenance]]:
        q = fi.qualname

        def prov(line: int) -> Provenance:
            return Provenance(q, line, None, line)

        for acq in fi.acquires:
            yield Effect(LOCK, acq.lock), prov(acq.line)

        def visit(node: ast.AST) -> Iterator[tuple[Effect, Provenance]]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue  # nested defs carry their own effects
                yield from visit(child)
                if isinstance(child, ast.Call):
                    yield from check_call(child)
                elif isinstance(child, (ast.Assign, ast.AugAssign,
                                        ast.AnnAssign, ast.Delete)):
                    yield from check_write(child)

        def check_call(call: ast.Call) -> Iterator[tuple[Effect, Provenance]]:
            func = call.func
            d = _dotted(func)
            line = call.lineno
            if d == "time.sleep" or (isinstance(func, ast.Name)
                                     and func.id == "sleep"):
                yield Effect(HOST_SYNC, "time.sleep"), prov(line)
            if isinstance(func, ast.Attribute):
                attr = func.attr
                if attr == "item":
                    yield Effect(HOST_SYNC, ".item()"), prov(line)
                elif attr in ("result", "join") and not call.args:
                    yield Effect(HOST_SYNC, f".{attr}()"), prov(line)
                elif attr in ("wait", "wait_for"):
                    yield Effect(HOST_SYNC, f".{attr}()"), prov(line)
                if attr in _TRANSFER_CALLS:
                    yield Effect(TRANSFER, attr), prov(line)
                    yield from check_shard_seam(call, attr, line)
                # seeded tie-break stream: receiver named rng / *.rng
                if attr in RNG_CONSUME:
                    recv = _dotted(func.value)
                    if recv is not None and recv.split(".")[-1] == "rng":
                        if not any(fi.path.endswith(m)
                                   for m in RNG_SANCTIONED):
                            yield (Effect(RNG, f"{recv}.{attr}()"),
                                   prov(line))
                if attr == "fire" or (isinstance(func, ast.Name)
                                      and func.id == "fire"):
                    yield Effect(FAULT, "fire()"), prov(line)
            elif isinstance(func, ast.Name):
                if func.id == "fire":
                    yield Effect(FAULT, "fire()"), prov(line)
                if func.id in _TRANSFER_CALLS:
                    yield Effect(TRANSFER, func.id), prov(line)
                    yield from check_shard_seam(call, func.id, line)
            if d is not None:
                segments = {seg.lower() for seg in d.split(".")}
                if segments & TELEMETRY_SEGMENTS:
                    yield Effect(TELEMETRY, f"{d}()"), prov(line)
            # mutator calls on guarded attrs are writes too
            if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
                for attr_node in ast.walk(func.value):
                    if isinstance(attr_node, ast.Attribute):
                        yield from family_writes(
                            attr_node.attr, line, f".{func.attr}()")

        def check_shard_seam(
            call: ast.Call, name: str, line: int
        ) -> Iterator[tuple[Effect, Provenance]]:
            if name not in ("accounted_put", "account_upload"):
                return
            plane = None
            if call.args and isinstance(call.args[0], ast.Constant):
                plane = call.args[0].value
            else:
                for kw in call.keywords:
                    if kw.arg == "plane" and isinstance(kw.value,
                                                        ast.Constant):
                        plane = kw.value.value
            if plane != "node_planes":
                return
            if (fi.path.endswith(_BACKEND)
                    and _SHARD_SEAM_FUNC in fi.qualname):
                return  # the one sanctioned cold-start seam
            if self._suppressed(fi, line, "SHARD01"):
                return
            yield (Effect(WRITE, "SHARD01:node_planes"), prov(line))

        def family_writes(
            attr: str, line: int, how: str
        ) -> Iterator[tuple[Effect, Provenance]]:
            for fam in self.families:
                if fam.guards(attr) and not fam.is_owner(fi.path):
                    if self._suppressed(fi, line, fam.rule):
                        continue
                    yield (Effect(WRITE, f"{fam.rule}:{attr}"), prov(line))

        def check_write(
            stmt: ast.stmt,
        ) -> Iterator[tuple[Effect, Provenance]]:
            if isinstance(stmt, ast.Assign):
                targets = list(stmt.targets)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            else:  # Delete
                targets = list(stmt.targets)
            for tgt in targets:
                for node in ast.walk(tgt):
                    if isinstance(node, ast.Attribute):
                        yield from family_writes(node.attr, node.lineno,
                                                 "assignment")

        yield from visit(fi.node)

    # -- propagation ----------------------------------------------------
    def _carries(self, effect: Effect, callee_path: str) -> bool:
        """May this effect flow OUT of a function in `callee_path`?"""
        if effect.kind == WRITE:
            rule = effect.detail.split(":", 1)[0]
            if rule == "SHARD01":
                return not callee_path.endswith(_BACKEND)
            for fam in self.families:
                if fam.rule == rule and fam.guards(
                        effect.detail.split(":", 1)[1]):
                    if fam.is_owner(callee_path):
                        return False
            return True
        if effect.kind == RNG:
            return not any(callee_path.endswith(m) for m in RNG_SANCTIONED)
        return True

    def _propagate(self) -> None:
        for q, eff in self.direct.items():
            self.effects[q] = dict(eff)
        callers: dict[str, list[str]] = {}
        for fi in self.index.functions.values():
            for c in fi.calls:
                callers.setdefault(c.callee, []).append(fi.qualname)
        work = list(self.index.functions)
        pending = set(work)
        while work:
            q = work.pop()
            pending.discard(q)
            fi = self.index.functions[q]
            mine = self.effects.setdefault(q, {})
            grew = False
            for c in fi.calls:
                sub = self.effects.get(c.callee)
                if not sub:
                    continue
                callee_path = self.index.functions[c.callee].path
                for eff, p in sub.items():
                    if eff in mine:
                        continue
                    if not self._carries(eff, callee_path):
                        continue
                    mine[eff] = Provenance(p.origin, p.origin_line,
                                           c.callee, c.line)
                    grew = True
            if grew:
                for caller in callers.get(q, ()):
                    if caller not in pending:
                        pending.add(caller)
                        work.append(caller)

    # -- provenance rendering -------------------------------------------
    def chain(self, qualname: str, effect: Effect) -> list[tuple[str, int]]:
        """[(carrier qualname, call-site line), ...] ending at the origin."""
        out: list[tuple[str, int]] = []
        cur = qualname
        seen = {cur}
        while True:
            p = self.effects.get(cur, {}).get(effect)
            if p is None:
                break
            if p.via is None or p.via in seen:
                out.append((p.origin, p.origin_line))
                break
            out.append((cur, p.via_line))
            seen.add(p.via)
            cur = p.via
        return out

    def render_chain(self, qualname: str, effect: Effect) -> str:
        hops = self.chain(qualname, effect)
        if not hops:
            return qualname
        names = [q.split("::")[-1] for q, _ in hops]
        origin_q, origin_line = hops[-1]
        path = self.index.functions[origin_q].path
        return (" -> ".join(names)
                + f" ({path}:{origin_line})")

    def reaches(self, qualname: str, kind: str) -> list[Effect]:
        return sorted(
            (e for e in self.effects.get(qualname, {}) if e.kind == kind),
            key=lambda e: e.detail)
