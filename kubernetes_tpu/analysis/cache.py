"""Content-hash result cache for the whole-program lint pass.

The whole-program checker re-parses every file under the project root, so
`make lint` pays the full parse + fixpoint cost even when nothing changed.
This cache keys the final finding list on a digest of (a) every `.py`
file's content under the linted paths AND the project root, and (b) the
analysis package's own sources — editing a rule invalidates every entry,
so a stale cache can never mask a new rule's findings.

Entries live under `.kubesched_lint_cache/` next to the project root
(override with `$KUBESCHED_LINT_CACHE`); the directory is disposable and
gitignored. `--no-cache` bypasses it entirely. Only the default checker
set is ever cached — a custom checker list computes fresh.
"""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import Iterable

from .core import Finding, iter_python_files

ENV_DIR = "KUBESCHED_LINT_CACHE"
DIR_NAME = ".kubesched_lint_cache"
MAX_ENTRIES = 32
_SCHEMA = 1


def cache_dir(project_root: Path | None) -> Path:
    env = os.environ.get(ENV_DIR)
    if env:
        return Path(env)
    base = project_root.parent if project_root is not None else Path(".")
    return base / DIR_NAME


def _file_digest(path: Path) -> str | None:
    try:
        return hashlib.sha256(path.read_bytes()).hexdigest()
    except OSError:
        return None


def tree_digest(
    paths: Iterable[str | Path], project_root: Path | None
) -> str:
    """Digest of every .py under `paths` + root, salted with rule sources."""
    h = hashlib.sha256(f"schema={_SCHEMA}".encode())
    seen: set[Path] = set()
    roots: list[Path] = [Path(p) for p in paths]
    if project_root is not None:
        roots.append(Path(project_root))
    entries: list[tuple[str, str]] = []
    for f in iter_python_files(roots):
        rp = f.resolve()
        if rp in seen:
            continue
        seen.add(rp)
        d = _file_digest(rp)
        if d is not None:
            entries.append((rp.as_posix(), d))
    # salt: the analysis package's own sources — rule edits invalidate all
    for f in sorted(Path(__file__).resolve().parent.glob("*.py")):
        d = _file_digest(f)
        if d is not None:
            entries.append((f"salt:{f.name}", d))
    for name, digest in sorted(entries):
        h.update(f"{name}={digest}\n".encode())
    return h.hexdigest()


def load(key: str, project_root: Path | None) -> list[Finding] | None:
    entry = cache_dir(project_root) / f"{key}.json"
    try:
        data = json.loads(entry.read_text())
    except (OSError, ValueError):
        return None
    if data.get("schema") != _SCHEMA:
        return None
    try:
        return [Finding(p, ln, col, rule, msg)
                for p, ln, col, rule, msg in data["findings"]]
    except (KeyError, TypeError, ValueError):
        return None


def store(
    key: str, findings: list[Finding], project_root: Path | None
) -> None:
    d = cache_dir(project_root)
    try:
        d.mkdir(parents=True, exist_ok=True)
        payload = {
            "schema": _SCHEMA,
            "findings": [[f.path, f.line, f.col, f.rule, f.message]
                         for f in findings],
        }
        tmp = d / f".{key}.tmp"
        tmp.write_text(json.dumps(payload))
        tmp.replace(d / f"{key}.json")
        _prune(d)
    except OSError:
        pass  # cache is best-effort; lint results never depend on it


def _prune(d: Path) -> None:
    entries = sorted(
        (p for p in d.glob("*.json")),
        key=lambda p: p.stat().st_mtime if p.exists() else 0.0,
    )
    for stale in entries[:-MAX_ENTRIES]:
        try:
            stale.unlink()
        except OSError:
            pass
