"""Pipeline-state rule (PIPE01) for the streaming-waves double buffer.

Direct writes only; PIPE01's transitive mode (calling a mutating helper
cross-module) lives in whole_program.py and reuses this module's
guarded-attribute set.

The streaming wave pipeline keeps TWO device buffer sets live at once: the
base plane mirror (`_device_planes` + its `_mirror_dirty` repair debt) and
the in-flight wave's carry overlay, with the `InflightWave` handle
(`_inflight`, `poisoned`, `cursor_base_host`, `frame_shift`,
`_advanced_since_launch`, `_rerun_carry`) recording which buffer owns which
rows and where the seeded tie-break cursor stands. A write to any of that
state from outside `scheduler/tpu/backend.py` silently desynchronizes the
two buffers — the successor wave then scores against planes that are
neither host truth nor the predecessor's carry, and the golden bit-compat
contract breaks only under pipelined load, the hardest place to debug it.

PIPE01 therefore bans, outside `scheduler/tpu/backend.py`:

- assignment (plain, augmented, annotated, starred, tuple-unpacked) to an
  attribute in the guarded set: `_inflight`, `_mirror_dirty`,
  `_advanced_since_launch`, `_rerun_carry`, `poisoned`,
  `cursor_base_host`, `frame_shift`;
- `del` of such an attribute;
- mutating method calls on one (`.clear()`, `.update()`, `.add()`, ...).

The guard set is EXACT names (no prefix match, unlike SIG02's `_carry*`):
the scheduling loop legitimately owns its own `_inflight_wave` tuple and
must stay free to rotate it. Reads (`infl.poisoned`, `fl.cursor_base_host`)
and the sanctioned hook `InflightWave.mark_poisoned()` remain free — the
rule polices writes, not observation. The loop-side plane/carry state has
its own rule (SIG02, `carry_coherence.py`); PIPE01 covers the in-flight
half the pipeline added.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from .core import Checker, Finding, ModuleContext

PIPE01 = "PIPE01"

# the one module allowed to touch pipeline/in-flight-wave state directly
BACKEND = "scheduler/tpu/backend.py"

_GUARDED = {
    "_inflight",
    "_mirror_dirty",
    "_advanced_since_launch",
    "_rerun_carry",
    "poisoned",
    "cursor_base_host",
    "frame_shift",
}

# method names that mutate their receiver in-place
_MUTATORS = {
    "clear", "update", "add", "discard", "pop", "remove", "append",
    "extend", "setdefault", "store",
}


def _guarded_attrs(expr: ast.expr) -> Iterator[tuple[int, str]]:
    """(line, attr) for every guarded attribute access inside `expr`."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in _GUARDED:
            yield node.lineno, node.attr


class PipelineStateChecker(Checker):
    rules = {
        PIPE01: "double-buffer plane / in-flight-wave state written outside "
                "scheduler/tpu/backend.py — use the backend's sanctioned "
                "hooks (mark_poisoned / invalidate_carry) so the pipelined "
                "buffers stay coherent",
    }

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        p = ctx.posix_path
        if p.endswith(BACKEND):
            return  # the sanctioned site: backend.py owns this state
        for node in ast.walk(ctx.tree):
            yield from self._check_stmt(p, node)

    def _check_stmt(self, path: str, node: ast.AST) -> Iterator[Finding]:
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        elif isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _MUTATORS):
                for line, attr in _guarded_attrs(func.value):
                    yield Finding(
                        path, line, 0, PIPE01,
                        f"mutating call .{func.attr}() on guarded pipeline "
                        f"state {attr!r} outside backend.py — in-flight-wave "
                        "and double-buffer mutations must go through the "
                        "backend's sanctioned hooks (mark_poisoned / "
                        "invalidate_carry)",
                    )
            return
        for tgt in targets:
            for line, attr in _guarded_attrs(tgt):
                yield Finding(
                    path, line, 0, PIPE01,
                    f"write to guarded pipeline state {attr!r} outside "
                    "backend.py — the double-buffered planes and the "
                    "in-flight wave handle are only coherent when every "
                    "mutation routes through the backend's sanctioned "
                    "hooks (mark_poisoned / invalidate_carry)",
                )
