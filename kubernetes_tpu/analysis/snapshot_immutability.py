"""Snapshot/NodeInfo immutability rule (SNAP01).

The cache layer (scheduler/cache/) owns cluster state: everyone else sees a
`Snapshot` — a point-in-time, cycle-stable view (snapshot.py docstring) —
and per-node `NodeInfo` records reached through it. If a plugin or the
scheduling loop mutates either in place, two pods scheduled in the same
cycle disagree about the cluster, and the TPU plane builder's incremental
sync (generation counters) silently diverges from the host path. The
sanctioned pattern everywhere outside `scheduler/cache/` is
`ni = node_info.clone()` before any mutation, or routing the change through
the cache/snapshot fork API.

Tracking is name-based and per-function: parameters named/annotated
Snapshot/NodeInfo, `self.snapshot`-style attributes, values pulled out of a
snapshot (`snapshot.get(n)`, `snapshot.node_info_map[k]`, iteration over
`snapshot.list_nodes()`), minus anything reassigned — `ni = x.clone()`
yields a private copy and untracks the name.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, ModuleContext

SNAP01 = "SNAP01"

# path fragment that owns mutation rights
CACHE_LAYER = "scheduler/cache/"

SNAP_PARAM_NAMES = {"snapshot", "snap"}
SNAP_ATTR_NAMES = {"snapshot", "_snapshot"}
NI_PARAM_NAMES = {"node_info", "nodeinfo", "ni"}

NODEINFO_MUTATORS = {"add_pod", "remove_pod", "set_node"}
SNAPSHOT_MUTATORS = {
    "assume_pod", "forget_pod", "assume_placement", "forget_placement",
    "note_change", "note_membership", "rebuild_derived_lists",
    "refresh_list_index",
}
CONTAINER_MUTATORS = {"append", "appendleft", "add", "discard", "remove",
                      "pop", "popitem", "popleft", "clear", "update",
                      "extend", "insert", "setdefault"}
NI_LIST_PRODUCERS = {"list_nodes", "list_all", "values"}


def _annotation_names(ann: ast.expr | None) -> set[str]:
    if ann is None:
        return set()
    out: set[str] = set()
    for n in ast.walk(ann):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
        elif isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.add(n.value)
    return out


class _FnState:
    def __init__(self, fn: ast.FunctionDef):
        self.snap: set[str] = set()
        self.ni: set[str] = set()
        a = fn.args
        for p in (*a.posonlyargs, *a.args, *a.kwonlyargs):
            anns = _annotation_names(p.annotation)
            if p.arg in SNAP_PARAM_NAMES or "Snapshot" in anns:
                self.snap.add(p.arg)
            elif p.arg in NI_PARAM_NAMES or "NodeInfo" in anns:
                self.ni.add(p.arg)

    def is_snap(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.snap
        if isinstance(node, ast.Attribute):
            return node.attr in SNAP_ATTR_NAMES
        return False

    def is_ni(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.ni
        # snapshot.node_info_map[k]
        if isinstance(node, ast.Subscript):
            v = node.value
            if isinstance(v, ast.Attribute) and v.attr == "node_info_map":
                return self.is_snap(v.value)
        # snapshot.get(k) used inline
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "get" and self.is_snap(node.func.value):
                return True
        return False

    def is_tracked(self, node: ast.AST) -> bool:
        return self.is_snap(node) or self.is_ni(node)

    # -- assignment effects ---------------------------------------------
    def assign(self, target: ast.expr, value: ast.expr | None) -> None:
        if not isinstance(target, ast.Name):
            return
        name = target.id
        # any rebind clears old tracking first
        self.snap.discard(name)
        self.ni.discard(name)
        if value is None:
            return
        # x = something.clone() -> private copy, stays untracked
        if (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr == "clone"
        ):
            return
        if self.is_ni(value):
            self.ni.add(name)
        elif self.is_snap(value):
            self.snap.add(name)

    def track_loop_target(self, target: ast.expr, it: ast.expr) -> None:
        """for ni in snapshot.list_nodes() / .node_info_map.values():"""
        produces_ni = False
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute):
            if it.func.attr in NI_LIST_PRODUCERS:
                base = it.func.value
                if self.is_snap(base):
                    produces_ni = True
                elif (
                    isinstance(base, ast.Attribute)
                    and base.attr == "node_info_map"
                    and self.is_snap(base.value)
                ):
                    produces_ni = True
            elif it.func.attr == "items" and isinstance(it.func.value, ast.Attribute):
                if it.func.value.attr == "node_info_map" and self.is_snap(
                    it.func.value.value
                ):
                    # for name, ni in snap.node_info_map.items()
                    if isinstance(target, ast.Tuple) and len(target.elts) == 2:
                        self.assign_tracked_ni(target.elts[1])
                    return
        if produces_ni:
            self.assign_tracked_ni(target)

    def assign_tracked_ni(self, target: ast.expr) -> None:
        if isinstance(target, ast.Name):
            self.ni.add(target.id)


class SnapshotImmutabilityChecker(Checker):
    rules = {
        SNAP01: "Snapshot/NodeInfo mutated outside scheduler/cache/ "
                "(clone() first, or go through the cache API)",
    }

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if CACHE_LAYER in ctx.posix_path:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_fn(ctx, node)

    def _check_fn(
        self, ctx: ModuleContext, fn: ast.FunctionDef
    ) -> Iterable[Finding]:
        st = _FnState(fn)
        yield from self._walk(ctx, st, fn.body)

    def _walk(self, ctx, st: _FnState, stmts) -> Iterable[Finding]:
        for node in stmts:
            yield from self._stmt(ctx, st, node)

    def _stmt(self, ctx, st: _FnState, node: ast.stmt) -> Iterable[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs get their own pass from check_module
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            if node.value is not None:
                yield from self._expr(ctx, st, node.value)
            for tgt in targets:
                yield from self._check_store(ctx, st, tgt, aug=isinstance(node, ast.AugAssign))
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                for tgt in targets:
                    st.assign(tgt, node.value)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                yield from self._check_store(ctx, st, tgt)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            yield from self._expr(ctx, st, node.iter)
            st.track_loop_target(node.target, node.iter)
            yield from self._walk(ctx, st, node.body)
            yield from self._walk(ctx, st, node.orelse)
            return
        # generic: expressions then sub-statements, in order
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                yield from self._expr(ctx, st, child)
            elif isinstance(child, ast.stmt):
                yield from self._stmt(ctx, st, child)
            elif isinstance(child, (ast.excepthandler, ast.match_case, ast.withitem)):
                for sub in ast.iter_child_nodes(child):
                    if isinstance(sub, ast.expr):
                        yield from self._expr(ctx, st, sub)
                    elif isinstance(sub, ast.stmt):
                        yield from self._stmt(ctx, st, sub)

    def _check_store(
        self, ctx, st: _FnState, tgt: ast.expr, aug: bool = False
    ) -> Iterable[Finding]:
        """attribute / subscript stores on tracked objects."""
        base = None
        if isinstance(tgt, ast.Attribute):
            base = tgt.value
        elif isinstance(tgt, ast.Subscript):
            v = tgt.value
            base = v.value if isinstance(v, ast.Attribute) else v
        if base is not None and st.is_tracked(base):
            kind = "Snapshot" if st.is_snap(base) else "NodeInfo"
            yield Finding(
                ctx.posix_path, tgt.lineno, tgt.col_offset, SNAP01,
                f"store into {kind} outside {CACHE_LAYER} "
                "(clone() first, or go through the cache API)",
            )

    def _expr(self, ctx, st: _FnState, node: ast.expr) -> Iterable[Finding]:
        for n in ast.walk(node):
            if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)):
                continue
            attr = n.func.attr
            recv = n.func.value
            if attr in SNAPSHOT_MUTATORS and st.is_snap(recv):
                yield Finding(
                    ctx.posix_path, n.lineno, n.col_offset, SNAP01,
                    f"Snapshot.{attr}() outside {CACHE_LAYER} mutates the "
                    "shared cycle view",
                )
            elif attr in NODEINFO_MUTATORS and st.is_ni(recv):
                yield Finding(
                    ctx.posix_path, n.lineno, n.col_offset, SNAP01,
                    f"NodeInfo.{attr}() outside {CACHE_LAYER} mutates "
                    "shared cluster state (clone() first)",
                )
            elif (
                attr in CONTAINER_MUTATORS
                and isinstance(recv, ast.Attribute)
                and st.is_tracked(recv.value)
            ):
                kind = "Snapshot" if st.is_snap(recv.value) else "NodeInfo"
                yield Finding(
                    ctx.posix_path, n.lineno, n.col_offset, SNAP01,
                    f"{kind}.{recv.attr}.{attr}() outside {CACHE_LAYER} "
                    "mutates shared cluster state",
                )
