"""Signature-fragment rules (SIG01) for OpportunisticBatching / dedup.

The wave-dedup kernel groups pods by packed feature-row BYTES, so kernel
soundness never depends on the per-plugin `sign(pod)` fragments — but the
host-side `BatchCache` hint export (schedule_one.py) and the reference's
KEP-5598 equivalence classes DO: a fragment that reads a clock, an RNG, a
process-randomized `hash()`, or a traced jax value produces signatures
that drift between identical pods, silently zeroing the cache hit rate
(or worse, merging non-identical pods). Two mechanical checks:

- purity: a `sign()` method on a plugin class (or `Framework.sign_pod`)
  may not call into clock/RNG/jax sources — `time.*`, `random.*`,
  `uuid.*`, `secrets.*`, `datetime.*`, `os.urandom`, bare `hash()` /
  `id()` (PYTHONHASHSEED / address randomization: stable in-process,
  different every process — a restart would orphan every persisted hint),
  and `jax.*` / `jnp.*` (fragments are host code; a traced value here
  means a device sync per pod on the signing path);
- coverage: every kernel filter row in `ops/kernels.py FILTER_NAMES`
  either has a plugin `sign` fragment or an entry in `_SIGN_EXEMPT`
  below with a written justification — a new kernelized filter without a
  fragment makes pods differing ONLY in that dimension sign identically,
  and the BatchCache hint would then steer a non-clone onto a stale node
  list (caught later by the full filter re-check, but wasting the hint).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from .core import Finding, ModuleContext, ProjectChecker

SIG01 = "SIG01"

KERNELS = "ops/kernels.py"
PLUGINS_DIR = "scheduler/plugins"
RUNTIME = "scheduler/framework/runtime.py"

# filter rows with no signature fragment, each with its justification —
# additions here are code review decisions, not escape hatches
_SIGN_EXEMPT = {
    # node-side only: the filter reads node.spec.unschedulable and pod
    # tolerations of the unschedulable taint; the TaintToleration fragment
    # already keys the toleration list, so every pod adds no information
    "NodeUnschedulable": "node-side filter; tolerations signed by "
                         "TaintToleration's fragment",
    # spec.nodeName-pinned pods bypass batching entirely (the hint path
    # only serves schedulable pods); an unpinned pod contributes nothing
    "NodeName": "pinned pods never take the batch-hint path",
}

# call roots that make a fragment host-impure (clock / rng / traced)
_BANNED_ROOTS = {
    "time", "random", "uuid", "secrets", "datetime", "jax", "jnp",
}
_BANNED_BARE = {"hash", "id"}
_BANNED_ATTRS = {"urandom"}  # os.urandom and friends


def _dotted(func: ast.expr) -> tuple[str | None, str | None]:
    """(root name, last attribute) of a call target, e.g. time.monotonic ->
    ("time", "monotonic"); bare hash() -> ("hash", None)."""
    last = None
    node = func
    while isinstance(node, ast.Attribute):
        if last is None:
            last = node.attr
        node = node.value
    if isinstance(node, ast.Name):
        return node.id, last
    return None, last


def _impure_calls(fn: ast.FunctionDef) -> Iterable[tuple[int, str]]:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        root, last = _dotted(node.func)
        if root in _BANNED_ROOTS:
            yield node.lineno, f"{root}.{last}" if last else root
        elif root in _BANNED_BARE and last is None:
            yield node.lineno, root
        elif last in _BANNED_ATTRS:
            yield node.lineno, f"{root}.{last}" if root else last


def _class_plugin_name(cls: ast.ClassDef) -> str | None:
    """The `name = "..."` class attribute of a plugin class."""
    for stmt in cls.body:
        if (
            isinstance(stmt, ast.Assign)
            and any(isinstance(t, ast.Name) and t.id == "name"
                    for t in stmt.targets)
            and isinstance(stmt.value, ast.Constant)
            and isinstance(stmt.value.value, str)
        ):
            return stmt.value.value
    return None


class SignatureSyncChecker(ProjectChecker):
    rules = {
        SIG01: "signature fragment impure (clock/rng/hash/traced value) or "
               "a kernel filter row has no sign fragment / exemption",
    }

    # -- purity (module-scoped) ------------------------------------------

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        p = ctx.posix_path
        in_plugins = f"/{PLUGINS_DIR}/" in p or p.startswith(f"{PLUGINS_DIR}/")
        in_runtime = p.endswith(RUNTIME)
        if not (in_plugins or in_runtime):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if not (isinstance(stmt, ast.FunctionDef)
                        and stmt.name in ("sign", "sign_pod")):
                    continue
                for line, what in _impure_calls(stmt):
                    yield Finding(
                        p, line, 0, SIG01,
                        f"signature fragment {node.name}.{stmt.name} calls "
                        f"{what}() — fragments must be pure functions of "
                        "the pod spec (clock/rng/hash drift breaks "
                        "equivalence-class reuse)",
                    )

    # -- coverage (project-scoped) ---------------------------------------

    def check_project(self, root: Path) -> Iterable[Finding]:
        kernels = root / KERNELS
        plugins_dir = root / PLUGINS_DIR
        if not (kernels.is_file() and plugins_dir.is_dir()):
            return  # partial tree (fixture dirs) — nothing to cross-check
        try:
            ktree = ast.parse(kernels.read_text(), filename=str(kernels))
        except SyntaxError:
            return
        filter_names: list[tuple[str, int]] = []
        fn_line = 1
        for node in ktree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "FILTER_NAMES"
                for t in node.targets
            ) and isinstance(node.value, (ast.Tuple, ast.List)):
                fn_line = node.lineno
                for el in node.value.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, str):
                        filter_names.append((el.value, el.lineno))
        if not filter_names:
            return

        signed: set[str] = set()
        for pf in sorted(plugins_dir.glob("*.py")):
            try:
                tree = ast.parse(pf.read_text(), filename=str(pf))
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                has_sign = any(
                    isinstance(s, ast.FunctionDef) and s.name == "sign"
                    for s in node.body
                )
                if has_sign:
                    pname = _class_plugin_name(node)
                    if pname:
                        signed.add(pname)

        for name, line in filter_names:
            if name in signed:
                continue
            if name in _SIGN_EXEMPT:
                continue
            yield Finding(
                kernels.as_posix(), line or fn_line, 0, SIG01,
                f"kernel filter row {name!r} has no plugin sign fragment "
                "and no _SIGN_EXEMPT justification in "
                "analysis/signature_sync.py — unsigned dimensions merge "
                "non-identical pods in the BatchCache hint path",
            )
