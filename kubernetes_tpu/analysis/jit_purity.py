"""JIT purity / host-sync / bit-compat dtype rules (JIT01-JIT04).

These rules are per-file: the traced-closure walk below stops at the
module boundary. The cross-module closure — a host sync reached from a
traced root *through a helper in another module* — is EFF01 in
whole_program.py, which propagates effect sets over the project call
graph; keep the two in sync when adding host-sync patterns.

The bit-compat contract (SURVEY.md §7, ops/kernels.py module docstring) says
the dense kernels' score math is int32/float32 with a fixed op order, traced
once and replayed. Four things quietly break that:

- JIT01: host syncs — `.item()`, or `float()`/`int()`/`bool()` applied to a
  traced value — force a device round-trip per call and fail under jit.
- JIT02: `np.*` calls on traced values escape the trace (numpy computes on
  the concrete tracer-backed host buffer at trace time, freezing one
  input's values into the compiled program).
- JIT03: Python `for`/`while` driven by a traced array unrolls the loop at
  trace time (or raises TracerBoolConversionError) instead of lowering to
  `lax` control flow.
- JIT04: 64-bit dtypes (`float64`/`int64`/`uint64`/`complex128`) or enabling
  `jax_enable_x64` inside the bit-compat modules widen the score math and
  desync the TPU path from the host plugin fan-out.

Traced scope = functions decorated `@jax.jit` / `@functools.partial(jax.jit,
...)` (plus vmap/pmap), every function referenced from a traced body (the
kernel helpers `filter_masks`/`scores`/`_assign_step` are reached this way),
and defs nested inside traced bodies (shard_map bodies). Params at declared
`static_argnums` positions — and conventionally-static names like `cfg` /
`layout` / `comm` — are not traced values; neither are `.shape`/`.dtype`
/`len()` projections of traced arrays, which are static under jit.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, ModuleContext

JIT01 = "JIT01"
JIT02 = "JIT02"
JIT03 = "JIT03"
JIT04 = "JIT04"

# modules whose score math carries the bit-compat contract (JIT04 scope)
BIT_COMPAT_SUFFIXES = ("ops/kernels.py", "scheduler/tpu/backend.py")

WIDE_DTYPES = ("float64", "int64", "uint64", "complex128")

# params that hold static config by convention even without static_argnums
STATIC_PARAM_NAMES = {"self", "cls", "cfg", "config", "layout", "comm",
                      "mesh", "names", "axis_name"}

# attribute projections of a traced array that are static under jit
STATIC_PROJECTIONS = {"shape", "ndim", "dtype", "size", "aval"}

_JIT_DECORATORS = {"jit", "vmap", "pmap"}


def _dotted(node: ast.AST) -> str | None:
    """a.b.c attribute chain as a string, None for anything fancier."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_ref(node: ast.AST) -> bool:
    d = _dotted(node)
    return d is not None and d.split(".")[-1] in _JIT_DECORATORS


def _decorator_static_argnums(dec: ast.expr) -> tuple[bool, set[int]]:
    """(is_jit_decorator, static positional indices)."""
    if _is_jit_ref(dec):
        return True, set()
    if isinstance(dec, ast.Call):
        d = _dotted(dec.func)
        if d is not None and d.split(".")[-1] == "partial":
            if dec.args and _is_jit_ref(dec.args[0]):
                static: set[int] = set()
                for kw in dec.keywords:
                    if kw.arg in ("static_argnums", "static_argnames"):
                        static |= _const_ints(kw.value)
                return True, static
        elif _is_jit_ref(dec.func):
            static = set()
            for kw in dec.keywords:
                if kw.arg in ("static_argnums", "static_argnames"):
                    static |= _const_ints(kw.value)
            return True, static
    return False, set()


def _const_ints(node: ast.expr) -> set[int]:
    out: set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.add(el.value)
    return out


def _param_names(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    names = [p.arg for p in (*a.posonlyargs, *a.args)]
    if a.vararg:
        names.append(a.vararg.arg)
    names.extend(p.arg for p in a.kwonlyargs)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


class _TracedFn:
    def __init__(self, fn: ast.FunctionDef, static_params: set[str]):
        self.fn = fn
        self.static_params = static_params


def _collect_traced(tree: ast.Module) -> list[_TracedFn]:
    """jit-decorated roots + closure over referenced module-level defs +
    defs nested inside traced bodies (shard_map / scan bodies)."""
    module_defs: dict[str, ast.FunctionDef] = {
        n.name: n
        for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    roots: list[_TracedFn] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            is_jit, static_idx = _decorator_static_argnums(dec)
            if is_jit:
                params = _param_names(node)
                static = {params[i] for i in static_idx if i < len(params)}
                roots.append(_TracedFn(node, static))
                break

    traced: dict[ast.FunctionDef, _TracedFn] = {t.fn: t for t in roots}
    work = list(roots)
    while work:
        t = work.pop()
        for node in ast.walk(t.fn):
            # module-level helpers referenced from a traced body are traced
            if isinstance(node, ast.Name) and node.id in module_defs:
                fn = module_defs[node.id]
                if fn not in traced and fn is not t.fn:
                    nt = _TracedFn(fn, set())
                    traced[fn] = nt
                    work.append(nt)
            # nested defs (shard_map bodies) run inside the trace
            elif (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node is not t.fn
                and node not in traced
            ):
                nt = _TracedFn(node, set())
                traced[node] = nt
                work.append(nt)
    return list(traced.values())


class _TracedNames:
    """Param-seeded traced-value names for one function, with one level of
    local propagation (y = f(traced) makes y traced)."""

    def __init__(self, t: _TracedFn):
        self.names = {
            p
            for p in _param_names(t.fn)
            if p not in t.static_params and p not in STATIC_PARAM_NAMES
        }
        for _ in range(10):
            grew = False
            for node in ast.walk(t.fn):
                if isinstance(node, ast.Assign) and self.expr_traced(node.value):
                    for tgt in node.targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name) and n.id not in self.names:
                                self.names.add(n.id)
                                grew = True
            if not grew:
                break

    def expr_traced(self, node: ast.AST) -> bool:
        """Does this expression involve a traced value (ignoring static
        .shape/.dtype projections and len())?"""
        if isinstance(node, ast.Attribute) and node.attr in STATIC_PROJECTIONS:
            return False
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d == "len":
                return False
            if d is not None and d.split(".")[0] in ("jnp", "jax"):
                return True
        if isinstance(node, ast.Name):
            return node.id in self.names
        return any(
            self.expr_traced(child) for child in ast.iter_child_nodes(node)
        )


class JitPurityChecker(Checker):
    rules = {
        JIT01: "host sync inside a traced function "
               "(.item() / float() / int() / bool() on a traced value)",
        JIT02: "np.* call on a traced value inside a traced function "
               "(escapes the trace; use jnp)",
        JIT03: "Python for/while driven by a traced array "
               "(unrolls at trace time; use lax control flow)",
        JIT04: "64-bit dtype in a bit-compat module "
               "(score math contract is int32/float32, fixed op order)",
    }

    def __init__(self, bit_compat_suffixes: tuple[str, ...] = BIT_COMPAT_SUFFIXES):
        self.bit_compat_suffixes = bit_compat_suffixes

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: list[Finding] = []
        if ctx.posix_path.endswith(self.bit_compat_suffixes):
            findings.extend(self._check_64bit(ctx))
        for t in _collect_traced(ctx.tree):
            findings.extend(self._check_traced_body(ctx, t))
        return findings

    # -- JIT04 ---------------------------------------------------------
    def _check_64bit(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and node.attr in WIDE_DTYPES:
                yield Finding(
                    ctx.posix_path, node.lineno, node.col_offset, JIT04,
                    f"64-bit dtype {_dotted(node) or node.attr} in "
                    "bit-compat module (contract: int32/float32)",
                )
            elif (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and node.value in WIDE_DTYPES
            ):
                yield Finding(
                    ctx.posix_path, node.lineno, node.col_offset, JIT04,
                    f"64-bit dtype string {node.value!r} in bit-compat module",
                )
            elif (
                isinstance(node, ast.Constant)
                and node.value == "jax_enable_x64"
            ):
                yield Finding(
                    ctx.posix_path, node.lineno, node.col_offset, JIT04,
                    "jax_enable_x64 would widen the whole module to 64-bit",
                )

    # -- JIT01/02/03 ---------------------------------------------------
    def _check_traced_body(
        self, ctx: ModuleContext, t: _TracedFn
    ) -> Iterable[Finding]:
        tn = _TracedNames(t)
        fname = t.fn.name

        def walk(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                # nested defs get their own _TracedFn pass
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                yield child
                yield from walk(child)

        for node in walk(t.fn):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                # .item() on anything inside a trace is a host sync
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                ):
                    yield Finding(
                        ctx.posix_path, node.lineno, node.col_offset, JIT01,
                        f".item() inside traced function {fname!r} forces a "
                        "host sync",
                    )
                elif d in ("float", "int", "bool") and any(
                    tn.expr_traced(a) for a in node.args
                ):
                    yield Finding(
                        ctx.posix_path, node.lineno, node.col_offset, JIT01,
                        f"{d}() on a traced value inside {fname!r} forces a "
                        "host sync",
                    )
                elif (
                    d is not None
                    and d.split(".")[0] in ("np", "numpy")
                    and len(d.split(".")) > 1
                    and any(tn.expr_traced(a) for a in node.args)
                ):
                    yield Finding(
                        ctx.posix_path, node.lineno, node.col_offset, JIT02,
                        f"{d}() on a traced value inside {fname!r} escapes "
                        "the trace (use jnp)",
                    )
            elif isinstance(node, ast.For) and self._iter_is_traced_array(
                tn, node
            ):
                yield Finding(
                    ctx.posix_path, node.lineno, node.col_offset, JIT03,
                    f"Python for-loop over a traced array inside {fname!r} "
                    "unrolls at trace time",
                )
            elif isinstance(node, ast.While) and tn.expr_traced(node.test):
                yield Finding(
                    ctx.posix_path, node.lineno, node.col_offset, JIT03,
                    f"while-loop condition on a traced value inside "
                    f"{fname!r} cannot lower (use lax.while_loop)",
                )

    @staticmethod
    def _iter_is_traced_array(tn: _TracedNames, loop: ast.For) -> bool:
        """Flag iterating the array itself, not static structure around it:
        bare traced name, subscript of one, or a jnp/jax call result.
        `planes.items()` / `range(x.shape[0])` / `enumerate(names)` stay
        legal, as does `for k in planes:` dict-keys iteration — detected by
        the loop target serving as a subscript key in the body."""
        it = loop.iter
        if isinstance(it, ast.Name):
            if it.id not in tn.names:
                return False
            targets = {
                n.id for n in ast.walk(loop.target) if isinstance(n, ast.Name)
            }
            for node in ast.walk(loop):
                if isinstance(node, ast.Subscript):
                    for n in ast.walk(node.slice):
                        if isinstance(n, ast.Name) and n.id in targets:
                            return False  # keys iteration over a dict plane
            return True
        if isinstance(it, ast.Subscript):
            return tn.expr_traced(it.value)
        if isinstance(it, ast.Call):
            d = _dotted(it.func)
            return d is not None and d.split(".")[0] in ("jnp", "jax")
        return False
