"""kubesched-lint: AST-based invariant checker for the TPU scheduler.

Mechanically enforces the contracts the paper's bit-compat claim rests on:
jit purity (JIT01-JIT04), lock discipline in the threaded scheduler modules
(LOCK01-LOCK04, LOCK04 being the prepare/commit split's short-commit
contract), snapshot immutability outside the cache layer (SNAP01),
kernel/registry constant sync (REG01-REG02), fault-point declaration sync
— every fire() call site names a FAULT_POINTS entry (FI01),
signature-fragment
purity/coverage for the batching hint path (SIG01), carry coherence —
node-plane / device-carry state may only be written through backend.py's
invalidation hooks so the cross-wave signature cache can never go stale
(SIG02), pipeline-state ownership — the streaming-wave double buffer and
the in-flight wave handle may only be written from backend.py so the
pipelined buffers never desynchronize (PIPE01), host-side-only
telemetry — no recorder/tracer/metrics calls inside traced code (OBS01),
ledger metric-series sync — every series the pod latency ledger declares
and emits is registered in scheduler/metrics.py (OBS02),
accounted device-transfer seam — no raw device_put in backend.py and every
seam call names a declared TRANSFER_PLANES plane, so the transfer ledger
sees every byte (OBS03),
cold-start plane-upload seam — the full-plane re-put of the node planes is
only legal inside backend.py's one sanctioned cold-start seam, so per-burst
upload bytes cannot silently re-couple to cluster size (SHARD01),
retry/fault-injection discipline — no hand-rolled backoff loops or
ad-hoc random flakes outside the shared helpers (RET01),
and reconcile-restored state ownership — the attributes a restart's
reconcile() re-derives from store truth (RECONCILE_RESTORED_STATE in
scheduler/scheduler.py) are writable only in their sanctioned owning
modules, so crash recovery never races a stray writer (CRASH01),
and fleet shard-ownership state ownership — the member-held shard set and
the installed ownership predicate (FLEET_SHARD_STATE in
scheduler/fleet.py) are writable only in scheduler/fleet.py, so the
fleet's admission/pop gates can never disagree with the lease record
about who owns a pod (FLEET01).

On top of the per-file rules sits a whole-program pass (callgraph.py +
effects.py + whole_program.py): a project-wide symbol table and
conservative call graph over which per-function effect sets — host
syncs, telemetry, rng consumption, lock acquisition, guarded-state
writes, device transfers, fault points — are propagated to a fixpoint.
It powers the transitive rules: EFF01/EFF02 (host-sync or telemetry
reached from inside a traced region ACROSS a module boundary — the
closure of JIT01-03/OBS01), LOCK05 (lock-ordering cycles, the deadlock
half LOCK01-04 can't see), RNG01 (the seeded tie-break stream consumed
outside the sanctioned scheduling core), and a transitive mode for the
ownership rules (SIG02/PIPE01/GANG01/CRASH01/SHARD01/FLEET01: calling a
mutating helper cross-module is flagged, not just the direct write).

CLI: `python -m kubernetes_tpu.analysis [paths]` (exit 1 on findings);
suppress a single line with `# kubesched-lint: disable=RULE`. Extra
modes: `--format=json`, `--audit-suppressions` (dead-disable report,
LINT02), `--graph FUNC` (dump one function's call-graph slice + effect
sets), `--no-cache` (bypass `.kubesched_lint_cache/`).
"""

from .core import (
    Checker,
    Finding,
    ModuleContext,
    ProjectChecker,
    audit_suppressions,
    check_file,
    default_checkers,
    known_rules,
    run_paths,
)
from .callgraph import ProjectIndex, build_index
from .effects import EffectEngine
from .carry_coherence import CarryCoherenceChecker
from .crash_state import CrashStateChecker
from .fault_points import FaultPointChecker
from .fleet_state import FleetStateChecker
from .gang_seam import GangSeamChecker
from .jit_purity import JitPurityChecker
from .ledger_series import LedgerSeriesChecker
from .lock_discipline import LockDisciplineChecker
from .obs_purity import ObservabilityPurityChecker
from .pipeline_state import PipelineStateChecker
from .registry_sync import RegistrySyncChecker
from .retry_discipline import RetryDisciplineChecker
from .shard_seam import ShardSeamChecker
from .signature_sync import SignatureSyncChecker
from .snapshot_immutability import SnapshotImmutabilityChecker
from .stall_seam import StallSeamChecker
from .transfer_seam import TransferSeamChecker
from .whole_program import WholeProgramChecker

__all__ = [
    "CarryCoherenceChecker",
    "Checker",
    "CrashStateChecker",
    "EffectEngine",
    "FaultPointChecker",
    "Finding",
    "FleetStateChecker",
    "GangSeamChecker",
    "JitPurityChecker",
    "LedgerSeriesChecker",
    "LockDisciplineChecker",
    "ModuleContext",
    "ObservabilityPurityChecker",
    "PipelineStateChecker",
    "ProjectChecker",
    "ProjectIndex",
    "RegistrySyncChecker",
    "RetryDisciplineChecker",
    "ShardSeamChecker",
    "SignatureSyncChecker",
    "SnapshotImmutabilityChecker",
    "StallSeamChecker",
    "TransferSeamChecker",
    "WholeProgramChecker",
    "audit_suppressions",
    "build_index",
    "check_file",
    "default_checkers",
    "known_rules",
    "run_paths",
]
