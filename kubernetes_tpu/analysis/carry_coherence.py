"""Carry-coherence rule (SIG02) for the cross-wave signature cache.

This file catches DIRECT writes; the whole-program pass
(whole_program.py) adds SIG02's transitive mode — a function in a third
module calling into a mutating helper is flagged at the call site, so
the mutation can't be laundered through an intermediate module. A write
suppressed here generates no transitive taint.

The device-resident score rows (`TPUBackend.sig_cache`) are scores AGAINST
the carried node planes: any mutation of the carry state — the device plane
buffers, the `_carry*` bookkeeping, the dirty-row set — that does not pass
through the backend's sanctioned invalidation hooks (`invalidate_carry()`,
`mark_external()`, the carry-assembly path in `launch_batched`) leaves the
cache serving rows scored against planes that no longer exist. The replay
tier would then hand back bit-exact-looking but WRONG placements — the
worst failure mode, because every golden still passes on fresh runs.

SIG02 therefore bans, outside `scheduler/tpu/backend.py`:

- assignment (plain, augmented, annotated, starred, tuple-unpacked) to an
  attribute in the guarded set: `_carry`, `_carry_rows`, `_carry_anti`,
  `_carry_pref`, `_carry_external`, `_pending_dirty`, `_device_planes`,
  `sig_cache`, and anything else spelled `_carry*`;
- `del` of such an attribute;
- subscript/element writes through one (`backend._device_planes["x"] = p`);
- mutating method calls on one (`.clear()`, `.update()`, `.add()`,
  `.discard()`, `.pop()`, `.remove()`, `.append()`, `.extend()`,
  `.setdefault()`, and the cache's own `.store()`).

Reads (`backend._carry is not None`, `getattr(b, "_pending_dirty", ...)`)
and the sanctioned hooks (`invalidate_carry()` / `mark_external()`) remain
free — the rule polices writes, not observation.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from .core import Checker, Finding, ModuleContext

SIG02 = "SIG02"

# the one module allowed to touch carry/cache state directly
BACKEND = "scheduler/tpu/backend.py"

_GUARDED = {
    "_carry",
    "_carry_rows",
    "_carry_anti",
    "_carry_pref",
    "_carry_external",
    "_pending_dirty",
    "_device_planes",
    "sig_cache",
}

# method names that mutate their receiver in-place
_MUTATORS = {
    "clear", "update", "add", "discard", "pop", "remove", "append",
    "extend", "setdefault", "store",
}


def _is_guarded(name: str) -> bool:
    return name in _GUARDED or name.startswith("_carry")


def _guarded_attrs(expr: ast.expr) -> Iterator[tuple[int, str]]:
    """(line, attr) for every guarded attribute access inside `expr`."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and _is_guarded(node.attr):
            yield node.lineno, node.attr


class CarryCoherenceChecker(Checker):
    rules = {
        SIG02: "carry/plane/signature-cache state written outside "
               "scheduler/tpu/backend.py — route through invalidate_carry()"
               " / mark_external() so the cross-wave cache stays coherent",
    }

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        p = ctx.posix_path
        if p.endswith(BACKEND):
            return  # the sanctioned site: backend.py owns this state
        for node in ast.walk(ctx.tree):
            yield from self._check_stmt(p, node)

    def _check_stmt(self, path: str, node: ast.AST) -> Iterator[Finding]:
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        elif isinstance(node, ast.Call):
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _MUTATORS):
                for line, attr in _guarded_attrs(func.value):
                    yield Finding(
                        path, line, 0, SIG02,
                        f"mutating call .{func.attr}() on guarded carry "
                        f"state {attr!r} outside backend.py — use the "
                        "backend's invalidation hooks (invalidate_carry / "
                        "mark_external) instead",
                    )
            return
        for tgt in targets:
            for line, attr in _guarded_attrs(tgt):
                yield Finding(
                    path, line, 0, SIG02,
                    f"write to guarded carry state {attr!r} outside "
                    "backend.py — node-plane / device-carry mutations "
                    "must route through the backend's invalidation hooks "
                    "so the cross-wave signature cache is cleared with "
                    "them",
                )
