"""Registry-order / bit-compat constant sync rules (REG01, REG02).

The dense kernels hard-code two things the host plugin registry also owns:

- the filter-mask row order (`ops/kernels.py FILTER_NAMES`) — first-failure
  priority must equal the host plugin iteration order, or the reconstructed
  "0/N nodes are available" messages diverge from the reference;
- the score weights (`KernelConfig.weights`) — must match the registry's
  `DEFAULT_WEIGHTS`, and the backend's `KERNEL_SCORE_PLUGINS` /
  `KERNEL_FILTER_PLUGINS` handoff sets must cover exactly the kernelized
  plugins, or a plugin runs twice (host + device) or not at all.

Nothing imports across these modules for the constants (kernels.py must
stay importable without the scheduler package), so the only enforcement
possible is cross-parsing — this checker reads all three files and compares.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from .core import Finding, ProjectChecker

REG01 = "REG01"
REG02 = "REG02"

KERNELS = "ops/kernels.py"
REGISTRY = "scheduler/plugins/registry.py"
BACKEND = "scheduler/tpu/backend.py"

# registry weight name -> plugin class name where they differ
_CLASS_ALIASES = {"NodeResourcesBalancedAllocation": "BalancedAllocation"}

# mask rows appended after the FILTER_NAMES block (per-constraint PTS rows,
# then the inter-pod affinity rows) — part of the kernel filter set but not
# of the fixed-order prefix
_APPENDED_FILTER_ROWS = {"PodTopologySpread", "InterPodAffinity"}


def _call_name(node: ast.expr) -> str | None:
    if isinstance(node, ast.Call):
        f = node.func
        while isinstance(f, ast.Attribute):
            f = f.value
        if isinstance(f, ast.Name):
            # return the last attribute component if any
            g = node.func
            return g.attr if isinstance(g, ast.Attribute) else f.id
    return None


def _str_elts(node: ast.expr) -> list[tuple[str, int]] | None:
    """[(value, line)] for a tuple/list/set of string constants."""
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
                return None
            out.append((el.value, el.lineno))
        return out
    return None


class _Parsed:
    def __init__(self, path: Path):
        self.path = path
        self.ok = path.is_file()
        self.tree = ast.parse(path.read_text(), filename=str(path)) if self.ok else None

    def module_str_seq(self, name: str) -> tuple[list[tuple[str, int]], int] | None:
        """Tuple-of-strings module constant -> ([(str, line)], assign line)."""
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name for t in node.targets
            ):
                value = node.value
                # frozenset({...}) / tuple / list / set
                if isinstance(value, ast.Call) and value.args:
                    value = value.args[0]
                elts = _str_elts(value)
                if elts is not None:
                    return elts, node.lineno
        return None

    def module_str_dict(self, name: str) -> tuple[dict[str, int], list[str], int] | None:
        """str->int module dict -> (mapping, declaration order, line)."""
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name for t in node.targets
            ):
                if not isinstance(node.value, ast.Dict):
                    return None
                mapping, order = {}, []
                for k, v in zip(node.value.keys, node.value.values):
                    if not (
                        isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and isinstance(v, ast.Constant) and isinstance(v.value, int)
                    ):
                        return None
                    mapping[k.value] = v.value
                    order.append(k.value)
                return mapping, order, node.lineno
        return None

    def class_weights(self, cls_name: str, attr: str) -> tuple[list[tuple[str, int]], int] | None:
        """KernelConfig.weights default -> ([(name, weight)], line)."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef) and node.name == cls_name:
                for stmt in node.body:
                    if (
                        isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)
                        and stmt.target.id == attr
                        and isinstance(stmt.value, (ast.Tuple, ast.List))
                    ):
                        pairs = []
                        for el in stmt.value.elts:
                            if not (
                                isinstance(el, (ast.Tuple, ast.List))
                                and len(el.elts) == 2
                                and isinstance(el.elts[0], ast.Constant)
                                and isinstance(el.elts[1], ast.Constant)
                            ):
                                return None
                            pairs.append((el.elts[0].value, el.elts[1].value))
                        return pairs, stmt.lineno
        return None

    def plugin_order(self, fn_name: str, var: str) -> tuple[list[str], int] | None:
        """Class-name order of the `plugins = [...]` list in default_plugins."""
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == fn_name:
                for stmt in ast.walk(node):
                    if (
                        isinstance(stmt, ast.Assign)
                        and any(isinstance(t, ast.Name) and t.id == var for t in stmt.targets)
                        and isinstance(stmt.value, ast.List)
                    ):
                        names = [_call_name(el) for el in stmt.value.elts]
                        return [n for n in names if n], stmt.lineno
        return None


class RegistrySyncChecker(ProjectChecker):
    rules = {
        REG01: "kernel filter-mask row order out of sync with the plugin "
               "registry order (first-failure priority contract)",
        REG02: "kernel score weights / plugin handoff sets out of sync "
               "with plugins/registry.py DEFAULT_WEIGHTS",
    }

    def check_project(self, root: Path) -> Iterable[Finding]:
        kernels = _Parsed(root / KERNELS)
        registry = _Parsed(root / REGISTRY)
        backend = _Parsed(root / BACKEND)
        if not (kernels.ok and registry.ok and backend.ok):
            return  # partial tree (fixture dirs) — nothing to cross-check
        rel = lambda p: p.path.as_posix()

        filter_names = kernels.module_str_seq("FILTER_NAMES")
        weights = kernels.class_weights("KernelConfig", "weights")
        default_weights = registry.module_str_dict("DEFAULT_WEIGHTS")
        order = registry.plugin_order("default_plugins", "plugins")
        k_filter = backend.module_str_seq("KERNEL_FILTER_PLUGINS")
        k_score = backend.module_str_seq("KERNEL_SCORE_PLUGINS")

        for got, what, path in (
            (filter_names, "FILTER_NAMES", kernels),
            (weights, "KernelConfig.weights", kernels),
            (default_weights, "DEFAULT_WEIGHTS", registry),
            (order, "default_plugins() plugins list", registry),
            (k_filter, "KERNEL_FILTER_PLUGINS", backend),
            (k_score, "KERNEL_SCORE_PLUGINS", backend),
        ):
            if got is None:
                yield Finding(
                    rel(path), 1, 0, REG01,
                    f"could not parse {what} for cross-checking — keep it a "
                    "literal constant",
                )
        if None in (filter_names, weights, default_weights, order, k_filter, k_score):
            return

        # -- REG01: filter order ----------------------------------------
        fnames = [n for n, _ in filter_names[0]]
        reg_order, _ = order
        pos = {n: i for i, n in enumerate(reg_order)}
        last = -1
        for name, line in filter_names[0]:
            if name not in pos:
                yield Finding(
                    rel(kernels), line, 0, REG01,
                    f"filter row {name!r} is not a registry plugin",
                )
            elif pos[name] < last:
                yield Finding(
                    rel(kernels), line, 0, REG01,
                    f"filter row {name!r} breaks registry order — mask row "
                    "order must match host plugin iteration order "
                    f"(registry has it before {reg_order[last]!r})",
                )
            else:
                last = pos[name]
        want_filter = set(fnames) | _APPENDED_FILTER_ROWS
        have_filter = {n for n, _ in k_filter[0]}
        if have_filter != want_filter:
            extra = have_filter - want_filter
            missing = want_filter - have_filter
            yield Finding(
                rel(backend), k_filter[1], 0, REG01,
                "KERNEL_FILTER_PLUGINS out of sync with kernels.py mask "
                f"rows (extra: {sorted(extra)}, missing: {sorted(missing)})",
            )

        # -- REG02: score weights ---------------------------------------
        dw, dw_order, _ = default_weights
        w_line = weights[1]
        last = -1
        for name, w in weights[0]:
            if name not in dw:
                yield Finding(
                    rel(kernels), w_line, 0, REG02,
                    f"kernel weight for {name!r} has no registry "
                    "DEFAULT_WEIGHTS entry",
                )
                continue
            if dw[name] != w:
                yield Finding(
                    rel(kernels), w_line, 0, REG02,
                    f"kernel weight {name}={w} != registry "
                    f"DEFAULT_WEIGHTS[{name!r}]={dw[name]}",
                )
            cls = _CLASS_ALIASES.get(name, name)
            if cls not in reg_order:
                yield Finding(
                    rel(kernels), w_line, 0, REG02,
                    f"kernel-scored plugin {name!r} ({cls}) is not in "
                    "default_plugins()",
                )
            idx = dw_order.index(name)
            if idx < last:
                yield Finding(
                    rel(kernels), w_line, 0, REG02,
                    f"kernel weight {name!r} breaks DEFAULT_WEIGHTS "
                    "declaration order (fixed-op-order contract)",
                )
            else:
                last = idx
        want_score = {n for n, _ in weights[0]}
        have_score = {n for n, _ in k_score[0]}
        if have_score != want_score:
            extra = have_score - want_score
            missing = want_score - have_score
            yield Finding(
                rel(backend), k_score[1], 0, REG02,
                "KERNEL_SCORE_PLUGINS out of sync with KernelConfig.weights "
                f"(extra: {sorted(extra)}, missing: {sorted(missing)})",
            )
