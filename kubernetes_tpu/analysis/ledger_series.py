"""Ledger metric-series registry sync rule (OBS02).

The pod latency ledger (`scheduler/tpu/podlatency.py`) declares every
Prometheus series it emits in one literal `LEDGER_SERIES` constant and
resolves instruments at emission time by name (`self._series("...")`)
against the `scheduler/metrics.py` registry. A series emitted but never
registered silently drops every observation (`registry.get` returns
None); a registered-but-undeclared name rots the declared contract the
README documents. Nothing imports across the seam at runtime (the ledger
must construct without a metrics object at all), so — like FI01 for fault
points — the only enforcement possible is cross-parsing.

OBS02 flags, across the whole tree:
- a `LEDGER_SERIES` declaration that is not a literal tuple/list/set of
  string constants (can't be cross-checked);
- a declared series name with no matching literal registration
  (`r.counter/gauge/histogram("name", ...)`) in `scheduler/metrics.py`;
- a `_series(...)` emission call, in a module that declares
  `LEDGER_SERIES`, whose argument is not a string literal or names a
  series outside the declaration.

Findings are project-scoped, so per-line suppressions do not apply —
register (or declare) the series instead.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from .core import Finding, ProjectChecker

OBS02 = "OBS02"

METRICS_REGISTRY = "scheduler/metrics.py"
DECL_NAME = "LEDGER_SERIES"
REGISTER_METHODS = {"counter", "gauge", "histogram"}


def _registered_names(path: Path) -> set[str] | None:
    """Literal first args of every `*.counter/gauge/histogram(...)` call
    in the metrics registry module, or None if unparseable."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return None
    out: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in REGISTER_METHODS
                and node.args):
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            out.add(first.value)
    return out


def _parse_decl(tree: ast.AST) -> tuple[set[str] | None, int] | None:
    """(declared names | None-if-non-literal, lineno) for LEDGER_SERIES,
    or None when the module has no declaration at all."""
    for node in getattr(tree, "body", ()):
        if not (isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == DECL_NAME
            for t in node.targets
        )):
            continue
        value = node.value
        if isinstance(value, ast.Call) and value.args:
            value = value.args[0]  # frozenset((...)) / tuple((...)) wrapper
        if not isinstance(value, (ast.Tuple, ast.List, ast.Set)):
            return None, node.lineno
        out: set[str] = set()
        for el in value.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None, node.lineno
            out.add(el.value)
        return out, node.lineno
    return None


class LedgerSeriesChecker(ProjectChecker):
    rules = {
        OBS02: "ledger metric series out of sync with scheduler/metrics.py "
               "registry (unregistered, undeclared, or non-literal name)",
    }

    def check_project(self, root: Path) -> Iterable[Finding]:
        registry = root / METRICS_REGISTRY
        if not registry.is_file():
            return  # partial tree (fixture dirs) — nothing to cross-check
        registered = _registered_names(registry)
        if registered is None:
            yield Finding(
                registry.as_posix(), 1, 0, OBS02,
                "could not parse scheduler/metrics.py registrations for "
                "cross-checking",
            )
            return
        for path in sorted(root.rglob("*.py")):
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except (OSError, SyntaxError):
                continue  # LINT01 reports unparseable files
            yield from self._check_tree(path.as_posix(), tree, registered)

    def _check_tree(
        self, path: str, tree: ast.AST, registered: set[str]
    ) -> Iterable[Finding]:
        decl = _parse_decl(tree)
        if decl is None:
            return  # module emits no ledger series
        declared, lineno = decl
        if declared is None:
            yield Finding(
                path, lineno, 0, OBS02,
                f"{DECL_NAME} must be a literal tuple of string constants "
                "so OBS02 can cross-check it against scheduler/metrics.py",
            )
            return
        for name in sorted(declared - registered):
            yield Finding(
                path, lineno, 0, OBS02,
                f"{DECL_NAME} entry {name!r} is not registered in "
                "scheduler/metrics.py — every observation on it would be "
                "silently dropped",
            )
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_series"
                    and (node.args or node.keywords)):
                continue
            arg = node.args[0] if node.args else node.keywords[0].value
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                yield Finding(
                    path, node.lineno, node.col_offset, OBS02,
                    "_series() name must be a string literal so OBS02 can "
                    f"cross-check it against {DECL_NAME}",
                )
            elif arg.value not in declared:
                yield Finding(
                    path, node.lineno, node.col_offset, OBS02,
                    f"_series({arg.value!r}) emits a series not declared "
                    f"in {DECL_NAME}",
                )
