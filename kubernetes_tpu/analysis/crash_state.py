"""Reconcile-restored state rule (CRASH01).

Direct writes only; CRASH01's transitive mode (calling a mutating helper
cross-module) lives in whole_program.py, which re-parses the same
RECONCILE_RESTORED_STATE declaration via this module's _parse_state.

`scheduler/scheduler.py` declares, in one `RECONCILE_RESTORED_STATE`
literal, every attribute a fresh scheduler's `reconcile()` re-derives from
store truth after a crash — the assumed-pod set, the gang quorum table,
and the wave pipeline's in-flight handles — together with the ONE module
sanctioned to write each (its owning class). The restart contract
(README "Restart & recovery") is only sound if that state has exactly one
writer: a stray mutation from, say, a plugin or a controller would be
invisible to reconcile's sweeps, and the next crash/restart would recover
against state the store never agreed to.

CRASH01 therefore flags, across the whole tree:

- assignment (plain, augmented, annotated, tuple-unpacked) to a declared
  attribute outside its sanctioned module;
- `del` of such an attribute;
- mutating method calls on one (`.clear()`, `.update()`, `.popleft()`,
  ...).

The declaring module itself (`scheduler/scheduler.py`) is exempt — it
owns the contract and reconcile's sweeps go through the owners' methods
anyway. Reads stay free everywhere: the rule polices writes, not
observation. Like FI01, nothing imports the constant at the write sites,
so cross-parsing is the only enforcement possible; findings are
project-scoped and per-line suppressions do not apply — route the write
through the owning module instead.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, Iterator

from .core import Finding, ProjectChecker

CRASH01 = "CRASH01"

SCHEDULER = "scheduler/scheduler.py"

# method names that mutate their receiver in-place (the deque forms
# included: _wave_completions is a deque)
_MUTATORS = {
    "clear", "update", "add", "discard", "pop", "remove", "append",
    "extend", "setdefault", "store", "appendleft", "popleft", "insert",
}


def _parse_state(path: Path) -> dict[str, set[str]] | None:
    """The RECONCILE_RESTORED_STATE literal as {attr: sanctioned files},
    or None if it is not a literal tuple of (str, str) pairs."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "RECONCILE_RESTORED_STATE"
            for t in node.targets
        ):
            value = node.value
            if not isinstance(value, (ast.Tuple, ast.List)):
                return None
            out: dict[str, set[str]] = {}
            for el in value.elts:
                if not (isinstance(el, (ast.Tuple, ast.List))
                        and len(el.elts) == 2
                        and all(isinstance(c, ast.Constant)
                                and isinstance(c.value, str)
                                for c in el.elts)):
                    return None
                attr, owner = (c.value for c in el.elts)
                out.setdefault(attr, set()).add(owner)
            return out
    return None


def _guarded_attrs(
    expr: ast.expr, guarded: set[str]
) -> Iterator[tuple[int, str]]:
    """(line, attr) for every guarded attribute access inside `expr`."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in guarded:
            yield node.lineno, node.attr


class CrashStateChecker(ProjectChecker):
    rules = {
        CRASH01: "reconcile-restored scheduler state written outside its "
                 "sanctioned owner (see scheduler/scheduler.py "
                 "RECONCILE_RESTORED_STATE) — crash recovery only re-derives "
                 "state the owning module wrote",
    }

    def check_project(self, root: Path) -> Iterable[Finding]:
        decl = root / SCHEDULER
        if not decl.is_file():
            return  # partial tree (fixture dirs) — nothing to cross-check
        state = _parse_state(decl)
        if state is None:
            yield Finding(
                decl.as_posix(), 1, 0, CRASH01,
                "could not parse RECONCILE_RESTORED_STATE for "
                "cross-checking — keep it a literal tuple of "
                "(attribute, sanctioned module) string pairs",
            )
            return
        for path in sorted(root.rglob("*.py")):
            posix = path.as_posix()
            if posix.endswith(SCHEDULER):
                continue  # the contract's declaration site
            guarded = {
                attr for attr, owners in state.items()
                if not any(posix.endswith(owner) for owner in owners)
            }
            if not guarded:
                continue
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except (OSError, SyntaxError):
                continue  # LINT01 reports unparseable files
            yield from self._check_tree(posix, tree, guarded)

    def _check_tree(
        self, path: str, tree: ast.AST, guarded: set[str]
    ) -> Iterator[Finding]:
        for node in ast.walk(tree):
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = list(node.targets)
            elif isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in _MUTATORS):
                    for line, attr in _guarded_attrs(func.value, guarded):
                        yield Finding(
                            path, line, 0, CRASH01,
                            f"mutating call .{func.attr}() on "
                            f"reconcile-restored state {attr!r} outside its "
                            "sanctioned owner — route the write through the "
                            "owning module so crash recovery stays sound",
                        )
                continue
            for tgt in targets:
                for line, attr in _guarded_attrs(tgt, guarded):
                    yield Finding(
                        path, line, 0, CRASH01,
                        f"write to reconcile-restored state {attr!r} outside "
                        "its sanctioned owner (see RECONCILE_RESTORED_STATE) "
                        "— a stray writer here is invisible to reconcile's "
                        "sweeps and corrupts the next restart's recovery",
                    )
