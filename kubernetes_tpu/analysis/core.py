"""kubesched-lint core: findings, suppressions, checker registry, file runner.

The framework is deliberately small: a checker is a class with a `rules`
dict (rule id -> one-line description) and a `check_module(ctx)` hook that
yields `Finding`s for one parsed file; project-scoped checkers (registry
sync) instead implement `check_project(root)`. The runner parses each file
once, hands the shared `ModuleContext` to every checker, then filters the
merged findings through `# kubesched-lint: disable=RULE` line suppressions.

Suppression semantics (mirrors pylint's `# pylint: disable=` but scoped to
one physical line): a comment `# kubesched-lint: disable=RULE[,RULE2]` on
line N silences findings with those rule ids anchored to line N only. A
rule name no checker owns is itself reported (LINT00) so typo'd
suppressions can't silently rot.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Iterable, Iterator

SUPPRESS_RE = re.compile(r"#\s*kubesched-lint:\s*disable=([A-Za-z0-9_,\s-]+)")

# Rule owned by the framework itself: a suppression naming an unknown rule.
LINT00 = "LINT00"
LINT01 = "LINT01"
FRAMEWORK_RULES = {
    LINT00: "suppression names a rule no checker owns (typo'd disable)",
    LINT01: "file could not be parsed (syntax error or unreadable)",
}


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation anchored to a file/line."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class ModuleContext:
    """One parsed source file, shared by every module-scoped checker."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        # line number -> set of rule ids disabled on that line
        self.suppressions: dict[int, set[str]] = _parse_suppressions(source)

    @property
    def posix_path(self) -> str:
        return Path(self.path).as_posix()


def _parse_suppressions(source: str) -> dict[int, set[str]]:
    """Map line -> rule ids named in a kubesched-lint disable comment.

    Uses the tokenizer (not a per-line regex) so a '#' inside a string
    literal can never be misread as a suppression comment.
    """
    out: dict[int, set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenizeError:
        pass
    return out


class Checker:
    """Base class: module-scoped checkers override check_module."""

    # rule id -> one-line description; the CLI's --list-rules prints these
    rules: dict[str, str] = {}

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        return ()


class ProjectChecker(Checker):
    """Checkers that need to cross-parse several files (registry sync)."""

    def check_project(self, root: Path) -> Iterable[Finding]:
        return ()


def default_checkers() -> list[Checker]:
    from .carry_coherence import CarryCoherenceChecker
    from .crash_state import CrashStateChecker
    from .fault_points import FaultPointChecker
    from .gang_seam import GangSeamChecker
    from .jit_purity import JitPurityChecker
    from .ledger_series import LedgerSeriesChecker
    from .lock_discipline import LockDisciplineChecker
    from .obs_purity import ObservabilityPurityChecker
    from .pipeline_state import PipelineStateChecker
    from .registry_sync import RegistrySyncChecker
    from .retry_discipline import RetryDisciplineChecker
    from .shard_seam import ShardSeamChecker
    from .signature_sync import SignatureSyncChecker
    from .snapshot_immutability import SnapshotImmutabilityChecker
    from .transfer_seam import TransferSeamChecker

    return [
        JitPurityChecker(),
        LockDisciplineChecker(),
        SnapshotImmutabilityChecker(),
        RegistrySyncChecker(),
        SignatureSyncChecker(),
        CarryCoherenceChecker(),
        PipelineStateChecker(),
        ObservabilityPurityChecker(),
        RetryDisciplineChecker(),
        FaultPointChecker(),
        LedgerSeriesChecker(),
        TransferSeamChecker(),
        ShardSeamChecker(),
        GangSeamChecker(),
        CrashStateChecker(),
    ]


def known_rules(checkers: Iterable[Checker]) -> dict[str, str]:
    rules = dict(FRAMEWORK_RULES)
    for ch in checkers:
        rules.update(ch.rules)
    return rules


def iter_python_files(paths: Iterable[str | Path]) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def _apply_suppressions(
    findings: list[Finding],
    ctx: ModuleContext,
    rules: dict[str, str],
) -> list[Finding]:
    """Drop suppressed findings; report unknown rule names in suppressions."""
    kept = [
        f
        for f in findings
        if f.rule not in ctx.suppressions.get(f.line, ())
    ]
    for line, names in sorted(ctx.suppressions.items()):
        for name in sorted(names):
            if name not in rules:
                kept.append(
                    Finding(
                        ctx.posix_path,
                        line,
                        0,
                        LINT00,
                        f"unknown rule {name!r} in suppression "
                        f"(known: {', '.join(sorted(rules))})",
                    )
                )
    return kept


def check_file(
    path: str | Path, checkers: list[Checker] | None = None
) -> list[Finding]:
    """All module-scoped findings for one file, suppressions applied."""
    if checkers is None:
        checkers = default_checkers()
    p = Path(path)
    try:
        source = p.read_text()
        ctx = ModuleContext(p.as_posix(), source)
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        return [Finding(p.as_posix(), 1, 0, "LINT01", f"unparseable: {e}")]
    findings: list[Finding] = []
    for ch in checkers:
        findings.extend(ch.check_module(ctx))
    return _apply_suppressions(findings, ctx, known_rules(checkers))


def run_paths(
    paths: Iterable[str | Path],
    checkers: list[Checker] | None = None,
    project_root: str | Path | None = None,
) -> list[Finding]:
    """Lint every .py under `paths` plus project-scoped cross-file checks.

    `project_root` anchors the registry-sync checker; when None it is
    inferred as the `kubernetes_tpu` package directory containing (or
    contained by) the first path.
    """
    if checkers is None:
        checkers = default_checkers()
    findings: list[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(check_file(f, checkers))
    root = _infer_package_root(paths, project_root)
    if root is not None:
        for ch in checkers:
            if isinstance(ch, ProjectChecker):
                findings.extend(ch.check_project(root))
    return sorted(set(findings))


def _infer_package_root(
    paths: Iterable[str | Path], explicit: str | Path | None
) -> Path | None:
    if explicit is not None:
        return Path(explicit)
    for p in paths:
        p = Path(p).resolve()
        for cand in (p, *p.parents):
            if cand.name == "kubernetes_tpu" and cand.is_dir():
                return cand
    return None
