"""kubesched-lint core: findings, suppressions, checker registry, file runner.

The framework is deliberately small: a checker is a class with a `rules`
dict (rule id -> one-line description) and a `check_module(ctx)` hook that
yields `Finding`s for one parsed file; project-scoped checkers (registry
sync) instead implement `check_project(root)`. The runner parses each file
once, hands the shared `ModuleContext` to every checker, then filters the
merged findings through `# kubesched-lint: disable=RULE` line suppressions.

Suppression semantics (mirrors pylint's `# pylint: disable=` but scoped to
one physical line): a comment `# kubesched-lint: disable=RULE[,RULE2]` on
line N silences findings with those rule ids anchored to line N only. A
rule name no checker owns is itself reported (LINT00) so typo'd
suppressions can't silently rot.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Iterable, Iterator

SUPPRESS_RE = re.compile(r"#\s*kubesched-lint:\s*disable=([A-Za-z0-9_,\s-]+)")

# Rule owned by the framework itself: a suppression naming an unknown rule.
LINT00 = "LINT00"
LINT01 = "LINT01"
LINT02 = "LINT02"
FRAMEWORK_RULES = {
    LINT00: "suppression names a rule no checker owns (typo'd disable)",
    LINT01: "file could not be parsed (syntax error or unreadable)",
    LINT02: "dead suppression: the named rule no longer fires on that line "
            "(--audit-suppressions only; remove the stale disable comment)",
}


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation anchored to a file/line."""

    path: str
    line: int
    col: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class ModuleContext:
    """One parsed source file, shared by every module-scoped checker."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.lines = source.splitlines()
        # line number -> set of rule ids disabled on that line
        self.suppressions: dict[int, set[str]] = _parse_suppressions(source)

    @property
    def posix_path(self) -> str:
        return Path(self.path).as_posix()


def _parse_suppressions(source: str) -> dict[int, set[str]]:
    """Map line -> rule ids named in a kubesched-lint disable comment.

    Uses the tokenizer (not a per-line regex) so a '#' inside a string
    literal can never be misread as a suppression comment.
    """
    out: dict[int, set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenizeError:
        pass
    return out


class Checker:
    """Base class: module-scoped checkers override check_module."""

    # rule id -> one-line description; the CLI's --list-rules prints these
    rules: dict[str, str] = {}

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        return ()


class ProjectChecker(Checker):
    """Checkers that need to cross-parse several files (registry sync)."""

    def check_project(self, root: Path) -> Iterable[Finding]:
        return ()


def default_checkers() -> list[Checker]:
    from .carry_coherence import CarryCoherenceChecker
    from .crash_state import CrashStateChecker
    from .fault_points import FaultPointChecker
    from .fleet_state import FleetStateChecker
    from .gang_seam import GangSeamChecker
    from .jit_purity import JitPurityChecker
    from .ledger_series import LedgerSeriesChecker
    from .lock_discipline import LockDisciplineChecker
    from .obs_purity import ObservabilityPurityChecker
    from .pipeline_state import PipelineStateChecker
    from .registry_sync import RegistrySyncChecker
    from .retry_discipline import RetryDisciplineChecker
    from .shard_seam import ShardSeamChecker
    from .signature_sync import SignatureSyncChecker
    from .snapshot_immutability import SnapshotImmutabilityChecker
    from .stall_seam import StallSeamChecker
    from .transfer_seam import TransferSeamChecker
    from .whole_program import WholeProgramChecker

    return [
        JitPurityChecker(),
        LockDisciplineChecker(),
        SnapshotImmutabilityChecker(),
        RegistrySyncChecker(),
        SignatureSyncChecker(),
        CarryCoherenceChecker(),
        PipelineStateChecker(),
        ObservabilityPurityChecker(),
        RetryDisciplineChecker(),
        FaultPointChecker(),
        LedgerSeriesChecker(),
        StallSeamChecker(),
        TransferSeamChecker(),
        ShardSeamChecker(),
        GangSeamChecker(),
        CrashStateChecker(),
        FleetStateChecker(),
        WholeProgramChecker(),
    ]


def known_rules(checkers: Iterable[Checker]) -> dict[str, str]:
    rules = dict(FRAMEWORK_RULES)
    for ch in checkers:
        rules.update(ch.rules)
    return rules


def iter_python_files(paths: Iterable[str | Path]) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def _apply_suppressions(
    findings: list[Finding],
    ctx: ModuleContext,
    rules: dict[str, str],
) -> list[Finding]:
    """Drop suppressed findings; report unknown rule names in suppressions."""
    kept = [
        f
        for f in findings
        if f.rule not in ctx.suppressions.get(f.line, ())
    ]
    for line, names in sorted(ctx.suppressions.items()):
        for name in sorted(names):
            if name not in rules:
                kept.append(
                    Finding(
                        ctx.posix_path,
                        line,
                        0,
                        LINT00,
                        f"unknown rule {name!r} in suppression "
                        f"(known: {', '.join(sorted(rules))})",
                    )
                )
    return kept


def check_file(
    path: str | Path, checkers: list[Checker] | None = None
) -> list[Finding]:
    """All module-scoped findings for one file, suppressions applied."""
    if checkers is None:
        checkers = default_checkers()
    p = Path(path)
    try:
        source = p.read_text()
        ctx = ModuleContext(p.as_posix(), source)
    except (SyntaxError, UnicodeDecodeError, OSError) as e:
        return [Finding(p.as_posix(), 1, 0, "LINT01", f"unparseable: {e}")]
    findings: list[Finding] = []
    for ch in checkers:
        findings.extend(ch.check_module(ctx))
    return _apply_suppressions(findings, ctx, known_rules(checkers))


def run_paths(
    paths: Iterable[str | Path],
    checkers: list[Checker] | None = None,
    project_root: str | Path | None = None,
    use_cache: bool = False,
) -> list[Finding]:
    """Lint every .py under `paths` plus project-scoped cross-file checks.

    `project_root` anchors the registry-sync checker; when None it is
    inferred as the `kubernetes_tpu` package directory containing (or
    contained by) the first path. With `use_cache`, the final finding list
    is memoized on a content digest of every involved file (plus the
    analysis package's own sources) under `.kubesched_lint_cache/` — only
    when `checkers` is the default set, since a custom list isn't part of
    the key.
    """
    default_set = checkers is None
    if checkers is None:
        checkers = default_checkers()
    root = _infer_package_root(paths, project_root)
    key = None
    if use_cache and default_set:
        from . import cache

        key = cache.tree_digest(paths, root)
        cached = cache.load(key, root)
        if cached is not None:
            return cached
    findings: list[Finding] = []
    for f in iter_python_files(paths):
        findings.extend(check_file(f, checkers))
    if root is not None:
        for ch in checkers:
            if isinstance(ch, ProjectChecker):
                findings.extend(ch.check_project(root))
    result = sorted(set(findings))
    if key is not None:
        from . import cache

        cache.store(key, result, root)
    return result


def audit_suppressions(
    paths: Iterable[str | Path],
    checkers: list[Checker] | None = None,
    project_root: str | Path | None = None,
) -> list[Finding]:
    """LINT02 findings for dead `# kubesched-lint: disable=` comments.

    A suppression is dead when the rule it names (a known rule — unknown
    names are LINT00's job) produces no raw finding on that exact line.
    Raw means pre-suppression: module checkers run unfiltered, and the
    whole-program checker runs with its own suppression filtering off.
    Project-scoped checkers that never honored suppressions are included
    too, so a stale SHARD01/GANG01 disable is still reported as dead.
    """
    if checkers is None:
        checkers = default_checkers()
    from .whole_program import WholeProgramChecker

    audit_checkers: list[Checker] = [
        WholeProgramChecker(honor_suppressions=False)
        if isinstance(ch, WholeProgramChecker) else ch
        for ch in checkers
    ]
    rules = known_rules(audit_checkers)

    # raw findings keyed on (resolved path, line, rule); module checkers
    # only need to run on files that actually carry suppressions — a
    # finding elsewhere can't prove any disable comment live
    fired: set[tuple[str, int, str]] = set()
    suppressed: list[tuple[Path, ModuleContext]] = []
    for f in iter_python_files(paths):
        try:
            ctx = ModuleContext(Path(f).as_posix(), Path(f).read_text())
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue  # LINT01 reports unparseable files
        if not ctx.suppressions:
            continue
        for ch in audit_checkers:
            for finding in ch.check_module(ctx):
                fired.add((Path(finding.path).resolve().as_posix(),
                           finding.line, finding.rule))
        suppressed.append((Path(f), ctx))
    # project checkers re-parse the whole tree, so only run the ones
    # whose rules some suppression actually names; the whole-program
    # checker can also emit the ownership-family ids it transits
    needed: set[str] = set()
    for _, ctx in suppressed:
        for names in ctx.suppressions.values():
            needed.update(names)
    root = _infer_package_root(paths, project_root)
    if root is not None:
        for ch in audit_checkers:
            if not isinstance(ch, ProjectChecker):
                continue
            emits = set(ch.rules)
            if isinstance(ch, WholeProgramChecker):
                emits |= {"SIG02", "PIPE01", "GANG01", "CRASH01", "SHARD01"}
            if not emits & needed:
                continue
            for finding in ch.check_project(root):
                fired.add((Path(finding.path).resolve().as_posix(),
                           finding.line, finding.rule))
    out: list[Finding] = []
    for path, ctx in suppressed:
        resolved = path.resolve().as_posix()
        for line, names in sorted(ctx.suppressions.items()):
            for name in sorted(names):
                if name not in rules or name in FRAMEWORK_RULES:
                    continue  # unknown names are LINT00; LINT01/02 unreal
                if (resolved, line, name) not in fired:
                    out.append(Finding(
                        ctx.posix_path, line, 0, LINT02,
                        f"dead suppression: {name} no longer fires on "
                        "this line — remove the disable comment so the "
                        "justification trail stays honest",
                    ))
    return sorted(set(out))


def _infer_package_root(
    paths: Iterable[str | Path], explicit: str | Path | None
) -> Path | None:
    if explicit is not None:
        return Path(explicit)
    for p in paths:
        p = Path(p).resolve()
        for cand in (p, *p.parents):
            if cand.name == "kubernetes_tpu" and cand.is_dir():
                return cand
    return None
