"""Stall-seam rule (OBS04) for the pipeline stall profiler.

The stall profiler (`scheduler/tpu/stallprofiler.py`) promises a CLOSED
attribution vocabulary: every wave's wall clock decomposes into overlap
plus reasons from the literal `STALL_REASONS` tuple, and the README stall
table / zpage / bench columns are all keyed by those exact strings. That
contract only holds if (a) every seam stamp names a declared literal —
a typo'd or ad-hoc reason string would either raise at runtime on a cold
path or silently fork the vocabulary — and (b) the per-record stall state
is written in exactly one place, so the coverage invariant
(`overlap + sum(stalls) ~= wall`) can be reasoned about locally.

Nothing imports across these seams at check time (the scheduling loop
stamps through a recorder attribute, the profiler never imports its
owner), so — like FI01 for fault points and OBS02 for ledger series —
enforcement is cross-parsing. OBS04 flags, across the whole tree:

- a `STALL_REASONS` / `STALL_SERIES` declaration in stallprofiler.py that
  is not a literal tuple/list of string constants (can't be cross-checked);
- a declared stall series with no matching literal registration in
  `scheduler/metrics.py` (the OBS02 registration contract), and a
  `_series(...)` call in stallprofiler.py naming anything else;
- a `mark_gap(...)` / `note_stall(...)` / `stall_profiler.stall(...)`
  call site, outside stallprofiler.py, whose reason argument is not a
  string literal or names an undeclared reason — seams must not launder
  reasons through variables or helpers;
- a write (assign / augmented / del / mutating method call) to per-record
  stall state (`stall_by_reason`, `stall_coverage`, `stall_dominant`,
  `_stall_acc`, `_stall_mark`, `_stall_done`) outside stallprofiler.py —
  seams report through `mark_gap`/`note_stall`, never by poking records.
  (WaveRecord's dataclass field declarations are annotated NAME targets,
  not attribute writes, so declaring the fields stays legal.)

Findings are project-scoped, so per-line suppressions do not apply — use
a declared reason (or declare a new one, updating the README table and
invariant together) instead.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, Iterator

from .core import Finding, ProjectChecker
from .ledger_series import METRICS_REGISTRY, _registered_names

OBS04 = "OBS04"

PROFILER = "scheduler/tpu/stallprofiler.py"

_REASON_CALLS = {"mark_gap": 1, "note_stall": 1}
# `.stall(record, reason)` is a common-enough method name that the rule
# only binds it when called through a `stall_profiler` attribute chain
_STALL_CM = "stall"

_GUARDED_ATTRS = {
    "stall_by_reason",
    "stall_coverage",
    "stall_dominant",
    "_stall_acc",
    "_stall_mark",
    "_stall_done",
}

_MUTATORS = {
    "clear", "update", "add", "discard", "pop", "remove", "append",
    "extend", "setdefault",
}


def _parse_literal_tuple(tree: ast.AST, name: str):
    """(values | None-if-non-literal, lineno) for a module-level `name =
    (...)` declaration, or None when absent."""
    for node in getattr(tree, "body", ()):
        if not (isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        )):
            continue
        value = node.value
        if not isinstance(value, (ast.Tuple, ast.List)):
            return None, node.lineno
        out: list[str] = []
        for el in value.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None, node.lineno
            out.append(el.value)
        return out, node.lineno
    return None


def _reason_arg(node: ast.Call, pos: int) -> ast.expr | None:
    if len(node.args) > pos:
        return node.args[pos]
    for kw in node.keywords:
        if kw.arg == "reason":
            return kw.value
    return None


def _via_stall_profiler(func: ast.Attribute) -> bool:
    """True when the call receiver is a `...stall_profiler` chain (or a
    bare name that obviously holds one, e.g. `prof`/`profiler`)."""
    recv = func.value
    if isinstance(recv, ast.Attribute):
        return recv.attr == "stall_profiler"
    if isinstance(recv, ast.Name):
        return "prof" in recv.id
    return False


class StallSeamChecker(ProjectChecker):
    rules = {
        OBS04: "stall seam out of contract: non-literal/undeclared stall "
               "reason at a mark_gap/note_stall/stall call site, stall "
               "record state written outside stallprofiler.py, or "
               "STALL_REASONS/STALL_SERIES out of sync with their "
               "consumers",
    }

    def check_project(self, root: Path) -> Iterable[Finding]:
        prof_path = root / PROFILER
        if not prof_path.is_file():
            return  # partial tree (fixture dirs) — nothing to cross-check
        try:
            prof_tree = ast.parse(prof_path.read_text(),
                                  filename=str(prof_path))
        except (OSError, SyntaxError):
            return  # LINT01 reports unparseable files
        reasons = self._declared(prof_path, prof_tree, "STALL_REASONS")
        series = self._declared(prof_path, prof_tree, "STALL_SERIES")
        yield from self._decl_findings(prof_path, reasons, "STALL_REASONS")
        yield from self._decl_findings(prof_path, series, "STALL_SERIES")
        if series and series[0] is not None:
            registry = root / METRICS_REGISTRY
            registered = (_registered_names(registry)
                          if registry.is_file() else None)
            if registered is not None:
                for name in series[0]:
                    if name not in registered:
                        yield Finding(
                            prof_path.as_posix(), series[1], 0, OBS04,
                            f"STALL_SERIES entry {name!r} is not registered "
                            "in scheduler/metrics.py — every stall "
                            "observation on it would be silently dropped",
                        )
            yield from self._check_series_calls(prof_path, prof_tree,
                                                set(series[0]))
        if reasons is None or reasons[0] is None:
            return  # vocabulary unknowable; the decl finding covers it
        declared = set(reasons[0])
        for path in sorted(root.rglob("*.py")):
            posix = path.as_posix()
            if posix.endswith(PROFILER):
                continue  # the owner: internal indirection is its business
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except (OSError, SyntaxError):
                continue
            yield from self._check_tree(posix, tree, declared)

    def _declared(self, path: Path, tree: ast.AST, name: str):
        return _parse_literal_tuple(tree, name)

    def _decl_findings(self, path: Path, decl, name: str
                       ) -> Iterator[Finding]:
        if decl is None:
            yield Finding(
                path.as_posix(), 1, 0, OBS04,
                f"stallprofiler.py must declare {name} so OBS04 can "
                "cross-check its consumers",
            )
        elif decl[0] is None:
            yield Finding(
                path.as_posix(), decl[1], 0, OBS04,
                f"{name} must be a literal tuple of string constants so "
                "OBS04 can cross-check it",
            )

    def _check_series_calls(self, path: Path, tree: ast.AST,
                            declared: set[str]) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_series"
                    and (node.args or node.keywords)):
                continue
            arg = node.args[0] if node.args else node.keywords[0].value
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                yield Finding(
                    path.as_posix(), node.lineno, node.col_offset, OBS04,
                    "_series() name must be a string literal so OBS04 can "
                    "cross-check it against STALL_SERIES",
                )
            elif arg.value not in declared:
                yield Finding(
                    path.as_posix(), node.lineno, node.col_offset, OBS04,
                    f"_series({arg.value!r}) emits a series not declared "
                    "in STALL_SERIES",
                )

    def _check_tree(self, path: str, tree: ast.AST,
                    declared: set[str]) -> Iterator[Finding]:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(path, node, declared)
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in _MUTATORS
                        and isinstance(func.value, ast.Attribute)
                        and func.value.attr in _GUARDED_ATTRS):
                    yield self._write_finding(path, func.value.lineno,
                                              func.value.attr,
                                              f"mutating call .{func.attr}()")
                continue
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = list(node.targets)
            for tgt in targets:
                for sub in ast.walk(tgt):
                    if (isinstance(sub, ast.Attribute)
                            and sub.attr in _GUARDED_ATTRS):
                        yield self._write_finding(path, sub.lineno, sub.attr,
                                                  "write")

    def _check_call(self, path: str, node: ast.Call,
                    declared: set[str]) -> Iterator[Finding]:
        func = node.func
        if not isinstance(func, ast.Attribute):
            return
        if func.attr in _REASON_CALLS:
            pos = _REASON_CALLS[func.attr]
        elif func.attr == _STALL_CM and _via_stall_profiler(func):
            pos = 1
        else:
            return
        arg = _reason_arg(node, pos)
        if arg is None:
            return
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            yield Finding(
                path, node.lineno, node.col_offset, OBS04,
                f"{func.attr}() stall reason must be a string literal at "
                "the seam — a variable or helper-forwarded reason can't be "
                "cross-checked against STALL_REASONS",
            )
        elif arg.value not in declared:
            yield Finding(
                path, node.lineno, node.col_offset, OBS04,
                f"{func.attr}({arg.value!r}) names a stall reason not "
                "declared in STALL_REASONS — the attribution vocabulary "
                "is closed; declare the reason (and update the README "
                "stall table) instead",
            )

    def _write_finding(self, path: str, line: int, attr: str,
                       what: str) -> Finding:
        return Finding(
            path, line, 0, OBS04,
            f"{what} on stall record state {attr!r} outside "
            "stallprofiler.py — per-record stall attribution has exactly "
            "one writer (StallProfiler.finalize); seams report through "
            "mark_gap/note_stall instead",
        )
