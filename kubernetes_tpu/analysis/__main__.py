"""CLI: python -m kubernetes_tpu.analysis [paths...]

Exit status 0 when clean, 1 when any unsuppressed finding remains, 2 on
usage errors. Default path is the kubernetes_tpu package itself.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import default_checkers, known_rules, run_paths


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="kubesched-lint: invariant checker for the TPU scheduler",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the kubernetes_tpu "
             "package)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print every rule id and description, then exit",
    )
    args = parser.parse_args(argv)

    checkers = default_checkers()
    if args.list_rules:
        for rule, desc in sorted(known_rules(checkers).items()):
            print(f"{rule}  {desc}")
        return 0

    paths = args.paths or [str(Path(__file__).resolve().parent.parent)]
    findings = run_paths(paths, checkers)
    for f in findings:
        print(f.render())
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
