"""CLI: python -m kubernetes_tpu.analysis [paths...]

Exit status 0 when clean, 1 when any unsuppressed finding remains, 2 on
usage errors. Default path is the kubernetes_tpu package itself.

Modes beyond the plain lint run:
  --list-rules          print every rule id + description
  --format=json         machine-readable findings (one object per finding)
  --audit-suppressions  report dead `# kubesched-lint: disable=` comments
  --graph FUNC          dump call graph + inferred effects for a function
  --no-cache            bypass the content-hash result cache
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import (
    _infer_package_root, audit_suppressions, default_checkers, known_rules,
    run_paths,
)


def _dump_graph(needle: str, paths: list[str]) -> int:
    """Debugging aid for rule authors: one function's slice of the graph."""
    from .whole_program import indexed

    root = _infer_package_root(paths, None)
    if root is None:
        print(f"--graph: no kubernetes_tpu package root under {paths}",
              file=sys.stderr)
        return 2
    index, engine = indexed(root)
    hits = index.lookup(needle)
    if not hits:
        print(f"--graph: no function matches {needle!r}", file=sys.stderr)
        return 2
    for fi in hits:
        print(f"{fi.qualname}  ({fi.path}:{fi.lineno})")
        if fi.traced_root:
            print("  traced root (jit/vmap/pmap/shard_map)")
        direct = engine.direct.get(fi.qualname, {})
        trans = engine.effects.get(fi.qualname, {})
        print(f"  direct effects ({len(direct)}):")
        for eff in sorted(direct, key=lambda e: (e.kind, e.detail)):
            print(f"    {eff.render()}  @ line {direct[eff].origin_line}")
        inherited = {e: p for e, p in trans.items() if e not in direct}
        print(f"  transitive effects ({len(inherited)}):")
        for eff in sorted(inherited, key=lambda e: (e.kind, e.detail)):
            print(f"    {eff.render()}  via "
                  f"{engine.render_chain(fi.qualname, eff)}")
        print(f"  calls out ({len(fi.calls)}):")
        for c in fi.calls:
            held = f"  [holding {', '.join(sorted(c.held))}]" if c.held else ""
            print(f"    line {c.line}: {c.expr}() -> {c.callee} "
                  f"({c.kind}){held}")
        callers = list(index.callers_of(fi.qualname))
        print(f"  called from ({len(callers)}):")
        for caller, c in callers:
            print(f"    {caller.qualname}:{c.line}")
        print()
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="kubesched-lint: invariant checker for the TPU scheduler",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to lint (default: the kubernetes_tpu "
             "package)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print every rule id and description, then exit",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="finding output format (json: one object per finding with "
             "path/line/col/rule/message keys)",
    )
    parser.add_argument(
        "--audit-suppressions", action="store_true",
        help="report dead suppressions (LINT02) instead of linting",
    )
    parser.add_argument(
        "--graph", metavar="FUNC",
        help="dump the call graph + inferred effect sets for a named "
             "function (suffix match, e.g. TPUBackend.collect), then exit",
    )
    parser.add_argument(
        "--no-cache", action="store_true",
        help="bypass the content-hash result cache "
             "(.kubesched_lint_cache/)",
    )
    args = parser.parse_args(argv)

    checkers = default_checkers()
    if args.list_rules:
        for rule, desc in sorted(known_rules(checkers).items()):
            print(f"{rule}  {desc}")
        return 0

    paths = args.paths or [str(Path(__file__).resolve().parent.parent)]
    if args.graph:
        return _dump_graph(args.graph, paths)
    if args.audit_suppressions:
        findings = audit_suppressions(paths, checkers)
    else:
        # checkers=None keeps the default set, which is what the result
        # cache is keyed for
        findings = run_paths(paths, None, use_cache=not args.no_cache)
    if args.format == "json":
        print(json.dumps(
            [{"path": f.path, "line": f.line, "col": f.col,
              "rule": f.rule, "message": f.message} for f in findings],
            indent=2))
    else:
        for f in findings:
            print(f.render())
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
