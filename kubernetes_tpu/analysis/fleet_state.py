"""Fleet shard-ownership state rule (FLEET01).

Direct writes only; FLEET01's transitive mode (calling a mutating helper
cross-module) lives in whole_program.py, which re-parses the same
FLEET_SHARD_STATE declaration via this module's _parse_state.

`scheduler/fleet.py` declares, in one `FLEET_SHARD_STATE` literal, the
state the active-active fleet's correctness hangs on — the shard set a
member currently holds (`_owned_shards`) and the ownership predicate
installed into the scheduler, loop, and queue gates (`shard_filter`) —
together with the ONE module sanctioned to write each. The zero-
double-bind contract (README "Scheduler fleet") is only sound if that
state has exactly one writer: a stray mutation from, say, a plugin or a
test helper would let a member's admission gates disagree with the lease
record about who owns a pod, and two members would pop — and race to
bind — the same pod.

FLEET01 therefore flags, across the whole tree:

- assignment (plain, augmented, annotated, tuple-unpacked) to a declared
  attribute outside its sanctioned module;
- `del` of such an attribute;
- mutating method calls on one (`.add()`, `.discard()`, `.clear()`, ...).

The declaring module itself (`scheduler/fleet.py`) is exempt — it owns
the contract: ownership changes only through the per-shard electors'
acquire/release callbacks, and the filter is installed only through
`install_shard_filter`. Reads stay free everywhere (every gate is a
read). Like CRASH01, nothing imports the constant at the write sites, so
cross-parsing is the only enforcement possible; findings are
project-scoped and per-line suppressions do not apply — route the write
through scheduler/fleet.py instead.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from .core import Finding, ProjectChecker
from .crash_state import _MUTATORS, _guarded_attrs

FLEET01 = "FLEET01"

FLEET = "scheduler/fleet.py"


def _parse_state(path: Path) -> dict[str, set[str]] | None:
    """The FLEET_SHARD_STATE literal as {attr: sanctioned files}, or None
    if it is not a literal tuple of (str, str) pairs."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "FLEET_SHARD_STATE"
            for t in node.targets
        ):
            value = node.value
            if not isinstance(value, (ast.Tuple, ast.List)):
                return None
            out: dict[str, set[str]] = {}
            for el in value.elts:
                if not (isinstance(el, (ast.Tuple, ast.List))
                        and len(el.elts) == 2
                        and all(isinstance(c, ast.Constant)
                                and isinstance(c.value, str)
                                for c in el.elts)):
                    return None
                attr, owner = (c.value for c in el.elts)
                out.setdefault(attr, set()).add(owner)
            return out
    return None


class FleetStateChecker(ProjectChecker):
    rules = {
        FLEET01: "fleet shard-ownership state written outside its "
                 "sanctioned owner (see scheduler/fleet.py "
                 "FLEET_SHARD_STATE) — the zero-double-bind contract "
                 "needs the ownership gates and the lease record to have "
                 "one writer",
    }

    def check_project(self, root: Path) -> Iterable[Finding]:
        decl = root / FLEET
        if not decl.is_file():
            return  # partial tree (fixture dirs) — nothing to cross-check
        state = _parse_state(decl)
        if state is None:
            yield Finding(
                decl.as_posix(), 1, 0, FLEET01,
                "could not parse FLEET_SHARD_STATE for cross-checking — "
                "keep it a literal tuple of (attribute, sanctioned "
                "module) string pairs",
            )
            return
        for path in sorted(root.rglob("*.py")):
            posix = path.as_posix()
            if posix.endswith(FLEET):
                continue  # the contract's declaration site
            guarded = {
                attr for attr, owners in state.items()
                if not any(posix.endswith(owner) for owner in owners)
            }
            if not guarded:
                continue
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except (OSError, SyntaxError):
                continue  # LINT01 reports unparseable files
            yield from self._check_tree(posix, tree, guarded)

    def _check_tree(self, path, tree, guarded):
        for node in ast.walk(tree):
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            elif isinstance(node, ast.Delete):
                targets = list(node.targets)
            elif isinstance(node, ast.Call):
                func = node.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in _MUTATORS):
                    for line, attr in _guarded_attrs(func.value, guarded):
                        yield Finding(
                            path, line, 0, FLEET01,
                            f"mutating call .{func.attr}() on fleet "
                            f"shard-ownership state {attr!r} outside its "
                            "sanctioned owner — route the write through "
                            "scheduler/fleet.py so ownership gates and "
                            "the lease record cannot disagree",
                        )
                continue
            for tgt in targets:
                for line, attr in _guarded_attrs(tgt, guarded):
                    yield Finding(
                        path, line, 0, FLEET01,
                        f"write to fleet shard-ownership state {attr!r} "
                        "outside its sanctioned owner (see "
                        "FLEET_SHARD_STATE) — a stray writer here lets "
                        "two members both believe they own a pod, which "
                        "is a double-bind waiting for a watch gap",
                    )
