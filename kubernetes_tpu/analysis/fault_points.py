"""Fault-point declaration sync rule (FI01).

`utils/faultinject.py` declares every injection point in one
`FAULT_POINTS` constant: the golden bit-compat tests register-and-disarm
exactly that set, and chaos schedules arm by those names. A `fire()` call
site whose point name is not declared there can never be armed — the
chaos suite silently stops covering that seam — and a non-literal point
name can't be cross-checked at all. Nothing imports FAULT_POINTS at the
call sites (fire is called from packages that must not depend on the
constant's module loading order), so the only enforcement possible is
cross-parsing, same as the registry-sync checker.

FI01 flags, across the whole tree:
- a `fire(...)` / `*.fire(...)` call whose point argument is not a string
  literal;
- a literal point name missing from FAULT_POINTS.

`utils/faultinject.py` itself is exempt (the registry dispatches by
variable). Findings are project-scoped, so per-line suppressions do not
apply — declare the point instead.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from .core import Finding, ProjectChecker

FI01 = "FI01"

FAULTINJECT = "utils/faultinject.py"


def _parse_points(path: Path) -> set[str] | None:
    """The FAULT_POINTS literal as a set of names, or None if unparseable."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "FAULT_POINTS"
            for t in node.targets
        ):
            value = node.value
            if isinstance(value, ast.Call) and value.args:
                value = value.args[0]  # frozenset((...)) wrapper
            if isinstance(value, (ast.Tuple, ast.List, ast.Set)):
                out: set[str] = set()
                for el in value.elts:
                    if not (isinstance(el, ast.Constant)
                            and isinstance(el.value, str)):
                        return None
                    out.add(el.value)
                return out
    return None


def _point_arg(call: ast.Call) -> ast.expr | None:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "point":
            return kw.value
    return None


class FaultPointChecker(ProjectChecker):
    rules = {
        FI01: "fire() call site out of sync with utils/faultinject.py "
              "FAULT_POINTS (undeclared or non-literal point name)",
    }

    def check_project(self, root: Path) -> Iterable[Finding]:
        decl = root / FAULTINJECT
        if not decl.is_file():
            return  # partial tree (fixture dirs) — nothing to cross-check
        points = _parse_points(decl)
        if points is None:
            yield Finding(
                decl.as_posix(), 1, 0, FI01,
                "could not parse FAULT_POINTS for cross-checking — keep it "
                "a literal tuple of string constants",
            )
            return
        for path in sorted(root.rglob("*.py")):
            if path.as_posix().endswith(FAULTINJECT):
                continue  # the registry dispatches by variable
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except (OSError, SyntaxError):
                continue  # LINT01 reports unparseable files
            yield from self._check_tree(path.as_posix(), tree, points)

    def _check_tree(
        self, path: str, tree: ast.AST, points: set[str]
    ) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name):
                name = func.id
            elif isinstance(func, ast.Attribute):
                name = func.attr
            else:
                continue
            if name != "fire":
                continue
            arg = _point_arg(node)
            if arg is None:
                yield Finding(
                    path, node.lineno, node.col_offset, FI01,
                    "fire() call without a point argument",
                )
            elif not (isinstance(arg, ast.Constant)
                      and isinstance(arg.value, str)):
                yield Finding(
                    path, node.lineno, node.col_offset, FI01,
                    "fire() point must be a string literal so FI01 can "
                    "cross-check it against FAULT_POINTS",
                )
            elif arg.value not in points:
                yield Finding(
                    path, node.lineno, node.col_offset, FI01,
                    f"fire({arg.value!r}) references a point not declared "
                    "in utils/faultinject.py FAULT_POINTS — it can never "
                    "be armed",
                )
