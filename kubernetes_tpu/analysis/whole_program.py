"""Whole-program rules: EFF01/EFF02, LOCK05, RNG01, transitive ownership.

These are the call-graph-transitive closures of the per-file rules:

- **EFF01** — a `jit`/`vmap`/`pmap`/`shard_map` root transitively reaches
  a host sync / blocking call (`time.sleep`, `.item()`, `.result()`,
  `.wait()`, lock acquisition) through a helper in ANOTHER module. The
  per-file JIT01-03 closure stops at the module boundary; this rule
  doesn't. In-module chains are deliberately left to JIT01-03 so one
  defect never produces two findings.
- **EFF02** — same closure for telemetry/recorder calls (OBS01's
  transitive half).
- **LOCK05** — lock-ordering cycle detection. Every `with <lock>:`
  acquisition records the locks already held; every call site records the
  locks lexically held around it, and the callee's transitively inferred
  lock set contributes order edges `held -> acquired`. A cycle in that
  graph is a potential deadlock no single-file rule can see; the finding
  dumps the full acquisition-order graph with a witness per edge.
- **RNG01** — the seeded tie-break stream (a receiver named `rng` /
  `*.rng`) is consumed or advanced (`random`/`randrange`/`shuffle`/...)
  outside the sanctioned scheduling-core modules
  (`schedule_one.py` / `backend.py` / `gangplanner.py` / `scheduler.py`
  and their `advance_rng` transplant path). Any other draw skews the
  host/device bit-identity goldens one position per call.
- **transitive ownership** — SIG02 / PIPE01 / GANG01 / CRASH01 / SHARD01
  gain a transitive mode: a function outside the owning module that CALLS
  a helper (in yet another module) which mutates the guarded state is
  flagged at the call site, reusing the family's rule id with a
  "(transitive)" message. A write on a line suppressed for the family
  rule generates no taint — a reviewed suppression ends the chain.

Unlike the older project-scoped checkers, findings from this checker DO
honor per-line `# kubesched-lint: disable=` suppressions (the audit mode
needs the raw stream, so filtering can be switched off).
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, Iterator

from .core import Finding, ProjectChecker
from .callgraph import ProjectIndex
from .effects import (
    HOST_SYNC, LOCK, RNG, TELEMETRY, WRITE,
    Effect, EffectEngine, RNG_SANCTIONED,
)

EFF01 = "EFF01"
EFF02 = "EFF02"
LOCK05 = "LOCK05"
RNG01 = "RNG01"

# in-process memo: building the index re-parses the whole tree (~2s on
# the real repo), and one test/CLI session hits the same unchanged tree
# many times (lint run + audit + --graph). Keyed on every file's
# (path, mtime_ns, size) so any edit invalidates.
_MEMO: dict[Path, tuple[tuple, ProjectIndex, EffectEngine]] = {}
_MEMO_MAX = 8


def _tree_signature(root: Path) -> tuple:
    sig = []
    for p in sorted(root.rglob("*.py")):
        try:
            st = p.stat()
        except OSError:
            continue
        sig.append((p.relative_to(root).as_posix(), st.st_mtime_ns,
                    st.st_size))
    return tuple(sig)


def indexed(root: str | Path) -> tuple[ProjectIndex, EffectEngine]:
    """Memoized (ProjectIndex, EffectEngine) for an unchanged tree."""
    root = Path(root).resolve()
    sig = _tree_signature(root)
    hit = _MEMO.get(root)
    if hit is not None and hit[0] == sig:
        return hit[1], hit[2]
    index = ProjectIndex(root)
    engine = EffectEngine(index)
    _MEMO[root] = (sig, index, engine)
    while len(_MEMO) > _MEMO_MAX:
        _MEMO.pop(next(iter(_MEMO)))
    return index, engine


def _strongly_connected(graph: dict[str, set[str]]) -> list[list[str]]:
    """Tarjan SCCs (iterative); only components of size >= 2 returned."""
    index_of: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work: list[tuple[str, Iterator[str]]] = [
            (root, iter(sorted(graph.get(root, ()))))]
        index_of[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index_of:
                    index_of[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index_of[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index_of[v]:
                comp: list[str] = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) >= 2:
                    sccs.append(sorted(comp))

    for node in sorted(graph):
        if node not in index_of:
            strongconnect(node)
    return sccs


class WholeProgramChecker(ProjectChecker):
    """Call-graph-transitive rules over the whole project tree."""

    rules = {
        EFF01: "traced (jit/vmap/pmap/shard_map) function transitively "
               "reaches a host-sync/blocking call through another module "
               "(cross-module closure of JIT01-JIT03)",
        EFF02: "traced function transitively reaches a telemetry/recorder "
               "call through another module (cross-module closure of "
               "OBS01)",
        LOCK05: "lock-ordering cycle across modules: two call paths "
                "acquire the same locks in opposite orders (potential "
                "deadlock); the acquisition-order graph is dumped in the "
                "finding",
        RNG01: "seeded tie-break rng stream consumed or advanced outside "
               "the sanctioned scheduling-core paths "
               "(schedule_one/backend.advance_rng/gangplanner/scheduler) "
               "— every stray draw shifts host/device bit-identity by one "
               "position",
    }

    def __init__(self, honor_suppressions: bool = True):
        self.honor_suppressions = honor_suppressions

    def check_project(self, root: Path) -> Iterable[Finding]:
        index, engine = indexed(root)
        raw: list[Finding] = []
        raw.extend(self._traced_closure(root, index, engine))
        raw.extend(self._rng_flow(root, index, engine))
        raw.extend(self._lock_order(root, index, engine))
        raw.extend(self._transitive_ownership(root, index, engine))
        if not self.honor_suppressions:
            return sorted(set(raw))
        kept = []
        for f in raw:
            rel = Path(f.path)
            try:
                rel_posix = rel.relative_to(root).as_posix()
            except ValueError:
                rel_posix = rel.as_posix()
            mod = index.modules.get(rel_posix)
            if mod is not None and f.rule in mod.suppressions.get(f.line, ()):
                continue
            kept.append(f)
        return sorted(set(kept))

    # -- EFF01 / EFF02 ---------------------------------------------------
    def _traced_closure(
        self, root: Path, index: ProjectIndex, engine: EffectEngine
    ) -> Iterator[Finding]:
        for q, fi in index.functions.items():
            if not fi.traced_root:
                continue
            for kind, rule, what in ((HOST_SYNC, EFF01, "host-sync"),
                                     (TELEMETRY, EFF02, "telemetry")):
                for eff in engine.reaches(q, kind):
                    anchor = self._module_exit(index, engine, q, eff,
                                               fi.path)
                    if anchor is None:
                        continue  # in-module: JIT01-03/OBS01 territory
                    a_path, a_line = anchor
                    yield Finding(
                        (root / a_path).as_posix(), a_line, 0, rule,
                        f"traced function {fi.name!r} transitively "
                        f"reaches {what} {eff.detail} across a module "
                        f"boundary: {engine.render_chain(q, eff)} — "
                        "device-path code must stay pure; hoist the "
                        "effect out of the traced region",
                    )

    @staticmethod
    def _module_exit(
        index: ProjectIndex, engine: EffectEngine, q: str, eff: Effect,
        home: str,
    ) -> tuple[str, int] | None:
        """(path, line) of the first hop leaving `home`, else None."""
        hops = engine.chain(q, eff)
        for i in range(len(hops) - 1):
            nxt = index.functions.get(hops[i + 1][0])
            if nxt is not None and nxt.path != home:
                carrier = index.functions[hops[i][0]]
                return carrier.path, hops[i][1]
        return None

    # -- RNG01 -----------------------------------------------------------
    def _rng_flow(
        self, root: Path, index: ProjectIndex, engine: EffectEngine
    ) -> Iterator[Finding]:
        seen: set[tuple[str, int, str]] = set()
        for q, fi in index.functions.items():
            for eff, prov in engine.direct.get(q, {}).items():
                if eff.kind != RNG:
                    continue
                key = (fi.path, prov.origin_line, eff.detail)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    (root / fi.path).as_posix(), prov.origin_line, 0,
                    RNG01,
                    f"seeded tie-break stream consumed via {eff.detail} "
                    f"in {fi.name!r} outside the sanctioned scheduling "
                    "core ("
                    + ", ".join(m.rsplit('/', 1)[-1] for m in RNG_SANCTIONED)
                    + ") — route draws through the core API or "
                    "backend.advance_rng so host/device streams stay "
                    "bit-identical",
                )

    # -- LOCK05 ----------------------------------------------------------
    def _lock_order(
        self, root: Path, index: ProjectIndex, engine: EffectEngine
    ) -> Iterator[Finding]:
        # order edge held -> acquired, with one witness per edge
        edges: dict[tuple[str, str], tuple[str, int, str]] = {}
        for fi in index.functions.values():
            for acq in fi.acquires:
                for held in acq.held:
                    if held != acq.lock:
                        edges.setdefault(
                            (held, acq.lock),
                            (fi.path, acq.line,
                             f"{fi.qualname} acquires {acq.lock} while "
                             f"holding {held}"))
            for c in fi.calls:
                if not c.held:
                    continue
                for eff in engine.reaches(c.callee, LOCK):
                    for held in c.held:
                        if held != eff.detail:
                            edges.setdefault(
                                (held, eff.detail),
                                (fi.path, c.line,
                                 f"{fi.qualname} calls {c.expr}() which "
                                 f"acquires {eff.detail} "
                                 f"[{engine.render_chain(c.callee, eff)}] "
                                 f"while holding {held}"))
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for comp in _strongly_connected(graph):
            in_cycle = [((a, b), w) for (a, b), w in sorted(edges.items())
                        if a in comp and b in comp]
            if not in_cycle:
                continue
            witness_path, witness_line, _ = in_cycle[0][1]
            lines = [
                f"  {a} -> {b}   [{p}:{ln}: {desc}]"
                for (a, b), (p, ln, desc) in in_cycle
            ]
            yield Finding(
                (root / witness_path).as_posix(), witness_line, 0, LOCK05,
                "lock-ordering cycle (potential deadlock) among: "
                + ", ".join(comp)
                + "; acquisition-order graph:\n" + "\n".join(lines)
                + "\n  fix: pick one global order for these locks and "
                "acquire in that order on every path",
            )

    # -- transitive ownership (SIG02/PIPE01/GANG01/CRASH01/SHARD01) ------
    def _transitive_ownership(
        self, root: Path, index: ProjectIndex, engine: EffectEngine
    ) -> Iterator[Finding]:
        fam_by_rule = {}
        for fam in engine.families:
            fam_by_rule.setdefault(fam.rule, []).append(fam)
        seen: set[tuple[str, int, str, str]] = set()
        for fi in index.functions.values():
            for c in fi.calls:
                callee = index.functions.get(c.callee)
                if callee is None or callee.path == fi.path:
                    continue
                for eff in engine.reaches(c.callee, WRITE):
                    rule, attr = eff.detail.split(":", 1)
                    if rule == "SHARD01":
                        owner_ok = fi.path.endswith(
                            "scheduler/tpu/backend.py")
                    else:
                        owner_ok = any(
                            fam.is_owner(fi.path) and fam.guards(attr)
                            for fam in fam_by_rule.get(rule, ()))
                    if owner_ok:
                        continue  # owners may delegate to helpers
                    key = (fi.path, c.line, rule, attr)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Finding(
                        (root / fi.path).as_posix(), c.line, 0, rule,
                        f"(transitive) {fi.name!r} calls {c.expr}() which "
                        f"mutates guarded {attr!r} outside its owning "
                        f"module: {engine.render_chain(c.callee, eff)} — "
                        "route the mutation through the owner's "
                        "sanctioned API instead",
                    )
