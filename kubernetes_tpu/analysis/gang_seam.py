"""Gang admission/placement seam rule (GANG01).

Direct writes only; GANG01's transitive mode (calling a mutating helper
cross-module) lives in whole_program.py.

The gang-wave fast path stays bit-compatible with the host pod-group cycle
only because every piece of group admission/placement state — the GangPlan
fields and the WaveRecord gang_* outcome fields — is produced in exactly
two places: `scheduler/tpu/gangplanner.py` (the admission gate and
placement enumeration) and `scheduler/tpu/backend.py` (`run_gang`, the
device execution and outcome stamping). A third writer — a plugin caching
a "better" domain choice, a test helper patching gang_outcome, a refactor
moving admission into the wave loop — silently forks the decision state
from the host `_pod_group_algorithm` it must mirror, and the parity
goldens only catch it for the configs they happen to cover. Nothing can
enforce the seam at runtime (a rogue write still produces a plausible
outcome), so — like SHARD01 for the cold-start upload and OBS03 for the
accounted transfer seam — the enforcement is cross-parsing.

GANG01 flags any attribute assignment (plain or augmented) whose target
attribute is one of the protected gang-state names in a module other than
the two seam files. Reading the state anywhere is fine — WaveRecord
serialization, metrics, dashboards and tests all observe; dataclass field
declarations (annotated class-level names) are not assignments and are
not flagged.

Findings are project-scoped, so per-line suppressions do not apply —
route the write through gangplanner.py/backend.py instead.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from .core import Finding, ProjectChecker

GANG01 = "GANG01"

# the two sanctioned writer modules (path suffixes)
SEAM_MODULES = (
    "scheduler/tpu/gangplanner.py",
    "scheduler/tpu/backend.py",
)

# GangPlan admission state + WaveRecord gang outcome fields
PROTECTED_ATTRS = {
    "gang_placements",
    "gang_n_constrained",
    "gang_has_fallback",
    "gang_required",
    "gang_groups",
    "gang_pods",
    "gang_fallback_pods",
    "gang_outcome",
}


def _attr_targets(node: ast.AST) -> Iterable[ast.Attribute]:
    """Attribute nodes written by an Assign/AugAssign, through tuples."""
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, ast.AugAssign):
        targets = [node.target]
    else:
        return
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Attribute):
                yield sub


class GangSeamChecker(ProjectChecker):
    rules = {
        GANG01: "gang admission/placement state written outside the "
                "sanctioned seam (gangplanner.py / backend.py) — a third "
                "writer forks the device decision state from the host "
                "pod-group cycle it must mirror",
    }

    def check_project(self, root: Path) -> Iterable[Finding]:
        for path in sorted(root.rglob("*.py")):
            posix = path.as_posix()
            if any(posix.endswith(m) for m in SEAM_MODULES):
                continue
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except (OSError, SyntaxError):
                continue  # LINT01 reports unparseable files
            for node in ast.walk(tree):
                for attr in _attr_targets(node):
                    if attr.attr in PROTECTED_ATTRS:
                        yield Finding(
                            posix, node.lineno, node.col_offset, GANG01,
                            f"assignment to gang state {attr.attr!r} outside "
                            "scheduler/tpu/gangplanner.py and "
                            "scheduler/tpu/backend.py — the gang seam owns "
                            "this state; everything else observes",
                        )
