"""Lock discipline rules (LOCK01-LOCK04) for the threaded modules.

These rules see one class in one file at a time; the deadlock half —
two call paths acquiring the same locks in opposite orders across
modules — is LOCK05 in whole_program.py, built from the per-call-site
held-lock sets the project call graph records.

The threaded scheduler components (api_dispatcher, cache, scheduling_queue,
pod_workers, controllers) follow client-go's convention: every shared
attribute is guarded by one `threading.Lock`/`RLock`/`Condition` held via
`with`. Three drift patterns this checker catches:

- LOCK01: an attribute mutated both under `with self._lock:` and outside it
  — the unlocked site is a data race. `__init__` is exempt (the object is
  not yet published), and attrs holding their own synchronization
  (queue.Queue, threading.Event) are exempt.
- LOCK02: raw `.acquire()`/`.release()` on a lock attribute — an exception
  between them leaks the lock; the repo style is `with`.
- LOCK03: a blocking call (`time.sleep`, `Queue.get`, `future.result()`,
  `.join()`, `.wait()` on a non-lock object) while holding a lock stalls
  every other thread on that lock. `self._cv.wait()` on the held Condition
  is the sanctioned idiom and is not flagged.
- LOCK04: commit-section discipline — in a lock-owning class, a method
  whose name contains "commit" is the short validate-and-publish tail of a
  prepare/commit split (store.bind_pods); it may not make blocking calls
  NOR call `faultinject.fire` (fire can sleep under a LATENCY spec, which
  LOCK03 cannot see), held or not. Slow work belongs in the prepare phase
  outside the lock.

Held contexts are `with self.<lock>:` bodies, whole methods whose names end
in `_locked` (the cache.py convention), and private methods whose
intra-class call sites are all themselves held (fixpoint) — this keeps
helpers like cache.py's `_move_to_head`, only ever called under the lock,
from producing false LOCK01 positives.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from .core import Checker, Finding, ModuleContext

LOCK01 = "LOCK01"
LOCK02 = "LOCK02"
LOCK03 = "LOCK03"
LOCK04 = "LOCK04"

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
# attrs that synchronize themselves; mutating them unlocked is by design
_SELF_SYNC_FACTORIES = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                        "Event", "Barrier"}
_QUEUE_FACTORIES = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}

_MUTATORS = {"append", "appendleft", "add", "discard", "remove", "pop",
             "popitem", "popleft", "clear", "update", "extend", "insert",
             "setdefault", "put", "put_nowait"}

_CTOR_METHODS = {"__init__", "__new__", "__post_init__"}


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _self_attr(node: ast.AST) -> str | None:
    """'X' for an expression `self.X`, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _factory_name(value: ast.expr) -> str | None:
    """'Lock' for `threading.Lock(...)` / `Lock(...)`, else None."""
    if isinstance(value, ast.Call):
        d = _dotted(value.func)
        if d is not None:
            return d.split(".")[-1]
    return None


@dataclasses.dataclass
class _Event:
    kind: str          # "mut" | "acquire" | "blocking" | "fire" | "call_self"
    name: str          # attr, or callee method, or blocking description
    held: bool         # with-block status at the site (pre-fixpoint)
    method: str
    line: int
    col: int
    detail: str = ""


class _ClassScan:
    """One pass over a ClassDef: lock attrs, safe attrs, per-site events."""

    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.lock_attrs: set[str] = set()
        self.self_sync_attrs: set[str] = set()
        self.queue_attrs: set[str] = set()
        self.methods: dict[str, ast.FunctionDef] = {}
        self.events: list[_Event] = []
        self._find_attr_types()
        for m in cls.body:
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[m.name] = m
        for name, m in self.methods.items():
            self._walk(m.body, name, held=False, in_nested=False)

    def _find_attr_types(self) -> None:
        for node in ast.walk(self.cls):
            if not isinstance(node, ast.Assign):
                continue
            fac = _factory_name(node.value)
            if fac is None:
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if fac in _LOCK_FACTORIES:
                    self.lock_attrs.add(attr)
                elif fac in _SELF_SYNC_FACTORIES:
                    self.self_sync_attrs.add(attr)
                    if fac in _QUEUE_FACTORIES:
                        self.queue_attrs.add(attr)

    # -- event collection ------------------------------------------------
    def _walk(self, stmts, method: str, held: bool, in_nested: bool) -> None:
        for node in stmts:
            self._visit(node, method, held, in_nested)

    def _visit(self, node: ast.AST, method: str, held: bool, in_nested: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def bodies run later, outside the enclosing with
            self._walk(node.body, method, held=False, in_nested=True)
            return
        if isinstance(node, ast.With):
            locks_here = any(
                _self_attr(item.context_expr) in self.lock_attrs
                for item in node.items
            )
            for item in node.items:
                self._visit_expr(item.context_expr, method, held)
            self._walk(node.body, method, held or locks_here, in_nested)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for tgt in targets:
                self._record_store(tgt, method, held)
            value = node.value
            if value is not None:
                self._visit_expr(value, method, held)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._record_store(tgt, method, held)
            return
        if isinstance(node, ast.Expr):
            self._visit_expr(node.value, method, held)
            return
        # generic statement: visit expressions, recurse into bodies
        for field in ast.iter_child_nodes(node):
            if isinstance(field, ast.expr):
                self._visit_expr(field, method, held)
            elif isinstance(field, ast.stmt):
                self._visit(field, method, held, in_nested)
            elif isinstance(field, (ast.excepthandler, ast.match_case)):
                self._visit(field, method, held, in_nested)

    def _record_store(self, tgt: ast.AST, method: str, held: bool) -> None:
        attr = _self_attr(tgt)
        if attr is None and isinstance(tgt, ast.Subscript):
            attr = _self_attr(tgt.value)
        if attr is not None:
            self.events.append(
                _Event("mut", attr, held, method, tgt.lineno, tgt.col_offset)
            )

    def _visit_expr(self, node: ast.AST, method: str, held: bool) -> None:
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            func = n.func
            if isinstance(func, ast.Name):
                # bare `fire("point")` (from ..utils.faultinject import fire)
                if func.id == "fire":
                    self.events.append(
                        _Event("fire", "fire()", held, method,
                               n.lineno, n.col_offset)
                    )
                continue
            if not isinstance(func, ast.Attribute):
                continue
            # fault-point visit: can sleep under a LATENCY spec
            if func.attr == "fire":
                self.events.append(
                    _Event("fire", _dotted(func) or ".fire()", held, method,
                           n.lineno, n.col_offset)
                )
            recv_attr = _self_attr(func.value)
            d = _dotted(func)
            # LOCK02: raw acquire/release on a lock attribute
            if func.attr in ("acquire", "release") and recv_attr in self.lock_attrs:
                self.events.append(
                    _Event("acquire", recv_attr, held, method,
                           n.lineno, n.col_offset, detail=func.attr)
                )
            # mutating method call on a self attribute
            elif func.attr in _MUTATORS and recv_attr is not None:
                self.events.append(
                    _Event("mut", recv_attr, held, method,
                           n.lineno, n.col_offset)
                )
            # intra-class call (for inferred-held fixpoint)
            elif recv_attr is not None and func.attr in self.methods:
                pass  # handled below as call_self via dotted check
            # blocking calls
            if d == "time.sleep":
                self.events.append(
                    _Event("blocking", "time.sleep", held, method,
                           n.lineno, n.col_offset)
                )
            elif func.attr in ("result", "join") and not n.args:
                # zero positional args: future.result()/thread.join();
                # str.join always takes one, so it never matches
                self.events.append(
                    _Event("blocking", f".{func.attr}()", held, method,
                           n.lineno, n.col_offset)
                )
            elif func.attr in ("wait", "wait_for"):
                if recv_attr not in self.lock_attrs:
                    self.events.append(
                        _Event("blocking", f".{func.attr}()", held, method,
                               n.lineno, n.col_offset)
                    )
            elif func.attr == "get" and recv_attr in self.queue_attrs:
                self.events.append(
                    _Event("blocking", f"self.{recv_attr}.get()", held,
                           method, n.lineno, n.col_offset)
                )
            # record self.method() call sites
            if (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and func.attr in self.methods
            ):
                self.events.append(
                    _Event("call_self", func.attr, held, method,
                           n.lineno, n.col_offset)
                )

    # -- held inference --------------------------------------------------
    def held_methods(self) -> set[str]:
        """_locked-suffix methods + private methods all of whose intra-class
        call sites are held (fixpoint)."""
        held = {m for m in self.methods if m.endswith("_locked")}
        sites: dict[str, list[_Event]] = {}
        for ev in self.events:
            if ev.kind == "call_self":
                sites.setdefault(ev.name, []).append(ev)
        changed = True
        while changed:
            changed = False
            for name in self.methods:
                if name in held or not name.startswith("_"):
                    continue
                if name.startswith("__"):
                    continue
                evs = sites.get(name)
                if evs and all(e.held or e.method in held for e in evs):
                    held.add(name)
                    changed = True
        return held


class LockDisciplineChecker(Checker):
    rules = {
        LOCK01: "attribute mutated both under and outside the lock "
                "(unlocked site is a data race)",
        LOCK02: "raw lock .acquire()/.release() — use `with` so exceptions "
                "can't leak the lock",
        LOCK03: "blocking call while holding a lock stalls every thread "
                "contending on it",
        LOCK04: "commit sections (methods named *commit*) must not block "
                "or visit fault points — prepare outside the lock",
    }

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    def _check_class(
        self, ctx: ModuleContext, cls: ast.ClassDef
    ) -> Iterable[Finding]:
        scan = _ClassScan(cls)
        if not scan.lock_attrs:
            return
        held_methods = scan.held_methods()

        def is_held(ev: _Event) -> bool:
            return ev.held or ev.method in held_methods

        # LOCK02 first: raw acquire/release anywhere in the class
        for ev in scan.events:
            if ev.kind == "acquire":
                yield Finding(
                    ctx.posix_path, ev.line, ev.col, LOCK02,
                    f"{cls.name}.{ev.method} calls self.{ev.name}."
                    f"{ev.detail}() directly; use `with self.{ev.name}:`",
                )
            elif ev.kind == "blocking" and is_held(ev):
                yield Finding(
                    ctx.posix_path, ev.line, ev.col, LOCK03,
                    f"{cls.name}.{ev.method} makes blocking call "
                    f"{ev.name} while holding a lock",
                )

        # LOCK04: commit sections stay short — no blocking, no fault
        # points (a LATENCY spec turns fire() into a sleep LOCK03 cannot
        # see), regardless of whether the lock is provably held
        for ev in scan.events:
            if "commit" not in ev.method:
                continue
            if ev.kind == "blocking":
                yield Finding(
                    ctx.posix_path, ev.line, ev.col, LOCK04,
                    f"{cls.name}.{ev.method} is a commit section but makes "
                    f"blocking call {ev.name} — move it to the prepare "
                    "phase outside the lock",
                )
            elif ev.kind == "fire":
                yield Finding(
                    ctx.posix_path, ev.line, ev.col, LOCK04,
                    f"{cls.name}.{ev.method} is a commit section but visits "
                    f"fault point via {ev.name} — injected latency would "
                    "sleep inside the lock; fire in the prepare phase",
                )

        # LOCK01: attr mutated both under and outside the lock
        exempt = scan.lock_attrs | scan.self_sync_attrs
        locked_attrs = {
            ev.name
            for ev in scan.events
            if ev.kind == "mut" and is_held(ev) and ev.name not in exempt
        }
        for ev in scan.events:
            if (
                ev.kind == "mut"
                and not is_held(ev)
                and ev.method not in _CTOR_METHODS
                and ev.name in locked_attrs
                and ev.name not in exempt
            ):
                yield Finding(
                    ctx.posix_path, ev.line, ev.col, LOCK01,
                    f"{cls.name}.{ev.method} mutates self.{ev.name} outside "
                    "the lock, but other sites mutate it under the lock",
                )
