"""Accounted device-transfer seam rule (OBS03).

The device telemetry layer (`scheduler/tpu/devicetelemetry.py`) only sees
the bytes that cross the host<->device boundary if every transfer in
`scheduler/tpu/backend.py` routes through its accounted seam
(`accounted_put` / `accounted_fetch`, or the accounting-only
`account_upload` / `account_fetch`). One raw `jax.device_put` added in a
refactor silently punches a hole in the transfer ledger — per-plane
attribution stops summing to the wave total and the "upload bytes flat as
node count grows" done-criterion becomes unmeasurable again. Nothing can
enforce this at runtime (the backend works with telemetry disabled), so —
like FI01 for fault points and OBS02 for ledger series — the enforcement
is cross-parsing.

OBS03 flags:
- a `TRANSFER_PLANES` declaration in devicetelemetry.py that is not a
  literal tuple of string constants (can't be cross-checked);
- any call to `device_put` (dotted or bare) in
  `scheduler/tpu/backend.py` — the backend must route uploads through
  the seam, which applies `device_put` itself;
- a seam call, anywhere in the tree outside the declaring module, whose
  plane argument is not a string literal or names a plane outside
  `TRANSFER_PLANES` — unattributable bytes.

Findings are project-scoped, so per-line suppressions do not apply —
route the transfer through the seam (or declare the plane) instead.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from .core import Finding, ProjectChecker

OBS03 = "OBS03"

BACKEND_MODULE = "scheduler/tpu/backend.py"
DECL_MODULE = "scheduler/tpu/devicetelemetry.py"
DECL_NAME = "TRANSFER_PLANES"
SEAM_METHODS = {"accounted_put", "accounted_fetch",
                "account_upload", "account_fetch"}


def _parse_planes(path: Path) -> tuple[set[str] | None, int] | None:
    """(declared plane names | None-if-non-literal, lineno), or None when
    the module has no TRANSFER_PLANES declaration at all."""
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except (OSError, SyntaxError):
        return None
    for node in getattr(tree, "body", ()):
        if not (isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == DECL_NAME
            for t in node.targets
        )):
            continue
        value = node.value
        if isinstance(value, ast.Call) and value.args:
            value = value.args[0]  # frozenset((...)) wrapper
        if not isinstance(value, (ast.Tuple, ast.List, ast.Set)):
            return None, node.lineno
        out: set[str] = set()
        for el in value.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None, node.lineno
            out.add(el.value)
        return out, node.lineno
    return None


def _call_name(node: ast.Call) -> str | None:
    """Last segment of the called name: `a.b.device_put(...)` -> device_put."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class TransferSeamChecker(ProjectChecker):
    rules = {
        OBS03: "device transfer bypasses the accounted telemetry seam "
               "(raw device_put in backend.py, or a non-literal/undeclared "
               "plane name at a seam call site)",
    }

    def check_project(self, root: Path) -> Iterable[Finding]:
        decl_file = root / DECL_MODULE
        if not decl_file.is_file():
            return  # partial tree (fixture dirs) — nothing to cross-check
        decl = _parse_planes(decl_file)
        if decl is None:
            yield Finding(
                decl_file.as_posix(), 1, 0, OBS03,
                f"{DECL_MODULE} must declare {DECL_NAME} so OBS03 can "
                "cross-check seam call sites against it",
            )
            return
        planes, lineno = decl
        if planes is None:
            yield Finding(
                decl_file.as_posix(), lineno, 0, OBS03,
                f"{DECL_NAME} must be a literal tuple of string constants "
                "so OBS03 can cross-check seam call sites against it",
            )
            return
        for path in sorted(root.rglob("*.py")):
            if path == decl_file:
                continue  # the seam itself forwards plane names internally
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except (OSError, SyntaxError):
                continue  # LINT01 reports unparseable files
            is_backend = path.as_posix().endswith(BACKEND_MODULE)
            yield from self._check_tree(path.as_posix(), tree, planes,
                                        is_backend)

    def _check_tree(self, path: str, tree: ast.AST, planes: set[str],
                    is_backend: bool) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if is_backend and name == "device_put":
                yield Finding(
                    path, node.lineno, node.col_offset, OBS03,
                    "raw device_put in backend.py bypasses the accounted "
                    "transfer seam — route the upload through "
                    "telemetry.accounted_put so the bytes are attributed",
                )
                continue
            if name not in SEAM_METHODS:
                continue
            arg = None
            if node.args:
                arg = node.args[0]
            else:
                for kw in node.keywords:
                    if kw.arg == "plane":
                        arg = kw.value
                        break
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                yield Finding(
                    path, node.lineno, node.col_offset, OBS03,
                    f"{name}() plane must be a string literal so OBS03 can "
                    f"cross-check it against {DECL_NAME}",
                )
            elif arg.value not in planes:
                yield Finding(
                    path, node.lineno, node.col_offset, OBS03,
                    f"{name}({arg.value!r}) attributes bytes to a plane "
                    f"not declared in {DECL_NAME}",
                )
