"""Project-wide symbol table + call graph for the whole-program pass.

Per-file checkers (JIT01-03, LOCK01-04, OBS01) stop at the module
boundary: a `time.sleep` reached through a helper in another module is
invisible to them. This module parses every file under the project root
once, builds a symbol table (modules, classes, functions at every nesting
level, lock attributes), and resolves call sites into a conservative call
graph the effect engine (`effects.py`) propagates over.

Resolution is deliberately conservative — an edge is only added when the
callee is unambiguous:

- bare-name calls resolve through the lexical scope chain: enclosing
  functions' nested defs, module-level functions, `from X import f`
  imports, local classes (instantiation edges to `__init__`);
- `self.method(...)` resolves within the receiver's class, then its base
  classes (bases resolved through local classes and from-imports);
- module-qualified calls (`backend.collect(...)` where `backend` names an
  imported module, via `import a.b as backend` or `from a import backend`)
  resolve to that module's functions;
- any other attribute call (`obj.method(...)`) resolves only when exactly
  one function in the whole project defines that method name AND the name
  is not a ubiquitous container/stdlib verb (`get`, `put`, `update`, ...)
  — the "unique-name" tier. Ambiguity means no edge, never a guess.

Nested defs get an implicit `nested` edge from their enclosing function:
a shard_map body or callback defined inside `f` is treated as running
with f's effects (a conservative over-approximation, documented in the
README "Whole-program analysis" subsection).

Every call site records the set of locks lexically held around it
(`with self._lock:` blocks, aliased `Condition(self._lock)` included),
which is what the LOCK05 acquisition-order graph is built from.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Iterable, Iterator

from .core import _parse_suppressions

# traced-region roots: decorators that put a function on the device path
TRACED_DECORATORS = {"jit", "vmap", "pmap", "shard_map"}

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}

# attribute-call names too generic for unique-name resolution: linking
# `x.get()` to the one project-defined `get` would be a guess, not a fact
UNIQUE_NAME_BLOCKLIST = {
    "get", "put", "pop", "update", "add", "remove", "clear", "append",
    "extend", "insert", "discard", "setdefault", "items", "keys", "values",
    "copy", "close", "open", "read", "write", "run", "start", "stop",
    "send", "join", "wait", "notify", "acquire", "release", "fire",
    "result", "cancel", "done", "set", "next", "sort", "count", "index",
    "format", "strip", "split", "encode", "decode", "render", "name",
    "submit", "shutdown", "flush", "reset", "register", "create", "delete",
    "list", "watch", "apply", "exists", "match", "check", "handle",
}


def _dotted(node: ast.AST) -> str | None:
    """a.b.c attribute chain as a string, None for anything fancier."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class CallSite:
    """One resolved call edge leaving a function."""

    line: int
    col: int
    callee: str               # qualname of the resolved FunctionInfo
    kind: str                 # local|import|module|self|unique|nested|class
    expr: str                 # rendered callee expression ("backend.collect")
    held: frozenset[str] = frozenset()   # lock ids lexically held here


@dataclasses.dataclass
class Acquire:
    """One `with <lock>:` entry inside a function body."""

    line: int
    lock: str                 # lock id ("path::Class.attr" / "path::name")
    held: frozenset[str] = frozenset()   # locks already held at entry


@dataclasses.dataclass
class FunctionInfo:
    qualname: str             # "<posix path>::Outer.inner" dotted nesting
    path: str                 # posix path of the defining module
    name: str
    cls: str | None           # immediately enclosing class name, if any
    node: ast.AST
    lineno: int
    traced_root: bool = False
    nested_in: str | None = None          # enclosing function's qualname
    calls: list[CallSite] = dataclasses.field(default_factory=list)
    acquires: list[Acquire] = dataclasses.field(default_factory=list)
    nested: dict[str, "FunctionInfo"] = dataclasses.field(
        default_factory=dict)


@dataclasses.dataclass
class ClassInfo:
    name: str
    path: str
    bases: list[str]
    methods: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    lock_attrs: set[str] = dataclasses.field(default_factory=set)
    lock_aliases: dict[str, str] = dataclasses.field(default_factory=dict)


class ModuleInfo:
    def __init__(self, rel: str, tree: ast.Module, source: str):
        self.rel = rel                      # posix path relative to root
        self.tree = tree
        self.suppressions = _parse_suppressions(source)
        self.imports: dict[str, str] = {}   # alias -> module rel path
        # local name -> (module rel path, symbol name) for `from X import f`
        self.from_syms: dict[str, tuple[str, str]] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.module_locks: set[str] = set()


def _is_traced_decorator(dec: ast.expr) -> bool:
    def is_ref(node: ast.AST) -> bool:
        d = _dotted(node)
        return d is not None and d.split(".")[-1] in TRACED_DECORATORS

    if is_ref(dec):
        return True
    if isinstance(dec, ast.Call):
        d = _dotted(dec.func)
        if d is not None and d.split(".")[-1] == "partial":
            return bool(dec.args) and is_ref(dec.args[0])
        return is_ref(dec.func)
    return False


class ProjectIndex:
    """Symbol table + call graph for every .py under one project root."""

    def __init__(self, root: Path):
        self.root = Path(root)
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}  # qualname -> fi
        # terminal method/function name -> qualnames (unique-name tier)
        self._by_name: dict[str, list[str]] = {}
        self._parse_all()
        self._resolve_all()

    # -- construction ---------------------------------------------------
    def _parse_all(self) -> None:
        for path in sorted(self.root.rglob("*.py")):
            rel = path.relative_to(self.root).as_posix()
            try:
                source = path.read_text()
                tree = ast.parse(source, filename=str(path))
            except (OSError, SyntaxError, UnicodeDecodeError):
                continue  # LINT01 reports unparseable files
            mod = ModuleInfo(rel, tree, source)
            self.modules[rel] = mod
            self._collect_imports(mod)
            self._collect_defs(mod)

    def _module_rel(self, parts: list[str]) -> str | None:
        """Resolve dotted module parts (relative to root) to a file."""
        if not parts:
            return None
        cand = self.root.joinpath(*parts)
        if cand.with_suffix(".py").is_file():
            return cand.with_suffix(".py").relative_to(self.root).as_posix()
        if (cand / "__init__.py").is_file():
            return (cand / "__init__.py").relative_to(self.root).as_posix()
        return None

    def _abs_parts(self, mod: ModuleInfo, node: ast.ImportFrom) -> list[str] | None:
        """Dotted parts (relative to root) of an import's source module."""
        if node.level == 0:
            parts = (node.module or "").split(".")
            # absolute imports of the package itself: strip the root name
            if parts and parts[0] == self.root.name:
                return parts[1:]
            return None  # stdlib / third-party
        # relative: level 1 = this file's package, each extra level up one
        base = Path(mod.rel).parent.parts
        up = node.level - 1
        if up > len(base):
            return None
        base = list(base[:len(base) - up]) if up else list(base)
        return base + (node.module.split(".") if node.module else [])

    def _collect_imports(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    parts = alias.name.split(".")
                    if parts[0] != self.root.name:
                        continue
                    rel = self._module_rel(parts[1:])
                    if rel is not None:
                        mod.imports[alias.asname or parts[-1]] = rel
            elif isinstance(node, ast.ImportFrom):
                parts = self._abs_parts(mod, node)
                if parts is None:
                    continue
                src = self._module_rel(parts)
                for alias in node.names:
                    local = alias.asname or alias.name
                    # `from pkg import submodule` vs `from mod import sym`
                    sub = self._module_rel(parts + [alias.name])
                    if sub is not None:
                        mod.imports[local] = sub
                    elif src is not None:
                        mod.from_syms[local] = (src, alias.name)

    def _collect_defs(self, mod: ModuleInfo) -> None:
        """Register every function/class at every nesting level."""

        def visit(body, cls: ClassInfo | None, fn: FunctionInfo | None,
                  prefix: str):
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{node.name}"
                    qualname = f"{mod.rel}::{qual}"
                    if qualname in self.functions:  # redefinition: keep 1st
                        qualname = f"{qualname}@{node.lineno}"
                    fi = FunctionInfo(
                        qualname=qualname, path=mod.rel, name=node.name,
                        cls=cls.name if cls is not None else None,
                        node=node, lineno=node.lineno,
                        traced_root=any(_is_traced_decorator(d)
                                        for d in node.decorator_list),
                        nested_in=fn.qualname if fn is not None else None,
                    )
                    self.functions[fi.qualname] = fi
                    self._by_name.setdefault(node.name, []).append(
                        fi.qualname)
                    if fn is not None:
                        fn.nested[node.name] = fi
                    elif cls is not None:
                        cls.methods[node.name] = fi
                    else:
                        mod.functions.setdefault(node.name, fi)
                    visit(node.body, None, fi, f"{qual}.")
                elif isinstance(node, ast.ClassDef):
                    ci = ClassInfo(name=node.name, path=mod.rel,
                                   bases=[b for b in
                                          (_dotted(x) for x in node.bases)
                                          if b is not None])
                    if fn is None:
                        mod.classes.setdefault(node.name, ci)
                    self._find_lock_attrs(ci, node)
                    visit(node.body, ci, None, f"{prefix}{node.name}.")
                else:
                    # module/class-level statements may nest defs (rare);
                    # only descend into compound statements
                    for sub in ast.iter_child_nodes(node):
                        if isinstance(sub, ast.stmt):
                            visit([sub], cls, fn, prefix)

        visit(mod.tree.body, None, None, "")
        # module-level locks: `_lock = threading.Lock()`
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                d = _dotted(node.value.func)
                if d is not None and d.split(".")[-1] in _LOCK_FACTORIES:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            mod.module_locks.add(tgt.id)

    @staticmethod
    def _find_lock_attrs(ci: ClassInfo, cls: ast.ClassDef) -> None:
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            d = _dotted(node.value.func)
            if d is None or d.split(".")[-1] not in _LOCK_FACTORIES:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    ci.lock_attrs.add(tgt.attr)
                    # Condition(self._lock): alias onto the wrapped lock
                    if (d.split(".")[-1] == "Condition"
                            and node.value.args
                            and isinstance(node.value.args[0], ast.Attribute)
                            and isinstance(node.value.args[0].value, ast.Name)
                            and node.value.args[0].value.id == "self"):
                        ci.lock_aliases[tgt.attr] = node.value.args[0].attr

    # -- call resolution ------------------------------------------------
    def _resolve_all(self) -> None:
        for mod in self.modules.values():
            for fi in self._module_functions(mod):
                self._resolve_function(mod, fi)

    def _module_functions(self, mod: ModuleInfo) -> Iterator[FunctionInfo]:
        for fi in self.functions.values():
            if fi.path == mod.rel:
                yield fi

    def _class_of(self, mod: ModuleInfo, name: str) -> ClassInfo | None:
        if name in mod.classes:
            return mod.classes[name]
        sym = mod.from_syms.get(name)
        if sym is not None:
            src = self.modules.get(sym[0])
            if src is not None:
                return src.classes.get(sym[1])
        return None

    def _method_in_class(self, mod: ModuleInfo, ci: ClassInfo, name: str,
                         seen: set[str] | None = None) -> FunctionInfo | None:
        """Method lookup through the (project-resolvable) MRO."""
        seen = seen or set()
        if ci.name in seen:
            return None
        seen.add(ci.name)
        if name in ci.methods:
            return ci.methods[name]
        owner_mod = self.modules.get(ci.path)
        for base in ci.bases:
            base_ci = self._class_of(owner_mod or mod, base.split(".")[-1])
            if base_ci is not None:
                hit = self._method_in_class(mod, base_ci, name, seen)
                if hit is not None:
                    return hit
        return None

    def _unique_by_name(self, name: str) -> FunctionInfo | None:
        if name in UNIQUE_NAME_BLOCKLIST or name.startswith("__"):
            return None
        quals = self._by_name.get(name, ())
        if len(quals) == 1:
            return self.functions[quals[0]]
        return None

    def _lock_id(self, mod: ModuleInfo, cls: ClassInfo | None,
                 ctx: ast.expr) -> str | None:
        """Lock id for a with-item context expression, or None."""
        if (isinstance(ctx, ast.Attribute)
                and isinstance(ctx.value, ast.Name)
                and ctx.value.id == "self" and cls is not None):
            attr = cls.lock_aliases.get(ctx.attr, ctx.attr)
            if attr in cls.lock_attrs or ctx.attr in cls.lock_attrs:
                return f"{mod.rel}::{cls.name}.{attr}"
        elif isinstance(ctx, ast.Name) and ctx.id in mod.module_locks:
            return f"{mod.rel}::{ctx.id}"
        return None

    def _resolve_function(self, mod: ModuleInfo, fi: FunctionInfo) -> None:
        cls = mod.classes.get(fi.cls) if fi.cls else None
        # lexical scope chain of enclosing functions' nested defs
        scopes: list[dict[str, FunctionInfo]] = []
        enclosing = fi.nested_in
        while enclosing is not None:
            parent = self.functions.get(enclosing)
            if parent is None:
                break
            scopes.append(parent.nested)
            enclosing = parent.nested_in

        def resolve_call(call: ast.Call) -> tuple[FunctionInfo, str] | None:
            func = call.func
            if isinstance(func, ast.Name):
                name = func.id
                if name in fi.nested:
                    return fi.nested[name], "local"
                for scope in scopes:
                    if name in scope:
                        return scope[name], "local"
                if name in mod.functions:
                    return mod.functions[name], "local"
                sym = mod.from_syms.get(name)
                if sym is not None:
                    src = self.modules.get(sym[0])
                    if src is not None and sym[1] in src.functions:
                        return src.functions[sym[1]], "import"
                ci = self._class_of(mod, name)
                if ci is not None:
                    init = self._method_in_class(mod, ci, "__init__")
                    if init is not None:
                        return init, "class"
                return None
            if not isinstance(func, ast.Attribute):
                return None
            d = _dotted(func)
            if d is None:
                # chained receiver (self.x.y.method()): unique-name tier
                hit = self._unique_by_name(func.attr)
                return (hit, "unique") if hit is not None else None
            parts = d.split(".")
            if parts[0] == "self" and cls is not None and len(parts) == 2:
                m = self._method_in_class(mod, cls, parts[1])
                if m is not None:
                    return m, "self"
                hit = self._unique_by_name(parts[1])
                return (hit, "unique") if hit is not None else None
            if len(parts) == 2 and parts[0] in mod.imports:
                src = self.modules.get(mod.imports[parts[0]])
                if src is not None and parts[1] in src.functions:
                    return src.functions[parts[1]], "module"
                return None
            hit = self._unique_by_name(parts[-1])
            return (hit, "unique") if hit is not None else None

        held: list[str] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child = fi.nested.get(node.name)
                if child is not None and child.node is node:
                    fi.calls.append(CallSite(
                        node.lineno, node.col_offset, child.qualname,
                        "nested", node.name, frozenset(held)))
                return  # nested bodies are their own FunctionInfo pass
            if isinstance(node, ast.With):
                entered: list[str] = []
                for item in node.items:
                    visit(item.context_expr)
                    lock = self._lock_id(mod, cls, item.context_expr)
                    if lock is not None:
                        fi.acquires.append(
                            Acquire(item.context_expr.lineno, lock,
                                    frozenset(held)))
                        held.append(lock)
                        entered.append(lock)
                for stmt in node.body:
                    visit(stmt)
                for _ in entered:
                    held.pop()
                return
            if isinstance(node, ast.Call):
                resolved = resolve_call(node)
                if resolved is not None:
                    callee, kind = resolved
                    if callee.qualname != fi.qualname:
                        fi.calls.append(CallSite(
                            node.lineno, node.col_offset, callee.qualname,
                            kind, _dotted(node.func) or callee.name,
                            frozenset(held)))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in fi.node.body:  # type: ignore[attr-defined]
            visit(stmt)

    # -- queries --------------------------------------------------------
    def lookup(self, needle: str) -> list[FunctionInfo]:
        """Functions whose qualname ends with `needle` (for --graph)."""
        hits = [fi for q, fi in self.functions.items()
                if q == needle or q.endswith(f"::{needle}")
                or q.endswith(f".{needle}") or fi.name == needle]
        return sorted(hits, key=lambda fi: fi.qualname)

    def callers_of(self, qualname: str) -> Iterable[tuple[FunctionInfo, CallSite]]:
        for fi in self.functions.values():
            for c in fi.calls:
                if c.callee == qualname:
                    yield fi, c


def build_index(root: str | Path) -> ProjectIndex:
    return ProjectIndex(Path(root))
