"""Cold-start plane-upload seam rule (SHARD01).

Direct seam calls only; SHARD01's transitive mode (a caller in a third
module reaching a full-plane upload through a helper) lives in
whole_program.py.

The delta-maintained device planes only deliver their flat upload curve if
the full-plane re-put of the node planes stays demoted to the one
sanctioned cold-start seam: `TPUBackend._cold_start_upload` in
`scheduler/tpu/backend.py` (cold start, bucket reshape, builder full
rebuild, or a dirty set so large a wholesale put beats the row scatter).
A second full-plane upload site added in a refactor silently re-couples
per-burst transfer volume to cluster size — `upload_bytes_per_wave` grows
with node count again and the multichip done-criterion ("upload flat at
25k-100k nodes") regresses without any test failing, because the result is
still bit-identical. Nothing can enforce this at runtime (the scatter path
and the full path produce the same mirror), so — like OBS03 for the
accounted seam and FI01 for fault points — the enforcement is
cross-parsing.

SHARD01 flags any `accounted_put` / `account_upload` call whose plane
literal is `"node_planes"` that is not lexically inside a function named
`_cold_start_upload` in `scheduler/tpu/backend.py`. Per-row delta traffic
must use the `"delta_rows"` / `"delta_idx"` planes instead; non-literal
plane names are OBS03's concern and are not re-flagged here.

Findings are project-scoped, so per-line suppressions do not apply —
route the upload through `_cold_start_upload` (or scatter the dirty rows)
instead.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable

from .core import Finding, ProjectChecker

SHARD01 = "SHARD01"

BACKEND_MODULE = "scheduler/tpu/backend.py"
SEAM_FUNC = "_cold_start_upload"
FULL_PLANE = "node_planes"
UPLOAD_METHODS = {"accounted_put", "account_upload"}


def _call_name(node: ast.Call) -> str | None:
    """Last segment of the called name: `a.b.accounted_put(...)`."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _plane_literal(node: ast.Call) -> str | None:
    """The call's plane argument when it is a string literal, else None."""
    arg = None
    if node.args:
        arg = node.args[0]
    else:
        for kw in node.keywords:
            if kw.arg == "plane":
                arg = kw.value
                break
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return None


class _UploadVisitor(ast.NodeVisitor):
    """Collect full-plane upload calls with their enclosing function name."""

    def __init__(self) -> None:
        self.stack: list[str] = []
        self.hits: list[tuple[ast.Call, str | None]] = []

    def _visit_func(self, node: ast.AST) -> None:
        self.stack.append(node.name)  # type: ignore[attr-defined]
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node: ast.Call) -> None:
        if (_call_name(node) in UPLOAD_METHODS
                and _plane_literal(node) == FULL_PLANE):
            self.hits.append((node, self.stack[-1] if self.stack else None))
        self.generic_visit(node)


class ShardSeamChecker(ProjectChecker):
    rules = {
        SHARD01: "full-plane re-put of the node planes outside the one "
                 "sanctioned cold-start seam (backend.py "
                 f"{SEAM_FUNC}) — scatter dirty rows instead so "
                 "upload bytes stay flat as node count grows",
    }

    def check_project(self, root: Path) -> Iterable[Finding]:
        backend_file = root / BACKEND_MODULE
        if not backend_file.is_file():
            return  # partial tree (fixture dirs) — nothing to cross-check
        for path in sorted(root.rglob("*.py")):
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except (OSError, SyntaxError):
                continue  # LINT01 reports unparseable files
            is_backend = path.as_posix().endswith(BACKEND_MODULE)
            visitor = _UploadVisitor()
            visitor.visit(tree)
            for node, func in visitor.hits:
                if is_backend and func == SEAM_FUNC:
                    continue
                where = (f"function {func}()" if func else "module scope")
                yield Finding(
                    path.as_posix(), node.lineno, node.col_offset, SHARD01,
                    f"full-plane upload of {FULL_PLANE!r} in {where} — the "
                    "only sanctioned full re-put is backend.py "
                    f"{SEAM_FUNC}(); churned rows must go through the "
                    "'delta_rows'/'delta_idx' scatter path",
                )
