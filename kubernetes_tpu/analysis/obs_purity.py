"""OBS01: telemetry stays host-side — never inside a jit/vmap/pmap graph.

Per-file, like JIT01-03; the cross-module closure (a telemetry call
reached from a traced root through a helper in another module) is EFF02
in whole_program.py, which reuses this module's TELEMETRY_SEGMENTS set.

The wave flight recorder's contract (scheduler/tpu/flightrecorder.py) is
that recording happens post-`collect`, on the host: a recorder/tracer/
metrics call inside a traced function would either fail at trace time
(locks, deques, perf_counter aren't traceable) or — worse — run once at
trace time and silently freeze a single observation into the compiled
program, while also perturbing the traced op sequence the bit-compat
goldens pin. This rule walks the same traced-function closure JIT01-JIT03
use (jit/vmap/pmap roots + referenced helpers + nested defs) and flags any
call whose dotted name touches a telemetry surface.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, ModuleContext
from .jit_purity import _collect_traced, _dotted, _TracedFn

OBS01 = "OBS01"

# dotted-name segments (lowercased) that identify a telemetry surface:
# recorder/tracer objects, span helpers, metrics facades, profile capture
TELEMETRY_SEGMENTS = {
    "recorder", "flight_recorder", "flightrecorder", "tracer", "metrics",
    "span", "wave_phase", "begin_wave", "end_wave", "take_profile", "pprof",
    # device telemetry seam (scheduler/tpu/devicetelemetry.py): accounting
    # wraps device calls, never runs inside them
    "telemetry", "device_telemetry", "devicetelemetry",
    "accounted_put", "accounted_fetch", "account_upload", "account_fetch",
    "compile_span", "note_resident", "stamp_watermark",
    # stall profiler seam (scheduler/tpu/stallprofiler.py): stamps are
    # host-side wall-clock arithmetic, never inside traced code
    "stall_profiler", "stallprofiler", "mark_gap", "note_stall",
    "note_handoff",
}


class ObservabilityPurityChecker(Checker):
    rules = {
        OBS01: "telemetry/recorder call inside a jit/vmap/pmap call graph "
               "(flight recording is host-side only, post-collect)",
    }

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: list[Finding] = []
        for t in _collect_traced(ctx.tree):
            findings.extend(self._check_traced_body(ctx, t))
        return findings

    def _check_traced_body(
        self, ctx: ModuleContext, t: _TracedFn
    ) -> Iterable[Finding]:
        fname = t.fn.name

        def walk(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                # nested defs get their own _TracedFn pass (jit_purity
                # collects them as separate traced functions)
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                yield child
                yield from walk(child)

        for node in walk(t.fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            segments = {seg.lower() for seg in d.split(".")}
            hit = segments & TELEMETRY_SEGMENTS
            if hit:
                yield Finding(
                    ctx.posix_path, node.lineno, node.col_offset, OBS01,
                    f"telemetry call {d}() inside traced function {fname!r} "
                    "(recording is host-side only — move it after collect)",
                )
