"""Retry / fault-injection discipline (RET01).

The degradation ladder rests on two shared facilities: bounded retry with
full-jitter backoff lives in `utils/backoff.py` (retry_call), and fault
injection lives in `utils/faultinject.py` (FaultRegistry behind named
points). Both are easy to bypass — a hand-rolled `while: try/except:
time.sleep(...)` loop reinvents backoff without the attempt bound, jitter,
or abort hook; an ad-hoc `if rng.random() < p: raise` flake makes a test
nondeterministic and invisible to the registry's seed/replay machinery.

RET01 flags both shapes everywhere except the two modules that own them:

- a `time.sleep` call inside an except handler inside a loop (the
  hand-rolled retry-backoff shape; `sleep` outside an except handler —
  polling loops — is fine and covered by LOCK03 where it matters), and
- a `raise` under an `if` whose condition draws randomness (`random()`,
  `randrange`, `randint`, `uniform`, `getrandbits`, `choice`) — the
  ad-hoc fault flake shape.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .core import Checker, Finding, ModuleContext
from .jit_purity import _dotted

RET01 = "RET01"

# modules that OWN the shared facilities and may use the raw shapes
EXEMPT_SUFFIXES = ("utils/backoff.py", "utils/faultinject.py")

RANDOM_FNS = {"random", "randrange", "randint", "uniform", "getrandbits",
              "choice"}


def _calls_randomness(test: ast.expr) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None and d.split(".")[-1] in RANDOM_FNS:
                return True
    return False


class RetryDisciplineChecker(Checker):
    rules = {
        RET01: "hand-rolled retry backoff or ad-hoc random fault — use "
               "utils.backoff.retry_call / utils.faultinject.FaultRegistry",
    }

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if ctx.posix_path.endswith(EXEMPT_SUFFIXES):
            return
        yield from self._scan(ctx, ctx.tree, in_loop=False, in_except=False)

    def _scan(self, ctx: ModuleContext, node: ast.AST,
              in_loop: bool, in_except: bool) -> Iterable[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # a nested def is its own retry context
                yield from self._scan(ctx, child, False, False)
                continue
            loop = in_loop or isinstance(child, (ast.While, ast.For))
            exc = in_except or isinstance(child, ast.ExceptHandler)
            if (loop and exc and isinstance(child, ast.Call)):
                d = _dotted(child.func)
                if d is not None and d.split(".")[-1] == "sleep":
                    yield Finding(
                        ctx.posix_path, child.lineno, child.col_offset,
                        RET01,
                        "sleep in an except handler inside a loop — "
                        "hand-rolled retry backoff; use "
                        "utils.backoff.retry_call",
                    )
            if isinstance(child, ast.If) and _calls_randomness(child.test):
                for sub in child.body:
                    for raise_node in ast.walk(sub):
                        if isinstance(raise_node, ast.Raise):
                            yield Finding(
                                ctx.posix_path, raise_node.lineno,
                                raise_node.col_offset, RET01,
                                "raise gated on a random draw — ad-hoc "
                                "fault flake; inject through "
                                "utils.faultinject.FaultRegistry",
                            )
            yield from self._scan(ctx, child, loop, exc)
