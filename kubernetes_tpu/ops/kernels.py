"""Dense scheduling kernels: filter masks + scores over [nodes] planes.

The TPU-native re-expression of the scheduler's two hot loops
(pkg/scheduler/schedule_one.go:844 findNodesThatPassFilters and
framework/runtime/framework.go:1320 RunScorePlugins): instead of fanning
filter/score plugin calls across 16 goroutines per node, every plugin becomes
vectorized integer/float32 arithmetic over the whole node axis at once, and
multi-pod batches become a lax.scan where pod i+1 sees pod i's assumed deltas
(subsuming both the gang default algorithm, schedule_one_podgroup.go:275, and
OpportunisticBatching, framework/runtime/batch.go).

Bit-compatibility: all score math is int32 with floor division on non-negative
operands, except BalancedAllocation which is float32 with a fixed op order and
PodTopologySpread's log-weight which is float32 — the host plugins use the
same numpy float32 op order, so host and device agree exactly.

Filter mask order mirrors the registry filter order (plugins/registry.py):
NodeUnschedulable, NodeName, TaintToleration, NodeAffinity, NodePorts,
NodeResourcesFit, PodTopologySpread.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from ..api.resource import CPU, MEM, PODS

MAX_NODE_SCORE = 100

LEAST_ALLOCATED = "LeastAllocated"
MOST_ALLOCATED = "MostAllocated"
REQUESTED_TO_CAPACITY_RATIO = "RequestedToCapacityRatio"

# Filter mask rows (first-failure priority == host plugin order); PTS emits
# per-constraint rows appended after these.
FILTER_NAMES = (
    "NodeUnschedulable", "NodeName", "TaintToleration", "NodeAffinity",
    "NodePorts", "NodeResourcesFit",
)

# image_locality.go:34-35 thresholds, in KiB: image totals are tracked in
# KiB so the whole kernel stays int32 (int64 is emulated on the TPU VPU;
# divergence from the reference's byte math is < 1 score point of rounding,
# and the host plugin uses the same KiB math so host/device parity is exact)
_IMG_MIN_KIB = 23 * 1024
_IMG_MAX_PER_CONTAINER_KIB = 1024 * 1024

# max getrandbits(32) words one scan step may consume for its tie-break
# draw (CPython _randbelow rejection sampling: P(reject) < 1/2 per word, so
# 16 words fail with probability < 2^-16). Exhaustion desynchronizes the
# whole word stream, not just one pod — the kernel therefore reports it via
# "tie_overflow" and the caller must discard the wave's results (the
# backend re-routes to the host path).
MAX_TIE_DRAWS = 16

# no-rng sentinel: all-zero words make every draw resolve to r=0, i.e. the
# first max-score winner (deterministic first-index semantics)
ZERO_TIE_WORDS = np.zeros(MAX_TIE_DRAWS, np.uint32)


# --------------------------------------------------------------------------
# reduction scope: single-device vs explicit mesh-sharded (shard_map)
# --------------------------------------------------------------------------
#
# Every cross-NODE reduction in the kernels goes through one of these. On a
# single device LocalComm is pure passthrough (identical programs to before
# the abstraction). Under jax.shard_map over the nodes axis, AxisComm turns
# each reduction into the MINIMAL collective: segment vectors psum once,
# normalizations become scalar pmax/pmin, and the winner pick exchanges a
# single [shards] tie-count gather per scan step — the SURVEY §7 "per-shard
# top-k, allgather argmax" design, replacing GSPMD's guessed partitioning
# (which made the sharded scan 6.7x SLOWER than single-device in round 4).


@dataclass(frozen=True)
class LocalComm:
    """Single-device reductions (no collectives). Hashable → jit-static."""

    n_shards: int = 1

    def seg(self, x):
        return x  # segment partial sums are already total

    def vmax(self, x):
        return jnp.max(x)

    def vmin(self, x):
        return jnp.min(x)

    def vsum(self, x):
        return jnp.sum(x)

    def gather_scalar(self, x):
        return jnp.asarray(x)[None]

    def index(self):
        return jnp.int32(0)


@dataclass(frozen=True)
class AxisComm:
    """Mesh-axis reductions for shard-local node planes (inside shard_map)."""

    axis: str
    n_shards: int

    def seg(self, x):
        # domain-segment partials: one psum makes the table replicated
        return jax.lax.psum(x, self.axis)

    def vmax(self, x):
        return jax.lax.pmax(jnp.max(x), self.axis)

    def vmin(self, x):
        return jax.lax.pmin(jnp.min(x), self.axis)

    def vsum(self, x):
        return jax.lax.psum(jnp.sum(x), self.axis)

    def gather_scalar(self, x):
        return jax.lax.all_gather(jnp.asarray(x), self.axis)

    def index(self):
        return jax.lax.axis_index(self.axis)


LOCAL_COMM = LocalComm()


@dataclass(frozen=True)
class KernelConfig:
    """Static (compile-time) kernel parameters."""

    strategy: str = LEAST_ALLOCATED
    # (resource column, weight) for the Fit score (NodeResourcesFitArgs)
    fit_resources: tuple[tuple[int, int], ...] = ((CPU, 1), (MEM, 1))
    # RequestedToCapacityRatio (utilization%, score) breakpoints
    rtc_shape: tuple[tuple[int, int], ...] = ((0, 0), (100, MAX_NODE_SCORE))
    # BalancedAllocation resource columns (exactly 2 supported in-kernel)
    balanced_resources: tuple[int, int] = (CPU, MEM)
    # plugin weights (apis/config/v1/default_plugins.go:29-73)
    weights: tuple[tuple[str, int], ...] = (
        ("TaintToleration", 3), ("NodeAffinity", 2), ("PodTopologySpread", 2),
        ("InterPodAffinity", 2),
        ("NodeResourcesFit", 1), ("NodeResourcesBalancedAllocation", 1),
        ("ImageLocality", 1),
    )
    # per-topology-key domain treatment: 0 = singleton fast path (every
    # domain holds exactly one node, e.g. kubernetes.io/hostname — counts
    # are pure elementwise math), else the padded domain-vocab size for the
    # one-hot-matmul segment reduction (e.g. zone: 8 domains → 8)
    topo_domains: tuple[int, ...] = (16, 0)
    # above this domain count, fall back to scatter segment_sum rather than
    # materializing a [dk, Nb] one-hot each step
    matmul_domain_cap: int = 2048
    max_constraints: int = 4
    # number of constraint SLOTS actually traced (hard / soft). Feature
    # arrays stay max_constraints wide; slots >= n_hard/n_soft are known-
    # inactive at compile time, so their segment reductions never enter the
    # program. Callers derive these from the pod batch (backend.kernel_config)
    n_hard: int = 4
    n_soft: int = 4
    # inter-pod affinity statics. ipa_existing_anti/pref: any node (or any
    # pod of the current wave, for the scan carry) contributes to the
    # ipa_anti / ipa_pref planes — when False the existing→incoming matmuls
    # are never traced. n_ipa_aff/anti/pref: max active incoming term slots
    # in the pod batch (like n_hard/n_soft).
    ipa_existing_anti: bool = False
    ipa_existing_pref: bool = False
    n_ipa_aff: int = 0
    n_ipa_anti: int = 0
    n_ipa_pref: int = 0
    max_ipa_terms: int = 4
    max_ipa_pref: int = 8
    ipa_ignore_preferred_existing: bool = False

    def weight(self, name: str) -> int:
        return dict(self.weights).get(name, 1)

    @property
    def ipa_active(self) -> bool:
        return (self.ipa_existing_anti or self.ipa_existing_pref
                or self.n_ipa_aff > 0 or self.n_ipa_anti > 0
                or self.n_ipa_pref > 0)


# --------------------------------------------------------------------------
# filtering
# --------------------------------------------------------------------------


def _pts_domain_stats(cfg, planes, mask, key_i, sel_i, comm=LOCAL_COMM,
                      capture_dseg=0):
    """Per-constraint domain stats: (has_key [Nb], count_at_node [Nb],
    min_count scalar, ndom scalar — number of domains with a participant).

    mask selects which nodes participate (all valid nodes for Filter,
    feasible nodes for Score — matching where the host plugin builds counts:
    PreFilter over all nodes, PreScore over the filtered list).

    Statically unrolls over the topology-key slots so each key uses its
    shape-appropriate reduction: singleton keys (hostname — one node per
    domain) are pure elementwise, small vocabs use a one-hot matmul (MXU,
    no scatter), giant non-singleton vocabs fall back to segment_sum.
    count_at_node is only meaningful where mask & has_key; callers gate on
    that, so the singleton path may return the raw per-node count everywhere.

    With capture_dseg > 0 also returns the selected key's per-domain
    (segment-count, participant-count) tables padded to capture_dseg — the
    signature-dedup scan carries these so clone steps can re-rank without
    redoing the segment reductions. Singleton keys capture zeros (their
    "table" is the per-node count itself).
    """
    dom_all = planes["domain"]
    if len(cfg.topo_domains) != dom_all.shape[1]:
        raise ValueError(
            f"KernelConfig.topo_domains has {len(cfg.topo_domains)} slots but "
            f"planes carry {dom_all.shape[1]} topology-key columns; build the "
            "config via TPUBackend.kernel_config/PlaneBuilder.topo_domains"
        )
    cnt = jnp.take(planes["sel_counts"], sel_i, axis=1)      # [Nb]
    big = jnp.iinfo(jnp.int32).max
    nb = dom_all.shape[0]
    has_key_o = jnp.zeros(nb, bool)
    count_o = jnp.zeros(nb, jnp.int32)
    min_o = jnp.int32(0)
    ndom_o = jnp.int32(0)
    seg_o = jnp.zeros(max(capture_dseg, 1), jnp.int32)
    pc_o = jnp.zeros(max(capture_dseg, 1), jnp.int32)
    for k, dk in enumerate(cfg.topo_domains):
        dom = dom_all[:, k]
        has_key = dom >= 0
        part = mask & has_key
        seg_cap = pc_cap = None
        if dk == 0:
            # singleton: domain ↔ node, so the segment sum is the identity
            count = cnt
            any_part = comm.vmax(part)
            min_c = jnp.where(
                any_part, comm.vmin(jnp.where(part, cnt, big)), 0
            )
            ndom = comm.vsum(part.astype(jnp.int32))
        elif dk <= cfg.matmul_domain_cap:
            dom_c = jnp.clip(dom, 0, dk - 1)
            # one-hot matmul at HIGHEST precision: the MXU's default bf16
            # input cast would round counts > 256; highest-precision f32 is
            # exact for integer values < 2^24
            oh = (jnp.arange(dk, dtype=jnp.int32)[:, None] == dom_c[None, :]
                  ).astype(jnp.float32)
            seg = comm.seg(jnp.matmul(
                oh, jnp.where(part, cnt, 0).astype(jnp.float32),
                precision=jax.lax.Precision.HIGHEST,
            ).astype(jnp.int32))
            pcf = comm.seg(jnp.matmul(
                oh, part.astype(jnp.float32),
                precision=jax.lax.Precision.HIGHEST,
            ))
            present = pcf > 0.5
            count = jnp.take(seg, dom_c)
            min_c = jnp.where(
                present.any(), jnp.min(jnp.where(present, seg, big)), 0
            )
            ndom = present.sum().astype(jnp.int32)
            seg_cap, pc_cap = seg, pcf.astype(jnp.int32)
        else:
            dom_c = jnp.clip(dom, 0, dk - 1)
            seg = comm.seg(jax.ops.segment_sum(
                jnp.where(part, cnt, 0), dom_c, num_segments=dk
            ))
            pc = comm.seg(jax.ops.segment_sum(
                jnp.where(part, 1, 0), dom_c, num_segments=dk
            ))
            present = pc > 0
            count = jnp.take(seg, dom_c)
            min_c = jnp.where(
                present.any(), jnp.min(jnp.where(present, seg, big)), 0
            )
            ndom = present.sum().astype(jnp.int32)
            seg_cap, pc_cap = seg, pc
        sel = key_i == k
        has_key_o = jnp.where(sel, has_key, has_key_o)
        count_o = jnp.where(sel, count, count_o)
        min_o = jnp.where(sel, min_c, min_o)
        ndom_o = jnp.where(sel, ndom, ndom_o)
        if capture_dseg and seg_cap is not None:
            pad = capture_dseg - seg_cap.shape[0]
            if pad > 0:
                seg_cap = jnp.pad(seg_cap, (0, pad))
                pc_cap = jnp.pad(pc_cap, (0, pad))
            seg_o = jnp.where(sel, seg_cap, seg_o)
            pc_o = jnp.where(sel, pc_cap, pc_o)
    if capture_dseg:
        return has_key_o, count_o, min_o, ndom_o, seg_o, pc_o
    return has_key_o, count_o, min_o, ndom_o


def _domain_sum_at_node(cfg: KernelConfig, planes: dict, k: int, col, part,
                        comm=LOCAL_COMM):
    """Domain-aggregate a per-node int32 column over topology key slot k:
    returns (has_key [Nb], at_node [Nb]) where at_node[i] = sum of col over
    participating nodes in i's domain of key k. Singleton keys (topo_domains
    slot 0) skip the reduction entirely — the domain sum IS the node value."""
    dk = cfg.topo_domains[k]
    dom = planes["domain"][:, k]
    has_key = dom >= 0
    p = part & has_key
    masked = jnp.where(p, col, 0)
    if dk == 0:
        return has_key, masked
    dom_c = jnp.clip(dom, 0, dk - 1)
    if dk <= cfg.matmul_domain_cap:
        oh = (jnp.arange(dk, dtype=jnp.int32)[:, None] == dom_c[None, :]
              ).astype(jnp.float32)
        seg = comm.seg(jnp.matmul(
            oh, masked.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST).astype(jnp.int32))
    else:
        seg = comm.seg(jax.ops.segment_sum(masked, dom_c, num_segments=dk))
    return has_key, jnp.take(seg, dom_c)


def _ipa_term_stats(cfg: KernelConfig, planes: dict, cnt_col, key_i, part,
                    comm=LOCAL_COMM):
    """Per-term domain stats for an incoming pod's term with dynamic key
    slot key_i: (has_key [Nb], count_at_node [Nb], anywhere scalar bool).
    Statically unrolled over key slots (same pattern as _pts_domain_stats)."""
    nb = planes["valid"].shape[0]
    has_key_o = jnp.zeros(nb, bool)
    count_o = jnp.zeros(nb, jnp.int32)
    any_o = jnp.bool_(False)
    for k in range(len(cfg.topo_domains)):
        has_key, at = _domain_sum_at_node(cfg, planes, k, cnt_col, part, comm)
        anywhere = comm.vsum(jnp.where(part & has_key, cnt_col, 0)) > 0
        sel = key_i == k
        has_key_o = jnp.where(sel, has_key, has_key_o)
        count_o = jnp.where(sel, at, count_o)
        any_o = jnp.where(sel, anywhere, any_o)
    return has_key_o, count_o, any_o


def _ipa_filters(cfg: KernelConfig, planes: dict, f: dict, comm=LOCAL_COMM):
    """InterPodAffinity's 3 predicate checks (filtering.go:352-412) as dense
    masks: (existing-anti reject, incoming-anti reject, incoming-aff reject).
    Inactive paths are compile-time zero rows."""
    valid = planes["valid"]
    nb = valid.shape[0]
    zero = jnp.zeros(nb, bool)
    fail1, fail2, fail3 = zero, zero, zero

    # 1. existing pods' required anti-affinity vs the incoming pod: per key
    # slot, count matching (pod, term) pairs on each node via one [Nb,Ta]
    # matvec, then domain-aggregate; reject nodes whose domain count > 0
    if cfg.ipa_existing_anti:
        tkey = planes["ipa_term_key"]
        for k in range(len(cfg.topo_domains)):
            w = (f["ipa_match"] & (tkey == k)).astype(jnp.float32)
            col = jnp.matmul(
                planes["ipa_anti"].astype(jnp.float32), w,
                precision=jax.lax.Precision.HIGHEST,
            ).astype(jnp.int32)
            has_key, at = _domain_sum_at_node(cfg, planes, k, col, valid, comm)
            fail1 = fail1 | (has_key & (at > 0))

    # 2. incoming required anti-affinity terms (filtering.go:389)
    for s in range(min(cfg.max_ipa_terms, cfg.n_ipa_anti)):
        t = f["ipa_anti_t"][s]
        active = t >= 0
        cnt_col = jnp.take(planes["ipa_counts"], jnp.clip(t, 0), axis=1)
        key_i = jnp.take(planes["ipa_term_key"], jnp.clip(t, 0))
        has_key, at, _ = _ipa_term_stats(cfg, planes, cnt_col, key_i, valid,
                                         comm)
        fail2 = fail2 | (active & has_key & (at > 0))

    # 3. incoming required affinity terms (filtering.go:404): every term must
    # match in the node's domain, unless it matches nowhere and the pod
    # matches its own term (self-match bootstrap)
    for s in range(min(cfg.max_ipa_terms, cfg.n_ipa_aff)):
        t = f["ipa_aff_t"][s]
        active = t >= 0
        cnt_col = jnp.take(planes["ipa_counts"], jnp.clip(t, 0), axis=1)
        key_i = jnp.take(planes["ipa_term_key"], jnp.clip(t, 0))
        has_key, at, anywhere = _ipa_term_stats(cfg, planes, cnt_col, key_i,
                                                valid, comm)
        ok = has_key & (at > 0)
        bootstrap = ~anywhere & f["ipa_aff_self"][s]
        fail3 = fail3 | (active & ~(ok | bootstrap))
    return fail1, fail2, fail3


def _ipa_score(cfg: KernelConfig, planes: dict, f: dict, feasible,
               comm=LOCAL_COMM):
    """InterPodAffinity score (scoring.go:81-257): weighted preferred-term
    matches accumulated per domain over FEASIBLE nodes (the host PreScore
    runs on the filtered list), min/max-normalized to [0,100]."""
    nb = planes["valid"].shape[0]
    if cfg.n_ipa_pref == 0 and not cfg.ipa_existing_pref:
        return jnp.zeros(nb, jnp.int32)
    raw = jnp.zeros(nb, jnp.int32)

    # incoming pod's preferred terms vs existing pods
    for s in range(min(cfg.max_ipa_pref, cfg.n_ipa_pref)):
        t = f["ipa_pref_t"][s]
        active = t >= 0
        w = f["ipa_pref_w"][s]
        cnt_col = jnp.take(planes["ipa_counts"], jnp.clip(t, 0), axis=1)
        key_i = jnp.take(planes["ipa_term_key"], jnp.clip(t, 0))
        has_key, at, _ = _ipa_term_stats(cfg, planes, cnt_col, key_i,
                                         feasible, comm)
        raw = raw + jnp.where(active & has_key, w * at, 0)

    # existing pods' preferred terms vs the incoming pod (signed weights are
    # pre-folded into the ipa_pref plane)
    if cfg.ipa_existing_pref and not cfg.ipa_ignore_preferred_existing:
        tkey = planes["ipa_term_key"]
        for k in range(len(cfg.topo_domains)):
            w = (f["ipa_match"] & (tkey == k)).astype(jnp.float32)
            col = jnp.matmul(
                planes["ipa_pref"].astype(jnp.float32), w,
                precision=jax.lax.Precision.HIGHEST,
            ).astype(jnp.int32)
            has_key, at = _domain_sum_at_node(cfg, planes, k, col, feasible,
                                              comm)
            raw = raw + jnp.where(has_key, at, 0)

    big = jnp.iinfo(jnp.int32).max
    mx = comm.vmax(jnp.where(feasible, raw, -big))
    mn = comm.vmin(jnp.where(feasible, raw, big))
    spread = mx - mn
    return jnp.where(
        spread == 0,
        jnp.where(mx > 0, MAX_NODE_SCORE, 0),
        MAX_NODE_SCORE * (raw - mn) // jnp.maximum(spread, 1),
    )


def filter_masks(cfg: KernelConfig, planes: dict, f: dict):
    """All filter plugins at once → (fails [F, Nb] bool, feasible [Nb] bool,
    fit_insufficient [R, Nb], too_many_pods [Nb]).

    fails rows follow FILTER_NAMES, then per-constraint PTS missing-key and
    skew rows (2 * max_constraints rows).
    """
    valid = planes["valid"]
    nb = valid.shape[0]
    iota = jnp.arange(nb, dtype=jnp.int32)

    # NodeUnschedulable (node_unschedulable.go:142)
    f_unsched = planes["unsched"] & ~f["tol_unsched"]

    # NodeName (node_name.go:79)
    f_name = (f["name_idx"] != -1) & (iota != f["name_idx"])

    # NodeAffinity single-name fast path (node_affinity.go:159): pinned
    # pods carry the node row index as a feature instead of an allow row
    f_pin = (f["aff_pin"] != -1) & (iota != f["aff_pin"])

    # TaintToleration filter (taint_toleration.go:119)
    tid = planes["taints"]
    tol = jnp.take(f["tol"], jnp.clip(tid, 0), axis=0)
    f_taint = ((tid >= 0) & ~tol).any(axis=1)

    # NodeAffinity required + nodeSelector (node_affinity.go:218) —
    # per-signature table rows shared across identical pods (the dense
    # analogue of SignPod, staging/.../framework/signers.go)
    row = jnp.take(planes["aff_match"], f["aff_sig"], axis=0)    # [G]
    allow = jnp.take(planes["aff_allow"], f["aff_sig"], axis=0)  # [Nb]
    gm = jnp.take(row, planes["group_id"])
    f_aff = ~(gm & allow)

    # NodePorts (node_ports.go:75)
    conflict = (planes["port_words"] & f["ports"][None, :]) != 0
    f_ports = f["has_ports"] & conflict.any(axis=1)

    # NodeResourcesFit (fit.go:673-760)
    free = planes["alloc"] - planes["used"]
    insufficient = (f["req"][None, :] > 0) & (f["req"][None, :] > free)
    # asarray: callers may drive this un-jitted with host numpy planes
    insufficient = jnp.asarray(insufficient).at[:, PODS].set(False)
    too_many = planes["used"][:, PODS] + 1 > planes["alloc"][:, PODS]
    f_fit = insufficient.any(axis=1) | too_many

    # PodTopologySpread hard constraints (filtering.go:314); slots beyond
    # cfg.n_hard are compile-time inactive — no reduction is traced for them
    pts_missing, pts_skew = [], []
    false_row = jnp.zeros(nb, bool)
    for c in range(cfg.max_constraints):
        if c >= cfg.n_hard:
            pts_missing.append(false_row)
            pts_skew.append(false_row)
            continue
        active = f["hard_active"][c]
        has_key, count, min_count, _ = _pts_domain_stats(
            cfg, planes, valid, f["hard_key"][c], f["hard_sel"][c]
        )
        skew = count + f["hard_self"][c] - min_count
        pts_missing.append(active & ~has_key)
        pts_skew.append(active & has_key & (skew > f["hard_skew"][c]))

    # InterPodAffinity (after PTS in registry filter order; 3 rows)
    ipa1, ipa2, ipa3 = _ipa_filters(cfg, planes, f)

    fails = jnp.stack(
        [f_unsched, f_name, f_taint, f_aff | f_pin, f_ports, f_fit]
        + pts_missing + pts_skew + [ipa1, ipa2, ipa3]
    )
    feasible = valid & ~fails.any(axis=0)
    return fails, feasible, insufficient.T, too_many


# --------------------------------------------------------------------------
# scoring
# --------------------------------------------------------------------------


def _strategy_score(cfg: KernelConfig, requested, capacity):
    """Integer strategy formulas (least_allocated.go:30-52 etc.); caller
    guarantees capacity > 0 via where()."""
    cap = jnp.maximum(capacity, 1)
    if cfg.strategy == LEAST_ALLOCATED:
        return (cap - requested) * MAX_NODE_SCORE // cap
    if cfg.strategy == MOST_ALLOCATED:
        return requested * MAX_NODE_SCORE // cap
    # RequestedToCapacityRatio piecewise-linear (requested_to_capacity_ratio.go)
    util = requested * 100 // cap
    shape = cfg.rtc_shape
    out = jnp.full_like(requested, shape[-1][1])
    for (x0, y0), (x1, y1) in reversed(list(zip(shape, shape[1:]))):
        seg = y1 if x1 == x0 else y0 + (y1 - y0) * (util - x0) // (x1 - x0)
        out = jnp.where(util <= x1, seg, out)
    return jnp.where(util <= shape[0][0], shape[0][1], out)


def _requested_for(planes, f, col):
    """Requested-including-pod per node; cpu/mem use NonZero accounting
    (resource_allocation.go:138)."""
    if col == CPU:
        return planes["nonzero_used"][:, 0] + f["nz_req"][0]
    if col == MEM:
        return planes["nonzero_used"][:, 1] + f["nz_req"][1]
    return planes["used"][:, col] + f["req"][col]


def _fit_score(cfg: KernelConfig, planes, f):
    """resource_allocation.go:52 — weighted mean of per-resource strategy
    scores, nodes with zero capacity for a resource exclude its weight."""
    nb = planes["valid"].shape[0]
    total = jnp.zeros(nb, jnp.int32)
    tw = jnp.zeros(nb, jnp.int32)
    for col, w in cfg.fit_resources:
        alloc = planes["alloc"][:, col]
        ok = alloc > 0
        requested = jnp.minimum(_requested_for(planes, f, col), alloc)
        s = _strategy_score(cfg, requested, alloc)
        total = total + jnp.where(ok, s * w, 0)
        tw = tw + jnp.where(ok, w, 0)
    return jnp.where(tw > 0, total // jnp.maximum(tw, 1), 0)


def _balanced_score(cfg: KernelConfig, planes, f):
    """balanced_allocation.go:204-230 — float32, fixed op order matching the
    host plugin's numpy float32 sequence exactly."""
    ca, cb = cfg.balanced_resources
    alloc_a = planes["alloc"][:, ca]
    alloc_b = planes["alloc"][:, cb]
    fa = jnp.minimum(
        _requested_for(planes, f, ca).astype(jnp.float32)
        / jnp.maximum(alloc_a, 1).astype(jnp.float32),
        jnp.float32(1.0),
    )
    fb = jnp.minimum(
        _requested_for(planes, f, cb).astype(jnp.float32)
        / jnp.maximum(alloc_b, 1).astype(jnp.float32),
        jnp.float32(1.0),
    )
    s = fa + fb
    mean = s / jnp.float32(2.0)
    var = ((fa - mean) ** 2 + (fb - mean) ** 2) / jnp.float32(2.0)
    std = jnp.sqrt(var)
    score = ((jnp.float32(1.0) - std) * jnp.float32(MAX_NODE_SCORE)).astype(jnp.int32)
    both = (alloc_a > 0) & (alloc_b > 0)
    return jnp.where(both, score, 0)


def _taint_score(planes, f, feasible, comm=LOCAL_COMM):
    """taint_toleration.go:180-215 — count intolerable PreferNoSchedule
    taints, inverted over the feasible set in normalize."""
    ptid = planes["prefer_taints"]
    tolp = jnp.take(f["tol_prefer"], jnp.clip(ptid, 0), axis=0)
    count = ((ptid >= 0) & ~tolp).sum(axis=1).astype(jnp.int32)
    max_count = comm.vmax(jnp.where(feasible, count, 0))
    return jnp.where(
        max_count > 0,
        MAX_NODE_SCORE - count * MAX_NODE_SCORE // jnp.maximum(max_count, 1),
        MAX_NODE_SCORE,
    )


def _node_affinity_score(planes, f, feasible, comm=LOCAL_COMM):
    """node_affinity.go:272 + normalize to max=100 over the feasible set."""
    row = jnp.take(planes["aff_pref"], f["aff_sig"], axis=0)    # [G]
    raw = jnp.take(row, planes["group_id"])
    mx = comm.vmax(jnp.where(feasible, raw, 0))
    normed = jnp.where(mx > 0, raw * MAX_NODE_SCORE // jnp.maximum(mx, 1), raw)
    has_pref = jnp.take(planes["aff_has_pref"], f["aff_sig"])
    return jnp.where(has_pref, normed, 0)


def _pts_normalize(raw, any_active, feasible, comm=LOCAL_COMM):
    """scoring.go:266-305 inverted min/max normalization over the feasible
    set — shared by the full and carried (clone-replay) PTS scorers so the
    op sequence is one definition, not two that could drift."""
    big = jnp.iinfo(jnp.int32).max
    mx = comm.vmax(jnp.where(feasible, raw, -big))
    mn = comm.vmin(jnp.where(feasible, raw, big))
    spread = mx - mn
    normed = jnp.where(
        spread == 0,
        MAX_NODE_SCORE,
        (mx - raw) * MAX_NODE_SCORE // jnp.maximum(spread, 1),
    )
    return jnp.where(any_active, normed, 0)


def _pts_score_core(cfg: KernelConfig, planes, f, feasible, comm=LOCAL_COMM,
                    capture_shape=None):
    """podtopologyspread scoring.go:118-305 — per-domain counts weighted by
    log(domains+2) float32, inverted min/max over the feasible set.

    capture_shape=(C, Dseg): additionally return the per-constraint domain
    segment/participant tables (zeros for singleton-key constraints) for the
    signature-dedup scan carry."""
    nb = planes["valid"].shape[0]
    segs = pcs = None
    if capture_shape is not None:
        segs = jnp.zeros(capture_shape, jnp.int32)
        pcs = jnp.zeros(capture_shape, jnp.int32)
    cost = jnp.zeros(nb, jnp.float32)
    if cfg.n_soft == 0:
        return jnp.zeros(nb, jnp.int32), segs, pcs
    any_active = f["soft_active"].any()
    for c in range(min(cfg.max_constraints, cfg.n_soft)):
        active = f["soft_active"][c]
        if capture_shape is None:
            has_key, count, _, nd = _pts_domain_stats(
                cfg, planes, feasible, f["soft_key"][c], f["soft_sel"][c],
                comm
            )
        else:
            has_key, count, _, nd, seg_c, pc_c = _pts_domain_stats(
                cfg, planes, feasible, f["soft_key"][c], f["soft_sel"][c],
                comm, capture_dseg=capture_shape[1]
            )
            segs = segs.at[c].set(seg_c)
            pcs = pcs.at[c].set(pc_c)
        w = jnp.log((nd + 2).astype(jnp.float32))
        cost = cost + jnp.where(
            active & has_key, count.astype(jnp.float32) * w, jnp.float32(0)
        )
    raw = cost.astype(jnp.int32)
    return _pts_normalize(raw, any_active, feasible, comm), segs, pcs


def _pts_score(cfg: KernelConfig, planes, f, feasible, comm=LOCAL_COMM):
    return _pts_score_core(cfg, planes, f, feasible, comm)[0]


def _pts_score_carried(cfg: KernelConfig, planes, f, feasible, sel_counts,
                       segs, pcs, comm=LOCAL_COMM):
    """PTS score for a clone step from the carried per-domain tables: the
    segment reductions of _pts_score_core become gathers into segs/pcs
    (patched after each placement), and singleton keys read the carried
    sel_counts elementwise. Against the same feasible set this is
    bit-identical to the full scorer — counts are the same int32 values,
    the log weight sees the same scalar, and the cost/normalize op order is
    shared (_pts_normalize)."""
    nb = planes["valid"].shape[0]
    if cfg.n_soft == 0:
        return jnp.zeros(nb, jnp.int32)
    dseg = segs.shape[1]
    cost = jnp.zeros(nb, jnp.float32)
    any_active = f["soft_active"].any()
    for c in range(min(cfg.max_constraints, cfg.n_soft)):
        active = f["soft_active"][c]
        key_i = f["soft_key"][c]
        cnt = jnp.take(sel_counts, f["soft_sel"][c], axis=1)
        has_key_o = jnp.zeros(nb, bool)
        count_o = jnp.zeros(nb, jnp.int32)
        nd_o = jnp.int32(0)
        for k, dk in enumerate(cfg.topo_domains):
            dom = planes["domain"][:, k]
            has_key = dom >= 0
            if dk == 0:
                count = cnt
                nd = comm.vsum((feasible & has_key).astype(jnp.int32))
            else:
                count = jnp.take(segs[c], jnp.clip(dom, 0, dseg - 1))
                nd = (pcs[c] > 0).sum().astype(jnp.int32)
            sel = key_i == k
            has_key_o = jnp.where(sel, has_key, has_key_o)
            count_o = jnp.where(sel, count, count_o)
            nd_o = jnp.where(sel, nd, nd_o)
        w = jnp.log((nd_o + 2).astype(jnp.float32))
        cost = cost + jnp.where(
            active & has_key_o, count_o.astype(jnp.float32) * w,
            jnp.float32(0)
        )
    raw = cost.astype(jnp.int32)
    return _pts_normalize(raw, any_active, feasible, comm)


def _image_score(planes, f):
    """image_locality.go:93-105 — int64 byte totals against
    [23MB, 1GB × containers]."""
    idx = jnp.clip(f["img_idx"], 0)
    present = f["img_idx"] >= 0
    sizes = jnp.take(planes["image_kib"], idx, axis=1)       # [Nb, 8]
    total = jnp.where(present[None, :], sizes, 0).sum(axis=1)
    max_thr = jnp.int32(_IMG_MAX_PER_CONTAINER_KIB) * f["num_containers"].astype(jnp.int32)
    span = jnp.maximum(max_thr - _IMG_MIN_KIB, 1)
    mid = MAX_NODE_SCORE * (total - _IMG_MIN_KIB) // span
    score = jnp.where(total < _IMG_MIN_KIB, 0, jnp.where(total > max_thr, MAX_NODE_SCORE, mid))
    return score.astype(jnp.int32)


def scores(cfg: KernelConfig, planes: dict, f: dict, feasible):
    """Weighted total per node (framework.go:1320 3-pass structure collapsed:
    raw score → normalize-over-feasible → weight+sum, all in one trace)."""
    per = {
        "NodeResourcesFit": _fit_score(cfg, planes, f),
        "NodeResourcesBalancedAllocation": _balanced_score(cfg, planes, f),
        "TaintToleration": _taint_score(planes, f, feasible),
        "NodeAffinity": _node_affinity_score(planes, f, feasible),
        "PodTopologySpread": _pts_score(cfg, planes, f, feasible),
        "InterPodAffinity": _ipa_score(cfg, planes, f, feasible),
        "ImageLocality": _image_score(planes, f),
    }
    total = jnp.zeros_like(per["NodeResourcesFit"])
    for name, s in per.items():
        total = total + s * cfg.weight(name)
    return total, per


# --------------------------------------------------------------------------
# single-pod and batched entry points
# --------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnums=0)
def _fit_and_score_jit(cfg: KernelConfig, planes: dict, f: dict):
    fails, feasible, insufficient, too_many = filter_masks(cfg, planes, f)
    total, per = scores(cfg, planes, f, feasible)
    return {
        "fails": fails,
        "feasible": feasible,
        "insufficient": insufficient,
        "too_many_pods": too_many,
        "total": jnp.where(feasible, total, -1),
        "per_plugin": per,
    }


def fit_and_score(cfg: KernelConfig, planes: dict, f: dict):
    """One pod against all nodes: the fused findNodesThatFitPod +
    prioritizeNodes kernel (schedule_one.go:626,941)."""
    return _fit_and_score_jit(cfg, planes, f)


def _static_pod_parts(cfg: KernelConfig, planes: dict, f: dict,
                      comm=LOCAL_COMM) -> dict:
    """Everything in filter_masks/scores that does NOT depend on the scan
    carry (used/nonzero_used/sel_counts): the static filter masks
    (unschedulable, name, taints, affinity, ports) and the static raw score
    inputs (PreferNoSchedule taint counts, affinity preference raw, image).

    Hoisting these out of the per-pod scan step — one vmapped [P, Nb] pass —
    is the batched path's main throughput lever: the step keeps only the
    carry-dependent math (fit, balanced, spread)."""
    valid = planes["valid"]
    nb = valid.shape[0]
    # GLOBAL row ids: under shard_map each shard sees rows
    # [index*nb, (index+1)*nb) of the full node bucket, and name/pin
    # features carry global indices
    iota = comm.index() * nb + jnp.arange(nb, dtype=jnp.int32)
    f_unsched = planes["unsched"] & ~f["tol_unsched"]
    f_name = (f["name_idx"] != -1) & (iota != f["name_idx"])
    tid = planes["taints"]
    tol = jnp.take(f["tol"], jnp.clip(tid, 0), axis=0)
    f_taint = ((tid >= 0) & ~tol).any(axis=1)
    row = jnp.take(planes["aff_match"], f["aff_sig"], axis=0)
    allow = jnp.take(planes["aff_allow"], f["aff_sig"], axis=0)
    f_aff = ~(jnp.take(row, planes["group_id"]) & allow)
    f_pin = (f["aff_pin"] != -1) & (iota != f["aff_pin"])
    conflict = (planes["port_words"] & f["ports"][None, :]) != 0
    f_ports = f["has_ports"] & conflict.any(axis=1)
    static_ok = valid & ~(f_unsched | f_name | f_pin | f_taint | f_aff
                          | f_ports)

    ptid = planes["prefer_taints"]
    tolp = jnp.take(f["tol_prefer"], jnp.clip(ptid, 0), axis=0)
    taint_cnt = ((ptid >= 0) & ~tolp).sum(axis=1).astype(jnp.int32)
    aff_raw = jnp.take(
        jnp.take(planes["aff_pref"], f["aff_sig"], axis=0), planes["group_id"]
    )
    aff_has_pref = jnp.take(planes["aff_has_pref"], f["aff_sig"])
    return {
        "static_ok": static_ok,
        "taint_cnt": taint_cnt,
        "aff_raw": aff_raw,
        "aff_has_pref": aff_has_pref,
        "img": _image_score(planes, f),
    }


def _dom_counts_init(cfg: KernelConfig, planes: dict, comm=LOCAL_COMM):
    """Carried per-domain selector-count tensors for the scan's hard-spread
    path: dom_counts [K, Dmax, S] (sum of sel_counts over each domain's
    valid nodes) and the static presence mask present [K, Dmax] (domain has
    >= 1 valid node carrying the key). One matmul per key slot, ONCE per
    wave — the per-step matmuls this replaces were the scan's last big cost."""
    valid = planes["valid"]
    sel = planes["sel_counts"]
    dmax = max((dk for dk in cfg.topo_domains if dk > 0), default=0)
    if dmax == 0 or cfg.n_hard == 0:
        return None, None
    counts, present = [], []
    for k, dk in enumerate(cfg.topo_domains):
        if dk == 0:
            counts.append(jnp.zeros((dmax, sel.shape[1]), jnp.int32))
            present.append(jnp.zeros(dmax, bool))
            continue
        dom = planes["domain"][:, k]
        part = valid & (dom >= 0)
        dom_c = jnp.clip(dom, 0, dk - 1)
        oh = (jnp.arange(dk, dtype=jnp.int32)[:, None] == dom_c[None, :]
              ).astype(jnp.float32) * part.astype(jnp.float32)[None, :]
        seg = comm.seg(jnp.matmul(
            oh, sel.astype(jnp.float32),
            precision=jax.lax.Precision.HIGHEST).astype(jnp.int32))
        pres = comm.seg(oh.sum(axis=1)) > 0.5
        pad = dmax - dk
        if pad:
            seg = jnp.pad(seg, ((0, pad), (0, 0)))
            pres = jnp.pad(pres, (0, pad))
        counts.append(seg)
        present.append(pres)
    return jnp.stack(counts), jnp.stack(present)


def _pts_hard_carried(cfg: KernelConfig, planes, sel_counts, dom_counts,
                      present, key_i, sel_i, comm=LOCAL_COMM):
    """Hard-constraint domain stats from the carried dom_counts — the
    gather-only replacement for _pts_domain_stats inside the scan."""
    dom_all = planes["domain"]
    big = jnp.iinfo(jnp.int32).max
    nb = dom_all.shape[0]
    cnt = jnp.take(sel_counts, sel_i, axis=1)
    has_key_o = jnp.zeros(nb, bool)
    count_o = jnp.zeros(nb, jnp.int32)
    min_o = jnp.int32(0)
    for k, dk in enumerate(cfg.topo_domains):
        dom = dom_all[:, k]
        has_key = dom >= 0
        if dk == 0:
            # singleton: per-node count IS the domain count
            part = planes["valid"] & has_key
            count = cnt
            min_c = jnp.where(comm.vmax(part),
                              comm.vmin(jnp.where(part, cnt, big)), 0)
        else:
            seg = jnp.take(dom_counts[k], sel_i, axis=1)  # [Dmax]
            count = jnp.take(seg, jnp.clip(dom, 0, dom_counts.shape[1] - 1))
            pres = present[k]
            min_c = jnp.where(pres.any(), jnp.min(jnp.where(pres, seg, big)), 0)
        sel_k = key_i == k
        has_key_o = jnp.where(sel_k, has_key, has_key_o)
        count_o = jnp.where(sel_k, count, count_o)
        min_o = jnp.where(sel_k, min_c, min_o)
    return has_key_o, count_o, min_o


def _finish_total(cfg: KernelConfig, ew, pts, f, sp, feasible,
                  comm=LOCAL_COMM):
    """Assemble the weighted total from the fit+balanced partial (ew), the
    PTS score and the static per-pod raws (taint counts, affinity prefs,
    image) normalized over the live feasible set. int32 addition is exact,
    so this grouping is value-identical to the pre-refactor flat sum."""
    max_tc = comm.vmax(jnp.where(feasible, sp["taint_cnt"], 0))
    taint = jnp.where(
        max_tc > 0,
        MAX_NODE_SCORE - sp["taint_cnt"] * MAX_NODE_SCORE // jnp.maximum(max_tc, 1),
        MAX_NODE_SCORE,
    )
    mx_aff = comm.vmax(jnp.where(feasible, sp["aff_raw"], 0))
    aff_normed = jnp.where(
        mx_aff > 0,
        sp["aff_raw"] * MAX_NODE_SCORE // jnp.maximum(mx_aff, 1),
        sp["aff_raw"],
    )
    return (
        ew
        + pts * cfg.weight("PodTopologySpread")
        + sp["img"] * cfg.weight("ImageLocality")
        + taint * cfg.weight("TaintToleration")
        + jnp.where(sp["aff_has_pref"], aff_normed, 0) * cfg.weight("NodeAffinity")
    )


def _fit_filter_row(cfg: KernelConfig, alloc_row, used_row, f):
    """NodeResourcesFit filter for ONE node row — the scalar analogue of the
    [Nb] block in _assign_step, used to patch the dedup carry after a
    placement (elementwise int compares: bit-identical to the full pass)."""
    free = alloc_row - used_row
    insuff = (f["req"] > 0) & (f["req"] > free)
    insuff = insuff.at[PODS].set(False)
    too_many = used_row[PODS] + 1 > alloc_row[PODS]
    return insuff.any() | too_many


def _assign_step(cfg: KernelConfig, planes: dict, present, tie_words, comm,
                 carry, inp, static_rows=None, uniq_f=None, fast=False):
    """One greedy step: carry-dependent filter+score only (static parts come
    precomputed via the scan xs), pick the best node with the HOST tie-break
    (seeded-rng draw over max-score winners in snapshot node order, fed by
    the precomputed tie_words stream), apply the pod's deltas. Score math is
    identical to filter_masks+scores — just partitioned by carry-dependence.

    Signature dedup (static_rows is not None): the step reads its static
    per-pod parts by gathering row sig_id from the per-SIGNATURE table
    instead of receiving them via xs. With fast=True the step is two-tier
    over a per-signature score-row TABLE carried through the scan (and,
    cross-wave, seeded from the previous wave's table): a slot whose
    signature already has a resident row replays it (ew + feasibility + PTS
    domain tables) and only pays the re-rank + tie-draw; a fresh signature
    takes the full pass and installs its row. After every placement EVERY
    resident row is patched at the winner column — a placement changes
    fit/balanced/feasibility at exactly that node for every signature —
    which is what makes replays (adjacent, a-b-a, and cross-wave alike)
    bit-identical to a full recompute. With hard spread constraints the
    carry-dependent fail mask is recomputed each step and a replay is only
    taken when the resident row's feasibility agrees with it (a placement
    can flip hard-spread skew at rows the winner patch doesn't model;
    the equality gate routes exactly those steps back to the full tier —
    a lost hit, never a wrong replay).

    Under shard_map (comm=AxisComm) the per-step cross-shard traffic is
    exactly: the scalar normalizations (pmax/pmin), one [shards] tie-count
    gather, and the scalar psums publishing the winner and its domain —
    the per-shard top-k → global argmax design of SURVEY §7. Table row
    columns are shard-local and owner-patched; the replicated segs/pcs
    domain tables learn the owner's per-slot deltas through one
    shape-preserving psum (comm.seg) per soft constraint."""
    (used, nonzero_used, sel_counts, dom_counts, ipa, cursor, overflow,
     tab, sig_scores) = carry
    if static_rows is None:
        f, sp = inp
        sid = None
    else:
        f, sid = inp
        sp = jax.tree_util.tree_map(lambda a: a[sid], static_rows)
    p = dict(planes)
    p["used"], p["nonzero_used"], p["sel_counts"] = used, nonzero_used, sel_counts
    if ipa is not None:
        p["ipa_counts"], p["ipa_anti"], p["ipa_pref"] = ipa

    if fast:
        t_ew, t_ffit, t_feas, t_segs, t_pcs, t_valid = tab
        capture_shape = t_segs.shape[1:]
        # hard-spread fail mask: carry-dependent, so recomputed EVERY step
        # (replays included) and shared by both tiers
        pts_fail = jnp.zeros(p["valid"].shape[0], bool)
        for c in range(min(cfg.max_constraints, cfg.n_hard)):
            active = f["hard_active"][c]
            if dom_counts is not None:
                has_key, count, min_count = _pts_hard_carried(
                    cfg, p, sel_counts, dom_counts, present,
                    f["hard_key"][c], f["hard_sel"][c], comm
                )
            else:
                has_key, count, min_count, _ = _pts_domain_stats(
                    cfg, p, p["valid"], f["hard_key"][c], f["hard_sel"][c],
                    comm
                )
            skew = count + f["hard_self"][c] - min_count
            pts_fail = pts_fail | (active & ~has_key) | (
                active & has_key & (skew > f["hard_skew"][c])
            )
        # IPA masks are carry-dependent through the ipa_* planes (a
        # placement in a topology domain can flip them at EVERY node of the
        # domain), so — exactly like the hard-spread mask — they are
        # recomputed every step and shared by both tiers; the resident row
        # never caches IPA state, only the equality gate below decides
        # whether the cached ew/fit/segs columns still apply.
        if cfg.ipa_active:
            ipa1, ipa2, ipa3 = _ipa_filters(cfg, p, f, comm)
            ipa_fail = ipa1 | ipa2 | ipa3
        else:
            ipa_fail = jnp.zeros(p["valid"].shape[0], bool)
        row_in = (t_ew[sid], t_ffit[sid], t_feas[sid], t_segs[sid],
                  t_pcs[sid])
        replay = t_valid[sid]
        if cfg.n_hard > 0 or cfg.ipa_active:
            # the resident t_ffit column is maintained exactly (placements
            # only change fit at their winner row, and every winner row is
            # patched), so static_ok & ~t_ffit & ~pts_fail & ~ipa_fail IS
            # the full-tier feasibility; replay only when the resident row
            # agrees with it. comm-reduced so every shard takes the same
            # cond branch (the branches contain collectives)
            feas_live = sp["static_ok"] & ~row_in[1] & ~pts_fail & ~ipa_fail
            mismatch = comm.vsum(
                (feas_live != row_in[2]).sum().astype(jnp.int32)) > 0
            replay = replay & ~mismatch

        def _full_tier(row):
            del row
            # dynamic filter: NodeResourcesFit + the shared hard-spread mask
            free = p["alloc"] - used
            insufficient = (f["req"][None, :] > 0) & (f["req"][None, :] > free)
            insufficient = insufficient.at[:, PODS].set(False)
            too_many = used[:, PODS] + 1 > p["alloc"][:, PODS]
            f_fit = insufficient.any(axis=1) | too_many
            feasible = sp["static_ok"] & ~f_fit & ~pts_fail & ~ipa_fail
            ew = (
                _fit_score(cfg, p, f) * cfg.weight("NodeResourcesFit")
                + _balanced_score(cfg, p, f)
                * cfg.weight("NodeResourcesBalancedAllocation")
            )
            pts, segs, pcs = _pts_score_core(
                cfg, p, f, feasible, comm, capture_shape=capture_shape
            )
            total = _finish_total(cfg, ew, pts, f, sp, feasible, comm)
            if cfg.ipa_active:
                total = total + _ipa_score(cfg, p, f, feasible, comm) \
                    * cfg.weight("InterPodAffinity")
            return total, (ew, f_fit, feasible, segs, pcs)

        def _cheap_tier(row):
            ew, f_fit, feasible, segs, pcs = row
            pts = _pts_score_carried(
                cfg, p, f, feasible, sel_counts, segs, pcs, comm
            )
            total = _finish_total(cfg, ew, pts, f, sp, feasible, comm)
            if cfg.ipa_active:
                # live recompute, same int32 op order as the non-dedup scan
                # (the ipa planes ride the carry, never the resident row)
                total = total + _ipa_score(cfg, p, f, feasible, comm) \
                    * cfg.weight("InterPodAffinity")
            return total, row

        total, row = jax.lax.cond(replay, _cheap_tier, _full_tier, row_in)
        feasible = row[2]
        # install the (possibly refreshed) row: a cheap-tier write is a
        # value-identity no-op, a full-tier write makes the slot resident
        t_ew = t_ew.at[sid].set(row[0])
        t_ffit = t_ffit.at[sid].set(row[1])
        t_feas = t_feas.at[sid].set(row[2])
        t_segs = t_segs.at[sid].set(row[3])
        t_pcs = t_pcs.at[sid].set(row[4])
        t_valid = t_valid.at[sid].set(True)
    else:
        # dynamic filters: NodeResourcesFit + PodTopologySpread hard
        # constraints
        free = p["alloc"] - used
        insufficient = (f["req"][None, :] > 0) & (f["req"][None, :] > free)
        insufficient = insufficient.at[:, PODS].set(False)
        too_many = used[:, PODS] + 1 > p["alloc"][:, PODS]
        f_fit = insufficient.any(axis=1) | too_many
        pts_fail = jnp.zeros_like(f_fit)
        for c in range(min(cfg.max_constraints, cfg.n_hard)):
            active = f["hard_active"][c]
            if dom_counts is not None:
                has_key, count, min_count = _pts_hard_carried(
                    cfg, p, sel_counts, dom_counts, present,
                    f["hard_key"][c], f["hard_sel"][c], comm
                )
            else:
                has_key, count, min_count, _ = _pts_domain_stats(
                    cfg, p, p["valid"], f["hard_key"][c], f["hard_sel"][c],
                    comm
                )
            skew = count + f["hard_self"][c] - min_count
            pts_fail = pts_fail | (active & ~has_key) | (
                active & has_key & (skew > f["hard_skew"][c])
            )
        if cfg.ipa_active:
            ipa1, ipa2, ipa3 = _ipa_filters(cfg, p, f, comm)
            ipa_fail = ipa1 | ipa2 | ipa3
        else:
            ipa_fail = jnp.zeros_like(f_fit)
        feasible = sp["static_ok"] & ~f_fit & ~pts_fail & ~ipa_fail

        # dynamic scores + static raws normalized over the live feasible set
        ew = (
            _fit_score(cfg, p, f) * cfg.weight("NodeResourcesFit")
            + _balanced_score(cfg, p, f)
            * cfg.weight("NodeResourcesBalancedAllocation")
        )
        total = _finish_total(
            cfg, ew, _pts_score(cfg, p, f, feasible, comm), f, sp, feasible,
            comm
        ) + _ipa_score(cfg, p, f, feasible, comm) * cfg.weight("InterPodAffinity")

    # winner selection = selectHost (schedule_one.go:1080-1134): uniform
    # seeded draw among max-score feasible nodes in snapshot node order.
    # Reproduces CPython Random.randrange(nw) exactly: k = nw.bit_length(),
    # take the top k bits of successive 32-bit MT words, reject r >= nw.
    # Sharded: each shard's ties are counted locally; ONE [shards] gather
    # gives every shard the global count + its own prefix (global node order
    # is shard-major, so prefix sums preserve snapshot order), and the draw
    # runs replicated — every shard computes the same r and agrees on the
    # owning shard without exchanging score vectors.
    key = jnp.where(feasible, total, -1)
    best = comm.vmax(key)
    # inactive slots (wave padding to ONE static shape — a fresh XLA compile
    # per odd wave size costs far more than scanning dead steps) place
    # nothing and consume no tie-break words
    found = (best >= 0) & f["active"]
    mask = feasible & (total == best) & found
    local_ties = mask.sum().astype(jnp.int32)
    tie_counts = comm.gather_scalar(local_ties)          # [shards]
    nw = tie_counts.sum()
    k = jnp.int32(32) - jax.lax.clz(jnp.maximum(nw, 1))
    idx = cursor + jnp.arange(MAX_TIE_DRAWS, dtype=jnp.int32)
    w = jnp.take(tie_words, jnp.clip(idx, 0, tie_words.shape[0] - 1))
    r = (w >> (jnp.uint32(32) - k.astype(jnp.uint32))).astype(jnp.int32)
    accept = r < nw
    first = jnp.argmax(accept).astype(jnp.int32)
    got_draw = accept.any()
    r_sel = jnp.where(got_draw, r[first], 0)
    use_draw = found & (nw > 1)
    r_final = jnp.where(use_draw, r_sel, 0)
    cursor = cursor + jnp.where(use_draw,
                                jnp.where(got_draw, first + 1, MAX_TIE_DRAWS), 0)
    overflow = overflow | (use_draw & ~got_draw)
    # my shard owns the winner iff the global tie index lands in my range
    my_prefix = jnp.cumsum(tie_counts)[comm.index()] - local_ties
    r_local = r_final - my_prefix
    owner = found & (r_local >= 0) & (r_local < local_ties)
    cs = jnp.cumsum(mask.astype(jnp.int32))
    win = jnp.argmax(mask & (cs == r_local + 1)).astype(jnp.int32)
    # single-row scatter-adds, not [Nb, R] one-hot multiplies — the update
    # touches one node's row, so the step shouldn't write whole planes;
    # non-owner shards add zero
    gate = owner.astype(jnp.int32)
    sel_prev = sel_counts
    used = used.at[win].add(gate * f["req"])
    nonzero_used = nonzero_used.at[win].add(gate * f["nz_req"])
    sel_counts = sel_counts.at[win].add(gate * f["sig_match"])
    if dom_counts is not None:
        # the placed pod joins its domains (dom_counts is REPLICATED under
        # sharding: every shard applies the same update, learning the
        # winner's domain ids through one scalar psum per key slot)
        for k, dk in enumerate(cfg.topo_domains):
            if dk == 0:
                continue
            idx = planes["domain"][win, k]
            g_idx = comm.vsum(gate * (idx + 1))  # 0 = no owner or no key
            delta = jnp.where(found & (g_idx > 0), f["sig_match"], 0)
            dom_counts = dom_counts.at[k, jnp.clip(g_idx - 1, 0)].add(delta)
    if ipa is not None:
        # the placed pod joins each matching term's count column and
        # contributes its own carried anti/preferred terms
        ipa_counts, ipa_anti, ipa_pref = ipa
        ipa = (
            ipa_counts.at[win].add(gate * f["ipa_match"].astype(jnp.int32)),
            ipa_anti.at[win].add(gate * f["ipa_anti_add"]),
            ipa_pref.at[win].add(gate * f["ipa_pref_add"]),
        )
    if fast:
        # patch EVERY resident row at the winner column: a placement changes
        # f_fit/feasible/fit/balanced at EXACTLY that node (only its
        # used/nonzero_used moved) for EVERY signature, plus the winner's
        # domain segment in each soft constraint's carried tables. Patching
        # all rows (not just the current slot's) is what lets a row survive
        # a-b-a runs and wave boundaries and still replay bit-identically.
        # All patches gate on `placed` so a no-placement step is a no-op.
        placed = owner
        rp = {
            "alloc": planes["alloc"][win][None],
            "used": used[win][None],
            "nonzero_used": nonzero_used[win][None],
            "valid": planes["valid"][win][None],
        }

        def _row_parts(fc):
            ew_w = (
                _fit_score(cfg, rp, fc)[0] * cfg.weight("NodeResourcesFit")
                + _balanced_score(cfg, rp, fc)[0]
                * cfg.weight("NodeResourcesBalancedAllocation")
            )
            return ew_w, _fit_filter_row(cfg, planes["alloc"][win],
                                         used[win], fc)

        ew_w, ffit_w = jax.vmap(_row_parts)(uniq_f)            # [C] each
        so_win = static_rows["static_ok"][:, win]              # [C]
        feas_w = so_win & ~ffit_w
        feas_old = t_feas[:, win]                              # [C]
        # row columns are shard-local: only the winner's owner patches them
        gate_c = t_valid & placed
        t_ew = t_ew.at[:, win].set(jnp.where(gate_c, ew_w, t_ew[:, win]))
        t_ffit = t_ffit.at[:, win].set(
            jnp.where(gate_c, ffit_w, t_ffit[:, win]))
        t_feas = t_feas.at[:, win].set(jnp.where(gate_c, feas_w, feas_old))
        dseg = t_segs.shape[2]
        for c in range(min(cfg.max_constraints, cfg.n_soft)):
            key_c = uniq_f["soft_key"][:, c]                   # [C]
            sel_c = uniq_f["soft_sel"][:, c]                   # [C]
            cnt_old_w = sel_prev[win][sel_c]                   # [C]
            cnt_new_w = sel_counts[win][sel_c]                 # [C]
            before = jnp.where(feas_old, cnt_old_w, 0)
            after = jnp.where(feas_w, cnt_new_w, 0)
            # segs/pcs are REPLICATED under sharding: non-owners contribute
            # zeros and learn the owner's per-slot deltas through one
            # shape-preserving psum per constraint
            seg_d = comm.seg(jnp.where(placed, after - before, 0))
            pc_d = comm.seg(jnp.where(
                placed,
                feas_w.astype(jnp.int32) - feas_old.astype(jnp.int32),
                0,
            ))
            for k, dk in enumerate(cfg.topo_domains):
                if dk == 0:
                    continue  # singleton keys replay from sel_counts directly
                dom_w = planes["domain"][win, k]
                g_dom = comm.vsum(gate * (dom_w + 1))  # 0 = none/no owner
                in_k = t_valid & (key_c == k) & (g_dom > 0)
                d_idx = jnp.clip(g_dom - 1, 0, dseg - 1)
                t_segs = t_segs.at[:, c, d_idx].add(
                    jnp.where(in_k, seg_d, 0))
                t_pcs = t_pcs.at[:, c, d_idx].add(
                    jnp.where(in_k, pc_d, 0))
        tab = (t_ew, t_ffit, t_feas, t_segs, t_pcs, t_valid)
        # per-signature score row export (host BatchCache warm-up): the slot
        # that pays the full pass stores its feasibility-gated totals;
        # replays (within-wave AND cross-wave) never store — the host
        # exporter drops all-(-1) rows, so a cross-wave hit simply keeps the
        # export it already made on the wave that scored it
        sig_scores = sig_scores.at[sid].set(jnp.where(
            replay, sig_scores[sid], jnp.where(feasible, total, -1)
        ))
    # publish the winner's GLOBAL row id (scalar psum; -1 when unplaced)
    nb = mask.shape[0]
    winner = comm.vsum(gate * (comm.index() * nb + win + 1)) - 1
    return (used, nonzero_used, sel_counts, dom_counts, ipa, cursor,
            overflow, tab, sig_scores), winner


def dedup_fast_capable(cfg: KernelConfig, comm=LOCAL_COMM) -> bool:
    """Whether the two-tier signature-replay scan is valid for this config:
    the winner-column patch covers the dynamic state of NodeResourcesFit +
    spread scoring; carry-dependent masks the patch can't track — hard
    spread AND inter-pod affinity — are recomputed live each step with their
    divergence caught by the per-step feasibility equality gate (a
    mismatching row re-runs the full tier: a lost hit, never a wrong
    replay). Under sharding the row columns are shard-local, the domain
    tables stay replicated via psum'd deltas, and the replay predicate is
    comm-reduced so every shard takes the same branch. No exclusions
    remain; the signature fast tier applies to every kernelizable config."""
    del cfg, comm  # kept for API compat; the replay tier covers all configs
    return True


def _batched_assign_core(cfg: KernelConfig, planes: dict, packed_f,
                         layout, tie_words, cursor_init, frame_shift,
                         comm=LOCAL_COMM, sig_ids=None, uniq_idx=None,
                         dedup=False, carry_map=None, sig_table=None,
                         xwave=False):
    from .planes import unpack_features

    # ONE host→device transfer carries the whole wave's features; the
    # unpack slices fuse away under XLA (see planes.pack_features)
    batched_f = unpack_features(packed_f, layout)
    dedup = dedup and sig_ids is not None  # static arg: resolved at trace
    fast = dedup and dedup_fast_capable(cfg, comm)
    xwave = (xwave and fast and carry_map is not None
             and sig_table is not None)
    nb = planes["valid"].shape[0]
    if dedup:
        # static per-pod parts ONCE PER SIGNATURE: the vmap runs over the
        # first-occurrence rows only; steps gather their row by sig_id
        uniq_f = jax.tree_util.tree_map(
            lambda a: jnp.take(a, uniq_idx, axis=0), batched_f
        )
        static_rows = jax.vmap(
            lambda f: _static_pod_parts(cfg, planes, f, comm)
        )(uniq_f)
        xs = (batched_f, sig_ids)
    else:
        uniq_f = None
        static_rows = None
        static = jax.vmap(
            lambda f: _static_pod_parts(cfg, planes, f, comm)
        )(batched_f)
        xs = (batched_f, static)
    dom_counts, present = _dom_counts_init(cfg, planes, comm)
    ipa = ((planes["ipa_counts"], planes["ipa_anti"], planes["ipa_pref"])
           if cfg.ipa_active else None)
    # pipelined launch: an uncollected predecessor wave consumes the first
    # words of this tie stream; its final cursor arrives as a device scalar
    # (cursor_init) minus the host-side frame shift — the subtract lives in
    # the trace so back-to-back waves chain with no host round trip and no
    # eager scalar op (each eager dispatch costs a device round trip)
    cursor0 = (jnp.asarray(cursor_init, jnp.int32)
               - jnp.asarray(frame_shift, jnp.int32))
    if fast:
        C = uniq_idx.shape[0]
        ct = max(1, min(cfg.max_constraints, cfg.n_soft))
        dmax = max((dk for dk in cfg.topo_domains if dk > 0), default=1)
        if xwave:
            # seed the table from the previous wave's resident rows: slot
            # c replays from prev slot carry_map[c] (host signature-bytes
            # match), -1 means a fresh signature — its row starts invalid
            # and pays the full tier on first occurrence
            m = jnp.clip(carry_map, 0)
            ok = carry_map >= 0
            tab0 = (
                jnp.where(ok[:, None], sig_table["ew"][m], 0),
                jnp.where(ok[:, None], sig_table["ffit"][m], False),
                jnp.where(ok[:, None], sig_table["feas"][m], False),
                jnp.where(ok[:, None, None], sig_table["segs"][m], 0),
                jnp.where(ok[:, None, None], sig_table["pcs"][m], 0),
                ok,
            )
        else:
            tab0 = (jnp.zeros((C, nb), jnp.int32),
                    jnp.zeros((C, nb), bool), jnp.zeros((C, nb), bool),
                    jnp.zeros((C, ct, dmax), jnp.int32),
                    jnp.zeros((C, ct, dmax), jnp.int32),
                    jnp.zeros(C, bool))
        sig_scores0 = jnp.full((C, nb), -1, jnp.int32)
    else:
        tab0 = None
        sig_scores0 = None
    init = (planes["used"], planes["nonzero_used"], planes["sel_counts"],
            dom_counts, ipa, cursor0, jnp.bool_(False), tab0, sig_scores0)
    step = functools.partial(_assign_step, cfg, planes, present, tie_words,
                             comm, static_rows=static_rows, uniq_f=uniq_f,
                             fast=fast)
    (used, nonzero_used, sel_counts, _, ipa_out, cursor, overflow, tab,
     sig_scores), winners = jax.lax.scan(step, init, xs, unroll=4)
    # single-transfer result: winners ++ [tie_consumed, tie_overflow] — the
    # host reads everything it needs in ONE device→host round trip (the
    # tunnel's per-transfer latency dominates small fetches)
    packed = jnp.concatenate([
        winners.astype(jnp.int32),
        jnp.stack([cursor, overflow.astype(jnp.int32)]),
    ])
    out = {"used": used, "nonzero_used": nonzero_used,
           "sel_counts": sel_counts, "tie_consumed": cursor,
           "tie_overflow": overflow, "packed": packed}
    if sig_scores is not None:
        out["sig_scores"] = sig_scores
    if tab is not None:
        # the resident table stays on device; the host only keeps the
        # signature-bytes → slot map and hands the dict back as sig_table
        # on the next chained wave
        out["sig_table"] = {"ew": tab[0], "ffit": tab[1], "feas": tab[2],
                            "segs": tab[3], "pcs": tab[4]}
    if ipa_out is not None:
        out["ipa_counts"], out["ipa_anti"], out["ipa_pref"] = ipa_out
    return winners, out


@functools.partial(jax.jit, static_argnums=(0, 3, 9, 12))
def _batched_assign_jit(cfg: KernelConfig, planes: dict, packed_f,
                        layout, tie_words, cursor_init, frame_shift,
                        sig_ids, uniq_idx, dedup, carry_map, sig_table,
                        xwave):
    return _batched_assign_core(cfg, planes, packed_f, layout, tie_words,
                                cursor_init, frame_shift, LOCAL_COMM,
                                sig_ids=sig_ids, uniq_idx=uniq_idx,
                                dedup=dedup, carry_map=carry_map,
                                sig_table=sig_table, xwave=xwave)


def batched_assign(cfg: KernelConfig, planes: dict, batched_f: dict,
                   tie_words=None, cursor_init=0, frame_shift=0,
                   sig_ids=None, uniq_idx=None, carry_map=None,
                   sig_table=None):
    """Greedy multi-pod assignment: lax.scan over the pod axis; pod i+1 sees
    pod i's assumed deltas (the in-kernel analogue of the cache assume in
    schedule_one.go:320-333 and of the gang default algorithm, and the
    dense subsumption of OpportunisticBatching's score-list reuse).

    Tie-break: with tie_words (a stream of getrandbits(32) words cloned from
    the host algorithm's seeded rng) the winner draw is bit-identical to the
    host path's selectHost (schedule_one.go:1080-1134); the result dict's
    "tie_consumed" says how many words were used so the caller can advance
    the live rng. Without tie_words every draw resolves to the first
    max-score winner (deterministic first-index).

    Signature dedup: sig_ids [P] int32 groups slots whose packed feature
    rows are byte-identical (backend.group_signatures); uniq_idx [G] holds
    each group's first-occurrence slot. The scan then runs the static pass
    once per signature and — where dedup_fast_capable — replays resident
    score rows for every later clone. Decisions (winners, tie stream,
    planes) are bit-identical to the non-dedup scan; `sig_scores` in the
    result holds each signature's feasibility-gated score row for host
    cache export and `sig_table` the resident per-signature rows.

    Cross-wave reuse: carry_map [G] int32 maps each of this wave's
    signature slots to its slot in the previous chained wave's sig_table
    (-1 = miss); sig_table is that wave's resident-row dict, still on
    device. Both must come from a wave whose output planes are THIS wave's
    input planes (the backend's carry path) — the host is responsible for
    that gate (SignatureScoreCache).

    Returns (winners [P] int32 node index or -1, dict with updated
    used/nonzero_used/sel_counts planes + tie_consumed/tie_overflow)."""
    from .planes import pack_features

    if tie_words is None:
        tie_words = ZERO_TIE_WORDS
    packed, layout = pack_features(batched_f)
    dedup = sig_ids is not None and uniq_idx is not None
    xwave = bool(dedup and carry_map is not None and sig_table is not None)
    return _batched_assign_jit(cfg, planes, packed, layout, tie_words,
                               np.int32(cursor_init) if isinstance(cursor_init, int) else cursor_init,
                               np.int32(frame_shift),
                               np.asarray(sig_ids, np.int32) if dedup else None,
                               np.asarray(uniq_idx, np.int32) if dedup else None,
                               dedup,
                               np.asarray(carry_map, np.int32) if xwave else None,
                               sig_table if xwave else None,
                               xwave)


# --------------------------------------------------------------------------
# gang waves: whole-PodGroup placement over topology-domain masks
# --------------------------------------------------------------------------
#
# The device-side half of the pod-group cycle (schedule_one_podgroup.go:520
# podGroupSchedulingPlacementAlgorithm): instead of dry-running the gang
# once per topology domain on the host — each dry run a full sequence of
# single-pod kernel dispatches against a placement-narrowed snapshot
# rebuild — ONE program vmaps the member scan over a [D, Nb] stack of
# domain masks. Narrowing a placement is exactly `valid &= mask`: every
# filter/score reduction in this module already gates on planes["valid"]
# (filters, normalizations, domain counts, IPA parts), so a masked scan is
# bit-identical to the host dry run in the narrowed snapshot.


def _gang_placement_score(planes, mask):
    """Device replica of TopologyPlacementGenerator.score_placement: mean
    free-capacity score (0-100, LeastAllocated shape) of the mask's nodes,
    same int32 floor math as the host plugin, computed on the PRE-scan
    planes (the host scores after the dry run reverted its assumes)."""
    alloc = planes["alloc"]
    used = planes["used"]
    score = jnp.zeros(mask.shape[0], jnp.int32)
    parts = jnp.zeros(mask.shape[0], jnp.int32)
    for col in (CPU, MEM):
        cap = alloc[:, col]
        ok = cap > 0
        req = jnp.minimum(used[:, col], cap)
        s = (cap - req) * MAX_NODE_SCORE // jnp.maximum(cap, 1)
        score = score + jnp.where(ok, s, 0)
        parts = parts + ok.astype(jnp.int32)
    node_val = jnp.where(parts > 0, score // jnp.maximum(parts, 1), 0)
    counted = mask & (parts > 0)
    n = jnp.sum(counted.astype(jnp.int32))
    total = jnp.sum(jnp.where(counted, node_val, 0))
    return jnp.where(n > 0, total // jnp.maximum(n, 1), 0)


@functools.partial(jax.jit, static_argnums=(0, 3, 6, 7))
def _gang_assign_jit(cfg: KernelConfig, planes: dict, packed_f, layout,
                     masks, tie_words, n_constrained, has_fallback):
    from .planes import unpack_features

    batched_f = unpack_features(packed_f, layout)
    n_active = jnp.sum(batched_f["active"].astype(jnp.int32))

    def one_domain(mask):
        # a placement-narrowed snapshot IS the base planes with valid
        # restricted to the placement's rows: every reduction downstream
        # gates on valid, so the scan below replays the host dry run
        p = dict(planes)
        p["valid"] = planes["valid"] & mask
        static = jax.vmap(
            lambda f: _static_pod_parts(cfg, p, f)
        )(batched_f)
        dom_counts, present = _dom_counts_init(cfg, p)
        ipa = ((p["ipa_counts"], p["ipa_anti"], p["ipa_pref"])
               if cfg.ipa_active else None)
        # every domain replays the SAME tie-word stream from cursor 0: the
        # host dry-runs restore the rng after each placement, so only the
        # winning domain's consumption ever advances the live stream
        init = (p["used"], p["nonzero_used"], p["sel_counts"], dom_counts,
                ipa, jnp.int32(0), jnp.bool_(False), None, None)
        step = functools.partial(_assign_step, cfg, p, present, tie_words,
                                 LOCAL_COMM)
        (_, _, _, _, _, cursor, overflow, _, _), winners = jax.lax.scan(
            step, init, (batched_f, static), unroll=4
        )
        placed = jnp.sum(
            ((winners >= 0) & batched_f["active"]).astype(jnp.int32)
        )
        return winners.astype(jnp.int32), cursor, overflow, placed

    winners, consumed, overflow, placed = jax.vmap(one_domain)(masks)
    scores = jax.vmap(
        lambda m: _gang_placement_score(planes, m)
    )(masks)

    # host winner semantics (schedule_one.py _pod_group_algorithm): best
    # CONSTRAINED domain by placement score, strict > over placement order
    # (argmax == first max); only when none fits does the Preferred /
    # unconstrained fallback row (index n_constrained) get the gang
    all_placed = placed == n_active
    key = jnp.where(all_placed & ~overflow, scores, -1)
    if n_constrained > 0:
        d_ids = jnp.arange(masks.shape[0], dtype=jnp.int32)
        ckey = jnp.where(d_ids < n_constrained, key, -1)
        cbest = jnp.max(ckey)
        cwin = jnp.argmax(ckey).astype(jnp.int32)
    else:
        cbest = jnp.int32(-1)
        cwin = jnp.int32(0)
    if has_fallback:
        fb = jnp.int32(n_constrained)
        fb_ok = all_placed[n_constrained] & ~overflow[n_constrained]
        win_d = jnp.where(cbest >= 0, cwin, fb)
        ok = (cbest >= 0) | fb_ok
    else:
        win_d = cwin
        ok = cbest >= 0

    # single-transfer result: winners per domain ++ per-domain telemetry
    # rows (consumed/overflow/placed/score) ++ [win_d, ok, n_active]
    return jnp.concatenate([
        winners.reshape(-1),
        consumed.astype(jnp.int32),
        overflow.astype(jnp.int32),
        placed.astype(jnp.int32),
        scores.astype(jnp.int32),
        jnp.stack([win_d, ok.astype(jnp.int32), n_active]),
    ])


def gang_assign(cfg: KernelConfig, planes: dict, batched_f: dict, masks,
                tie_words=None, n_constrained: int = 0,
                has_fallback: bool = True):
    """Whole-gang placement: one program scans the gang's members over
    every topology-domain mask at once and picks the domain that holds the
    ENTIRE group (all-or-nothing — a domain where any member fails to
    place scores -1 and can never win).

    masks is a [D, Nb] bool stack in the host placement order: rows
    [0, n_constrained) are the PlacementGenerate domains, row n_constrained
    (when has_fallback) is the unconstrained full-snapshot row Preferred
    topology and plugin-less gangs fall back to, and any remaining rows are
    all-False padding (an empty valid set places nobody, so a pad row can
    never be selected).

    Tie-break parity: every domain replays the same tie_words stream from
    cursor 0, mirroring the host's rng save/restore around each placement
    dry run; the caller advances the live rng by the winning domain's
    consumed count only, and MUST fall back to the host path when any real
    domain reports tie overflow (a truncated draw desynchronizes that
    domain's verdict, not just its stream).

    Returns the packed int32 result vector: winners [D*P] ++ consumed [D]
    ++ overflow [D] ++ placed [D] ++ score [D] ++ [win_d, ok, n_active] —
    ONE device->host fetch carries the whole gang verdict."""
    from .planes import pack_features

    if tie_words is None:
        tie_words = ZERO_TIE_WORDS
    packed, layout = pack_features(batched_f)
    return _gang_assign_jit(cfg, planes, packed, layout,
                            jnp.asarray(masks), tie_words,
                            int(n_constrained), bool(has_fallback))
