"""Dense TPU kernel layer: vocabularies, tensor planes, fit/score kernels.

The kernels enable jax x64 lazily at first invocation (int64 image-byte math
must match the host path exactly); all other kernel dtypes are explicit
(int32/float32), and importing this package has no global side effects.
"""

from .vocab import ClusterVocabs, Vocab, next_pow2
from .planes import (
    DeviceFlakeError,
    FallbackNeeded,
    Planes,
    PlaneBuilder,
    PodFeatureExtractor,
    pack_features,
    pad_features,
    stack_features,
    unpack_features,
)
from .kernels import (
    FILTER_NAMES,
    KernelConfig,
    batched_assign,
    fit_and_score,
)

__all__ = [
    "ClusterVocabs", "Vocab", "next_pow2", "DeviceFlakeError",
    "FallbackNeeded", "Planes",
    "PlaneBuilder", "PodFeatureExtractor", "pad_features", "stack_features",
    "FILTER_NAMES", "KernelConfig", "batched_assign", "fit_and_score",
]
