"""Tensor planes: the Snapshot materialized as dense [nodes, ...] arrays.

This is the TPU-native replacement for walking `[]NodeInfo` with 16 goroutines
(pkg/scheduler/framework/parallelize/parallelism.go): every per-node quantity a
filter or score plugin reads is laid out as a column of a dense plane, padded
to power-of-two buckets (static shapes for XLA), and updated incrementally by
NodeInfo generation (mirroring the O(changed) snapshot update of
pkg/scheduler/backend/cache/cache.go:190-360).

Planes (all numpy host-side; the backend uploads them to device HBM):
- alloc/used        [Nb, R]  int32   allocatable / requested, plane units
- nonzero_used      [Nb, 2]  int32   NonZeroRequested cpu/mem (scoring)
- valid             [Nb]     bool    padding mask
- unsched           [Nb]     bool    node.spec.unschedulable
- group_id          [Nb]     int32   node-label-group vocab id
- taints            [Nb, T]  int32   NoSchedule/NoExecute taint vocab ids, -1 pad
- prefer_taints     [Nb, Tp] int32   PreferNoSchedule taint vocab ids, -1 pad
- domain            [Nb, K]  int32   per-topology-key domain id, -1 = key absent
- sel_counts        [Nb, S]  int32   pods on node matching selector signature s
- port_words        [Nb, W]  uint32  used host-port bitset over the port vocab
- image_kib         [Nb, I]  int32   per-image KiB present on node
- ipa_counts        [Nb, Ta] int32   pods on node matching IPA term selector t
- ipa_anti          [Nb, Ta] int32   (pod, required-anti-affinity term) pairs
                                     on node with term id t (filtering.go:91)
- ipa_pref          [Nb, Ta] int32   signed preferred-term weight sums of pods
                                     on node per term id (scoring.go:81)
- ipa_term_key      [Ta]     int32   topology-key slot per term (global table)

Pod features (PodFeatureExtractor) are the per-pod side of the same split:
everything string-shaped is resolved host-side against the vocabularies, so
the kernel only gathers and compares integers.
"""

from __future__ import annotations

import numpy as np

from ..api.resource import CPU, MEM, ResourceNames
from ..api.types import NO_SCHEDULE, PREFER_NO_SCHEDULE, Pod, Taint
from .vocab import ClusterVocabs, next_pow2

ZONE_LABEL = "topology.kubernetes.io/zone"
HOSTNAME_LABEL = "kubernetes.io/hostname"
UNSCHEDULABLE_TAINT_KEY = "node.kubernetes.io/unschedulable"
_FIELD_HOSTNAME = "metadata.name"


class Planes:
    """Container of the dense node planes + index metadata."""

    __slots__ = (
        "node_names", "node_index", "n", "nb", "r",
        "alloc", "used", "nonzero_used", "valid", "unsched", "group_id",
        "taints", "prefer_taints", "domain", "sel_counts", "port_words",
        "image_kib", "ipa_counts", "ipa_anti", "ipa_pref", "ipa_term_key",
        "version", "bucket_sizes",
    )

    def as_dict(self) -> dict[str, np.ndarray]:
        """The kernel-input arrays (everything the jitted code consumes)."""
        return {
            "alloc": self.alloc,
            "used": self.used,
            "nonzero_used": self.nonzero_used,
            "valid": self.valid,
            "unsched": self.unsched,
            "group_id": self.group_id,
            "taints": self.taints,
            "prefer_taints": self.prefer_taints,
            "domain": self.domain,
            "sel_counts": self.sel_counts,
            "port_words": self.port_words,
            "image_kib": self.image_kib,
            "ipa_counts": self.ipa_counts,
            "ipa_anti": self.ipa_anti,
            "ipa_pref": self.ipa_pref,
            "ipa_term_key": self.ipa_term_key,
        }


def _canonical_fingerprint(vocabs: ClusterVocabs, names: ResourceNames) -> tuple:
    return (
        len(vocabs.taints), len(vocabs.prefer_taints), len(vocabs.groups),
        len(vocabs.topo_keys),
        tuple(len(vocabs.domain_vocab(i)) for i in range(len(vocabs.topo_keys))),
        len(vocabs.selectors), len(vocabs.ports), len(vocabs.images),
        len(vocabs.ipa_terms),
        names.width,
    )


class PlaneBuilder:
    """Builds and incrementally refreshes Planes from a Snapshot."""

    def __init__(self, names: ResourceNames, vocabs: ClusterVocabs | None = None):
        self.names = names
        self.vocabs = vocabs or ClusterVocabs()
        # default topology keys so the common spread constraints don't force
        # an early rebuild (podtopologyspread system defaults, plugin.go:46-60)
        self.vocabs.topo_keys.id(ZONE_LABEL)
        self.vocabs.topo_keys.id(HOSTNAME_LABEL)
        self._planes: Planes | None = None
        self._row_cache: dict[str, tuple[int, tuple]] = {}  # name -> (gen, fp)
        self._version = 0
        self.dirty_rows: list[int] | None = None  # rows changed by last sync
        # (snapshot uid, version, membership_version, fingerprint) of the
        # last sync — the O(changed) fast-path key (see _fast_sync)
        self._last_sync: tuple | None = None

    # -- public ------------------------------------------------------------

    def sync(self, snapshot) -> Planes:
        """Refresh planes from the snapshot; O(changed nodes) when the node
        set, bucket sizes, and vocabularies are stable."""
        p = self._fast_sync(snapshot)
        if p is not None:
            return p
        nodes = snapshot.list_nodes()
        names = [ni.name for ni in nodes]
        # intern node-derived vocab entries BEFORE sizing buckets, so the
        # fingerprint and bucket sizes already reflect this sync's content
        for ni in nodes:
            cached = self._row_cache.get(ni.name)
            if cached is None or cached[0] != ni.generation:
                self._register_node(ni)
        fp = _canonical_fingerprint(self.vocabs, self.names)
        buckets = self._bucket_sizes(len(nodes), fp)
        p = self._planes
        # strict append within the same pow2 node bucket: joined nodes get
        # new tail rows (existing rows keep their index), so membership
        # growth stays an O(changed) row update with dirty-row tracking
        # intact — the device mirror repairs it with a delta scatter, not a
        # full re-put. Removals/reorders still rebuild (rare, sanctioned).
        append = (
            p is not None and p.bucket_sizes == buckets
            and len(names) > len(p.node_names)
            and names[: len(p.node_names)] == p.node_names
        )
        if p is None or (not append and p.node_names != names) \
                or p.bucket_sizes != buckets:
            p = self._full_build(nodes, names, buckets, fp)
            self.dirty_rows: list[int] | None = None  # None = everything changed
        else:
            if append and p.node_names != names:
                old_n = p.n
                p.node_names = names
                for i in range(old_n, len(names)):
                    p.node_index[names[i]] = i
                p.n = len(names)
                p.valid[old_n: p.n] = True
                # new tail rows have no row-cache entry yet, so the loop
                # below writes (and dirties) exactly them + changed rows
            dirty: list[int] = []
            for i, ni in enumerate(nodes):
                cached = self._row_cache.get(ni.name)
                if cached is not None and cached == (ni.generation, fp):
                    continue
                self._write_row(p, i, ni, fp)
                dirty.append(i)
            self._finish_row_sync(p, dirty)
        self._stamp_sync(snapshot, p, fp)
        return p

    def _finish_row_sync(self, p: Planes, dirty: list[int]) -> None:
        """Shared tail of both sync paths: refresh GLOBAL (non-row) tables
        — a term interned mid-run (first pod with that affinity) dirties
        every row's counts, but its key-slot mapping lives here; a stale -1
        makes the kernel reject every node for that term — then record the
        dirty rows and bump the version when anything moved."""
        tables_changed = False
        for ti, (_ns, _sel, ki) in enumerate(self.vocabs.ipa_term_matchers):
            if p.ipa_term_key[ti] != ki:
                p.ipa_term_key[ti] = ki
                tables_changed = True
        self.dirty_rows = dirty
        if dirty or tables_changed:
            self._version += 1
            p.version = self._version

    def _stamp_sync(self, snapshot, p: Planes, fp: tuple) -> None:
        """Shared tail of both sync paths: _write_row may have interned new
        *values* (e.g. topology domains) mid-pass; restamp the row cache
        with the post-write fingerprint so the next sync doesn't see a
        spurious mismatch and rewrite every row. Row content is invariant
        to value-vocab growth (ids are append-only; shape-affecting growth
        changes bucket sizes and forces a rebuild). Records the fast-path
        key for the next sync."""
        fp2 = _canonical_fingerprint(self.vocabs, self.names)
        if fp2 != fp:
            self._row_cache = {
                nm: (gen, fp2) for nm, (gen, _) in self._row_cache.items()
            }
        self._planes = p
        self._last_sync = (
            getattr(snapshot, "uid", None),
            getattr(snapshot, "version", None),
            getattr(snapshot, "membership_version", None),
            fp2,
        )

    def _fast_sync(self, snapshot):
        """O(changed) sync via the snapshot's change feed: when this builder
        last synced this very snapshot and only row content changed since
        (no membership/order change, no vocab or bucket growth), re-extract
        ONLY the nodes named in the changelog suffix instead of scanning all
        N rows — the per-pod hybrid path syncs once per pod, and a full
        O(N) scan per pod dominated its profile at 5k nodes. Returns None
        to defer to the full path."""
        p = self._planes
        last = self._last_sync
        sv = getattr(snapshot, "version", None)
        if (p is None or last is None or sv is None
                or last[0] != snapshot.uid
                or last[2] != snapshot.membership_version
                or not (snapshot.changelog_base <= last[1] <= sv)):
            return None
        changed = set(snapshot.changelog[last[1] - snapshot.changelog_base:])
        for nm in changed:
            ni = snapshot.node_info_map.get(nm)
            if ni is None:
                return None  # feed references a node the map lost: full scan
            cached = self._row_cache.get(nm)
            if cached is None or cached[0] != ni.generation:
                self._register_node(ni)
        fp = _canonical_fingerprint(self.vocabs, self.names)
        if fp != last[3]:
            return None  # vocab growth: bucket sizes may move, full path
        if self._bucket_sizes(len(p.node_names), fp) != p.bucket_sizes:
            return None
        dirty: list[int] = []
        for nm in sorted(changed):
            ni = snapshot.node_info_map[nm]
            i = p.node_index.get(nm)
            if i is None:
                return None
            cached = self._row_cache.get(nm)
            if cached is not None and cached == (ni.generation, fp):
                continue
            self._write_row(p, i, ni, fp)
            dirty.append(i)
        self._finish_row_sync(p, dirty)
        self._stamp_sync(snapshot, p, fp)
        return p

    def topo_domains(self, planes: Planes) -> tuple[int, ...]:
        """Per-topology-key kernel treatment (KernelConfig.topo_domains):
        0 when every domain holds at most one node (hostname-style keys —
        the kernel then skips segment reductions entirely), else the padded
        domain-vocab size for the one-hot-matmul reduction."""
        v = self.vocabs
        out = []
        k_bucket = planes.domain.shape[1]
        for k in range(k_bucket):
            if k >= len(v.topo_keys):
                out.append(0)  # unused key slot
                continue
            col = planes.domain[: planes.n, k]
            vals = col[col >= 0]
            if vals.size == 0 or np.unique(vals).size == vals.size:
                out.append(0)
            else:
                out.append(next_pow2(len(v.domain_vocab(k)), 1))
        return tuple(out)

    # -- internals ----------------------------------------------------------

    def _register_node(self, ni) -> None:
        v = self.vocabs
        node = ni.node
        if node is not None:
            v.group_of_labels(dict(node.meta.labels))
            for tt in node.spec.taints:
                if tt.effect in (NO_SCHEDULE, "NoExecute"):
                    v.taints.id((tt.key, tt.value, tt.effect))
                elif tt.effect == PREFER_NO_SCHEDULE:
                    v.prefer_taints.id((tt.key, tt.value))
            for ki in range(len(v.topo_keys)):
                val = node.meta.labels.get(v.topo_keys.key(ki))
                if val is not None:
                    v.domain_vocab(ki).id(val)
        for (_ip, proto, port) in ni.used_ports:
            v.ports.id((proto, port))
        for img_name in ni.image_sizes:
            v.images.id(img_name)
        # existing pods' (anti)affinity terms — required AND preferred, so the
        # planes cover both filter (filtering.go:91) and score (scoring.go:81)
        for epi in ni.pods_with_affinity:
            for term in epi.required_affinity_terms:
                v.ipa_term_id(term)
            for term in epi.required_anti_affinity_terms:
                v.ipa_term_id(term)
            for _w, term in epi.preferred_affinity_terms:
                v.ipa_term_id(term)
            for _w, term in epi.preferred_anti_affinity_terms:
                v.ipa_term_id(term)

    def _bucket_sizes(self, n: int, fp: tuple) -> tuple:
        # node bucket stays pow2: measured on v5e, a 5120 bucket ran ~16%
        # SLOWER than 8192 for the 5k-node wave — XLA's tiling prefers the
        # aligned shape over the smaller element count
        v = self.vocabs
        max_taints = max((len(v.taints), 1))
        return (
            next_pow2(n, 8),                       # Nb
            next_pow2(self.names.width, 4),        # R
            next_pow2(max_taints, 1),              # T (vocab-sized: node rows index it)
            next_pow2(max(len(v.prefer_taints), 1), 1),   # Tp
            next_pow2(max(len(v.topo_keys), 2), 2),       # K
            next_pow2(max(len(v.selectors), 1), 1),       # S
            next_pow2((len(v.ports) + 31) // 32, 1),      # W port words
            next_pow2(max(len(v.images), 1), 1),          # I
            next_pow2(max(len(v.ipa_terms), 1), 1),       # Ta IPA terms
        )

    def _full_build(self, nodes, names, buckets, fp) -> Planes:
        nb, r, t, tp, k, s, w, im, ta = buckets
        p = Planes()
        p.node_names = names
        p.node_index = {nm: i for i, nm in enumerate(names)}
        p.n = len(nodes)
        p.nb, p.r = nb, r
        p.bucket_sizes = buckets
        p.alloc = np.zeros((nb, r), np.int32)
        p.used = np.zeros((nb, r), np.int32)
        p.nonzero_used = np.zeros((nb, 2), np.int32)
        p.valid = np.zeros(nb, bool)
        p.valid[: p.n] = True
        p.unsched = np.zeros(nb, bool)
        p.group_id = np.zeros(nb, np.int32)
        p.taints = np.full((nb, t), -1, np.int32)
        p.prefer_taints = np.full((nb, tp), -1, np.int32)
        p.domain = np.full((nb, k), -1, np.int32)
        p.sel_counts = np.zeros((nb, s), np.int32)
        p.port_words = np.zeros((nb, w), np.uint32)
        p.image_kib = np.zeros((nb, im), np.int32)
        p.ipa_counts = np.zeros((nb, ta), np.int32)
        p.ipa_anti = np.zeros((nb, ta), np.int32)
        p.ipa_pref = np.zeros((nb, ta), np.int32)
        # global term → topology-key-slot table (padded slots map to -1 so
        # the kernel's per-key unroll never picks them up)
        p.ipa_term_key = np.full(ta, -1, np.int32)
        for ti, (_ns, _sel, ki) in enumerate(self.vocabs.ipa_term_matchers):
            p.ipa_term_key[ti] = ki
        self._row_cache.clear()
        for i, ni in enumerate(nodes):
            self._write_row(p, i, ni, fp)
        self._version += 1
        p.version = self._version
        return p

    def _write_row(self, p: Planes, i: int, ni, fp: tuple) -> None:
        v = self.vocabs
        node = ni.node
        p.alloc[i, : p.r] = 0
        p.alloc[i, : min(len(ni.allocatable.v), p.r)] = [
            min(x, 2**31 - 1) for x in ni.allocatable.v[: p.r]
        ]
        p.used[i, : p.r] = 0
        p.used[i, : min(len(ni.requested.v), p.r)] = ni.requested.v[: p.r]
        p.nonzero_used[i, 0] = ni.nonzero_requested[CPU]
        p.nonzero_used[i, 1] = ni.nonzero_requested[MEM]
        labels = node.meta.labels if node is not None else {}
        p.unsched[i] = bool(node is not None and node.spec.unschedulable)
        p.group_id[i] = v.group_of_labels(dict(labels))
        # taints
        p.taints[i, :] = -1
        p.prefer_taints[i, :] = -1
        if node is not None:
            hard = [tt for tt in node.spec.taints if tt.effect in (NO_SCHEDULE, "NoExecute")]
            soft = [tt for tt in node.spec.taints if tt.effect == PREFER_NO_SCHEDULE]
            for j, tt in enumerate(hard[: p.taints.shape[1]]):
                p.taints[i, j] = v.taints.id((tt.key, tt.value, tt.effect))
            for j, tt in enumerate(soft[: p.prefer_taints.shape[1]]):
                p.prefer_taints[i, j] = v.prefer_taints.id((tt.key, tt.value))
        # topology domains
        p.domain[i, :] = -1
        for ki in range(len(v.topo_keys)):
            key = v.topo_keys.key(ki)
            val = labels.get(key)
            if val is not None and ki < p.domain.shape[1]:
                p.domain[i, ki] = v.domain_vocab(ki).id(val)
        # selector-signature pod counts (podtopologyspread/filtering.go:97)
        p.sel_counts[i, :] = 0
        for si, (ns, sel) in enumerate(v.selector_matchers):
            if si >= p.sel_counts.shape[1]:
                break
            c = 0
            for pi in ni.iter_pods():
                pod = pi.pod
                if pod.meta.namespace != ns or pod.is_terminating:
                    continue
                if sel.matches(pod.meta.labels):
                    c += 1
            p.sel_counts[i, si] = c
        # used host ports
        p.port_words[i, :] = 0
        for (_ip, proto, port) in ni.used_ports:
            b = v.ports.id((proto, port))
            if b // 32 < p.port_words.shape[1]:
                p.port_words[i, b // 32] |= np.uint32(1 << (b % 32))
        # images
        p.image_kib[i, :] = 0
        for img_name, size in ni.image_sizes.items():
            ii = v.images.id(img_name)
            if ii < p.image_kib.shape[1]:
                p.image_kib[i, ii] = size >> 10  # KiB keeps int32 on-device
        # inter-pod affinity planes (the dense topologyToMatchedTermCount:
        # per-term matching-pod counts + per-term carried anti/preferred
        # terms; domain aggregation happens on device)
        p.ipa_counts[i, :] = 0
        p.ipa_anti[i, :] = 0
        p.ipa_pref[i, :] = 0
        if v.ipa_terms:
            ta = p.ipa_counts.shape[1]
            for ti, (ns_set, sel, _ki) in enumerate(v.ipa_term_matchers):
                if ti >= ta or sel is None:
                    continue  # None-selector terms match nothing
                c = 0
                for epi in ni.iter_pods():
                    pod = epi.pod
                    if pod.meta.namespace in ns_set and sel.matches(pod.meta.labels):
                        c += 1
                p.ipa_counts[i, ti] = c
            for epi in ni.pods_with_required_anti_affinity:
                for term in epi.required_anti_affinity_terms:
                    ti = v.ipa_term_id(term)
                    if ti < ta:
                        p.ipa_anti[i, ti] += 1
            for epi in ni.pods_with_affinity:
                for w_, term in epi.preferred_affinity_terms:
                    ti = v.ipa_term_id(term)
                    if ti < ta:
                        p.ipa_pref[i, ti] += w_
                for w_, term in epi.preferred_anti_affinity_terms:
                    ti = v.ipa_term_id(term)
                    if ti < ta:
                        p.ipa_pref[i, ti] -= w_
        self._row_cache[ni.name] = (ni.generation, fp)


class FallbackNeeded(Exception):
    """Raised when a pod uses features the dense kernel does not model yet;
    the caller must run the host scheduling path for this pod."""

    # subclasses representing a real device failure (as opposed to a benign
    # "this pod isn't kernelizable") set this True; the TPU circuit breaker
    # counts only those toward tripping — duck-typed so consumers in the
    # host-side scheduler never import the tpu package to check
    device_flake = False


class DeviceFlakeError(FallbackNeeded):
    """The device path itself failed (today: an injected tpu.launch /
    tpu.collect fault; tomorrow: a real runtime error wrapped at the
    backend boundary). Handled exactly like FallbackNeeded — the wave's
    pods re-run per-pod, landing on the host tier — but ALSO counts as a
    circuit-breaker failure."""

    device_flake = True


class PodFeatureExtractor:
    """Resolves one Pod against the vocabularies into fixed-shape arrays.

    Raises FallbackNeeded for the long-tail features kept host-side
    (match_fields beyond the In(metadata.name) fast path, host ports with
    specific hostIPs, constraint/term counts beyond the kernel slots).
    Inter-pod (anti)affinity is fully kernelized.
    """

    MAX_CONSTRAINTS = 4  # padded constraint slots per pod
    MAX_IPA_TERMS = 4    # required (anti)affinity term slots per pod
    MAX_IPA_PREF = 8     # preferred (anti)affinity term slots per pod

    def __init__(self, names: ResourceNames, vocabs: ClusterVocabs,
                 system_default_spread: bool = True):
        self.names = names
        self.vocabs = vocabs
        self.system_default_spread = system_default_spread
        self._aff_sigs: dict = {}  # full-spec key -> (sig, pin name | None)
        self._aff_specs: list = []
        self._aff_spec_ids: dict = {}  # residual-spec key -> sig (dedup)
        self._aff_tables: dict | None = None
        self._aff_tables_key: tuple | None = None
        self._feat_cache: dict = {}
        self._feat_cache_key: tuple | None = None

    # -- vocab registration (must run before PlaneBuilder.sync) -------------

    def register(self, pod: Pod) -> None:
        """Intern every vocab entry this pod needs so the subsequent
        planes sync covers them."""
        from ..scheduler.plugins.pod_topology_spread import PodTopologySpread

        pts = PodTopologySpread(system_defaulting=self.system_default_spread)
        for action in ("DoNotSchedule", "ScheduleAnyway"):
            for c in pts._constraints_for(pod, action):
                ki = self.vocabs.topo_keys.id(c.topology_key)
                self.vocabs.domain_vocab(ki)
                sel = c.label_selector
                if sel is not None:
                    self.vocabs.selector_id(pod.meta.namespace, sel)
        aff = pod.spec.affinity
        if aff is not None and (aff.pod_affinity or aff.pod_anti_affinity):
            from ..scheduler.nodeinfo import PodInfo

            pi = PodInfo(pod, self.names)
            for term in pi.required_affinity_terms + pi.required_anti_affinity_terms:
                ti = self.vocabs.ipa_term_id(term)
                self.vocabs.domain_vocab(self.vocabs.ipa_term_matchers[ti][2])
            for _w, term in (pi.preferred_affinity_terms
                             + pi.preferred_anti_affinity_terms):
                ti = self.vocabs.ipa_term_id(term)
                self.vocabs.domain_vocab(self.vocabs.ipa_term_matchers[ti][2])
        for c in pod.spec.containers:
            for prt in c.ports:
                if prt.host_port > 0:
                    self.vocabs.ports.id((prt.protocol, prt.host_port))
            if c.image:
                self.vocabs.images.id(c.image)

    # -- extraction ----------------------------------------------------------

    def features_cached(self, pod: Pod, planes: Planes) -> dict[str, np.ndarray]:
        """features() memoized by pod shape: pods identical up to their name
        share one extraction (the dense analogue of SignPod sharing one
        score list, staging/.../framework/signers.go). Safe because every
        feature is a pure function of (spec, namespace, labels) and the
        vocab/bucket epoch — the cache clears when either changes. Callers
        must not mutate the returned arrays (stack_features copies)."""
        # epoch: features are pure in (spec, ns, labels) given vocab contents
        # (fingerprint = exact vocab lengths), bucket shapes, and the node
        # list (name_idx; node_index is fixed per Planes object). Plane ROW
        # content (used/counts) never enters features, so the cache survives
        # across waves.
        epoch = (planes.bucket_sizes, id(planes),
                 _canonical_fingerprint(self.vocabs, self.names))
        if self._feat_cache_key != epoch:
            self._feat_cache.clear()
            self._feat_cache_key = epoch
        key = (pod.meta.namespace, tuple(sorted(pod.meta.labels.items())),
               repr(pod.spec))
        f = self._feat_cache.get(key)
        if f is None:
            f = self.features(pod, planes)
            self._feat_cache[key] = f
        return f

    def features(self, pod: Pod, planes: Planes) -> dict[str, np.ndarray]:
        """Fixed-shape per-pod kernel inputs, aligned to `planes` buckets."""
        from ..api.resource import nonzero_request_vec, pod_request_vec
        from ..scheduler.plugins.pod_topology_spread import PodTopologySpread

        v = self.vocabs
        nb = planes.nb
        _, r, t, tp, k, s, w, im, ta = planes.bucket_sizes
        f: dict[str, np.ndarray] = {}

        # inter-pod (anti)affinity features: the pod's own term slots plus its
        # match vector against every interned term — the per-pod side of the
        # dense topologyToMatchedTermCount (interpodaffinity/filtering.go:91)
        self._ipa_features(pod, f, ta)

        # resources (noderesources/fit.go:317 computePodResourceRequest)
        req = pod_request_vec(pod, self.names)
        nz = nonzero_request_vec(req)
        f["req"] = np.array(req.row(r), np.int32)
        f["nz_req"] = np.array([nz[CPU], nz[MEM]], np.int32)

        # NodeName (node_name.go:79)
        if pod.spec.node_name:
            f["name_idx"] = np.int32(planes.node_index.get(pod.spec.node_name, -2))
        else:
            f["name_idx"] = np.int32(-1)

        # NodeUnschedulable toleration escape (node_unschedulable.go:142)
        f["tol_unsched"] = np.bool_(any(
            tl.key in (UNSCHEDULABLE_TAINT_KEY, "") and tl.operator == "Exists"
            for tl in pod.spec.tolerations
        ))

        # taint tolerance tables (tainttoleration.go Filter + Score)
        tol = np.zeros(t, bool)
        for j in range(len(v.taints)):
            key, val, eff = v.taints.key(j)
            taint = Taint(key, val, eff)
            tol[j] = any(tl.tolerates(taint) for tl in pod.spec.tolerations)
        f["tol"] = tol
        score_tols = [tl for tl in pod.spec.tolerations
                      if tl.effect in ("", PREFER_NO_SCHEDULE)]
        tolp = np.zeros(tp, bool)
        for j in range(len(v.prefer_taints)):
            key, val = v.prefer_taints.key(j)
            taint = Taint(key, val, PREFER_NO_SCHEDULE)
            tolp[j] = any(tl.tolerates(taint) for tl in score_tols)
        f["tol_prefer"] = tolp

        # node affinity / nodeSelector resolved to a shared signature row
        # (node_affinity.go:218; signature reuse mirrors SignPod,
        # staging/.../framework/signers.go — identical pods share one row).
        # A single-name required affinity (the daemonset shape) rides as a
        # per-pod pin index instead (node_affinity.go:159 fast path); -2 =
        # pinned to a node not in this snapshot -> infeasible everywhere
        sig, pin_name = self._affinity_sig(pod)
        f["aff_sig"] = np.int32(sig)
        f["aff_pin"] = np.int32(
            -1 if pin_name is None else planes.node_index.get(pin_name, -2)
        )

        # host ports (node_ports.go:75) — wildcard-ip pods only; the
        # (proto, port) bitset is exact for those
        ports = np.zeros(w, np.uint32)
        has_ports = False
        for c in pod.spec.containers:
            for prt in c.ports:
                if prt.host_port <= 0:
                    continue
                if prt.host_ip not in ("", "0.0.0.0"):
                    raise FallbackNeeded("host port with specific hostIP")
                b = v.ports.get((prt.protocol, prt.host_port))
                if b is None or b // 32 >= w:
                    raise FallbackNeeded("port vocab stale; re-register pod")
                ports[b // 32] |= np.uint32(1 << (b % 32))
                has_ports = True
        f["ports"] = ports
        f["has_ports"] = np.bool_(has_ports)

        # topology spread constraints → (key idx, selector idx, skew) slots
        pts = PodTopologySpread(system_defaulting=self.system_default_spread)
        for kind, action in (("hard", "DoNotSchedule"), ("soft", "ScheduleAnyway")):
            cs = pts._constraints_for(pod, action)
            if len(cs) > self.MAX_CONSTRAINTS:
                raise FallbackNeeded("more spread constraints than kernel slots")
            active = np.zeros(self.MAX_CONSTRAINTS, bool)
            ckey = np.zeros(self.MAX_CONSTRAINTS, np.int32)
            csel = np.zeros(self.MAX_CONSTRAINTS, np.int32)
            cskew = np.zeros(self.MAX_CONSTRAINTS, np.int32)
            cself = np.zeros(self.MAX_CONSTRAINTS, np.int32)
            for j, c in enumerate(cs):
                ki = v.topo_keys.get(c.topology_key)
                sel = c.label_selector
                si = (v.selectors.get((pod.meta.namespace, sel.canonical()))
                      if sel is not None else None)
                if ki is None or ki >= k or si is None or si >= s:
                    raise FallbackNeeded("spread vocab stale; re-register pod")
                active[j] = True
                ckey[j], csel[j], cskew[j] = ki, si, c.max_skew
                cself[j] = 1 if sel.matches(pod.meta.labels) else 0
            f[f"{kind}_active"] = active
            f[f"{kind}_key"] = ckey
            f[f"{kind}_sel"] = csel
            f[f"{kind}_skew"] = cskew
            f[f"{kind}_self"] = cself

        # image locality (image_locality.go:93-105)
        img_idx = np.full(8, -1, np.int32)
        n_containers = len(pod.spec.containers)
        if n_containers > 8:
            raise FallbackNeeded("more containers than image slots")
        for j, c in enumerate(pod.spec.containers):
            if c.image:
                ii = v.images.get(c.image)
                if ii is not None and ii < im:
                    img_idx[j] = ii
        f["img_idx"] = img_idx
        f["num_containers"] = np.int32(max(n_containers, 1))

        # which selector signatures this pod itself matches (batched-assign
        # carry update: the placed pod joins its own spread domains)
        sig = np.zeros(s, np.int32)
        for si, (ns, sel) in enumerate(v.selector_matchers):
            if si < s and ns == pod.meta.namespace and sel.matches(pod.meta.labels):
                sig[si] = 1
        f["sig_match"] = sig
        # real pod slot (pad_features flips this for wave padding)
        f["active"] = np.bool_(True)
        return f

    def _ipa_features(self, pod: Pod, f: dict, ta: int) -> None:
        """Inter-pod affinity per-pod inputs (all bucket-aligned to Ta):

        - ipa_match  [Ta] bool  term t's (ns, selector) matches THIS pod —
          drives the existing→incoming direction (check 1 of filtering.go:352
          and the existing-preferred side of scoring.go:81), and the scan
          carry update (a placed pod joins each matching term's counts).
        - ipa_aff_t/ipa_anti_t [MAX_IPA_TERMS] int32 term ids of the pod's
          required (anti)affinity terms, -1 pad; ipa_aff_self marks terms
          that match the pod itself (self-match bootstrap, filtering.go:404).
        - ipa_pref_t [MAX_IPA_PREF] int32 + ipa_pref_w signed weights for the
          pod's preferred terms (anti terms carry negative weight).
        - ipa_anti_add/ipa_pref_add [Ta] int32: the pod's own contribution to
          the ipa_anti/ipa_pref planes if placed (batched-scan carry).
        """
        from ..scheduler.nodeinfo import PodInfo

        v = self.vocabs
        match = np.zeros(ta, bool)
        for ti, (ns_set, sel, _ki) in enumerate(v.ipa_term_matchers):
            if ti >= ta or sel is None:
                continue
            match[ti] = (pod.meta.namespace in ns_set
                         and sel.matches(pod.meta.labels))
        f["ipa_match"] = match

        aff = pod.spec.affinity
        aff_t = np.full(self.MAX_IPA_TERMS, -1, np.int32)
        aff_self = np.zeros(self.MAX_IPA_TERMS, bool)
        anti_t = np.full(self.MAX_IPA_TERMS, -1, np.int32)
        pref_t = np.full(self.MAX_IPA_PREF, -1, np.int32)
        pref_w = np.zeros(self.MAX_IPA_PREF, np.int32)
        anti_add = np.zeros(ta, np.int32)
        pref_add = np.zeros(ta, np.int32)
        if aff is not None and (aff.pod_affinity or aff.pod_anti_affinity):
            pi = PodInfo(pod, self.names)
            if (len(pi.required_affinity_terms) > self.MAX_IPA_TERMS
                    or len(pi.required_anti_affinity_terms) > self.MAX_IPA_TERMS):
                raise FallbackNeeded("more required IPA terms than kernel slots")
            prefs = pi.preferred_affinity_terms + pi.preferred_anti_affinity_terms
            if len(prefs) > self.MAX_IPA_PREF:
                raise FallbackNeeded("more preferred IPA terms than kernel slots")
            def term_id(term):
                ti = v.ipa_term_lookup(term)
                if ti is None or ti >= ta:
                    raise FallbackNeeded("IPA vocab stale; re-register pod")
                return ti

            for j, term in enumerate(pi.required_affinity_terms):
                ti = term_id(term)
                aff_t[j] = ti
                aff_self[j] = term.matches(pod)
            for j, term in enumerate(pi.required_anti_affinity_terms):
                ti = term_id(term)
                anti_t[j] = ti
                anti_add[ti] += 1
            n_aff_pref = len(pi.preferred_affinity_terms)
            for j, (w_, term) in enumerate(prefs):
                ti = term_id(term)
                sign = 1 if j < n_aff_pref else -1
                pref_t[j] = ti
                pref_w[j] = sign * w_
                pref_add[ti] += sign * w_
        f["ipa_aff_t"] = aff_t
        f["ipa_aff_self"] = aff_self
        f["ipa_anti_t"] = anti_t
        f["ipa_pref_t"] = pref_t
        f["ipa_pref_w"] = pref_w
        f["ipa_anti_add"] = anti_add
        f["ipa_pref_add"] = pref_add

    def _affinity_sig(self, pod: Pod) -> tuple[int, str | None]:
        """Intern the pod's (nodeSelector, node affinity) spec into a
        (signature id, pinned node name | None); identical pods share one
        table row.

        match_fields support is limited to the reference's own fast path —
        a single term whose fields are `In(metadata.name, [...])`
        (node_affinity.go:159) — expressed as a node allowlist. When that
        allowlist is a SINGLE name and the term carries no expressions, the
        pin comes back as a per-pod feature and NO signature is minted:
        a daemonset-style run of uniquely-pinned pods must share one table
        row, not grow the [sigs, nodes] allow matrix by one row per pod
        (which made 5k daemon pods rebuild+upload a 5k-row table per wave).
        """
        aff = pod.spec.affinity
        node_aff = aff.node_affinity if aff else None
        required = node_aff.required if node_aff else None
        preferred = tuple(node_aff.preferred) if node_aff else ()
        selector = tuple(sorted(pod.spec.node_selector.items()))
        key = (selector, repr(required), repr(preferred))
        cached = self._aff_sigs.get(key)
        if cached is not None:
            return cached

        pin: str | None = None
        allowed_names: frozenset | None = None
        terms_for_groups = None
        if required is not None:
            terms = required.terms
            if any(t.match_fields for t in terms):
                if len(terms) != 1 or not all(
                    fr.key == _FIELD_HOSTNAME and fr.operator == "In"
                    for fr in terms[0].match_fields
                ):
                    raise FallbackNeeded("match_fields beyond In(metadata.name)")
                allowed: set[str] | None = None
                for fr in terms[0].match_fields:
                    vals = set(fr.values)
                    allowed = vals if allowed is None else (allowed & vals)
                allowed_names = frozenset(allowed or ())
                if (len(allowed_names) == 1
                        and not terms[0].match_expressions):
                    pin = next(iter(allowed_names))
                    allowed_names = None
                else:
                    # strip fields; expressions still gate per group
                    from ..api.types import NodeSelector, NodeSelectorTerm
                    terms_for_groups = NodeSelector(
                        (NodeSelectorTerm(terms[0].match_expressions, ()),)
                    )
            else:
                terms_for_groups = required
        for term in preferred:
            if term.preference.match_fields:
                raise FallbackNeeded("preferred term with match_fields")

        # intern the residual spec — shared across every pod whose affinity
        # differs only by its pinned name
        spec_key = (selector, repr(terms_for_groups), repr(preferred),
                    allowed_names)
        sig = self._aff_spec_ids.get(spec_key)
        if sig is None:
            sig = len(self._aff_specs)
            self._aff_specs.append(
                (dict(pod.spec.node_selector), terms_for_groups, preferred,
                 allowed_names)
            )
            self._aff_spec_ids[spec_key] = sig
        result = (sig, pin)
        self._aff_sigs[key] = result
        return result

    def affinity_tables(self, planes: Planes) -> dict[str, np.ndarray]:
        """Materialize the signature rows against the current group vocab and
        node set; cached until either grows or the node list changes."""
        v = self.vocabs
        n_sigs = len(self._aff_specs)
        a = next_pow2(n_sigs, 1)
        g = next_pow2(len(v.groups), 1)
        # actual group count must key the cache (not just its pow2 bucket):
        # new groups within the same bucket need their columns evaluated for
        # EVERY signature, which the incremental new-rows-only path can't do
        base_key = (a, g, len(v.groups), planes.nb, hash(tuple(planes.node_names)))
        prev = self._aff_tables
        if prev is not None and self._aff_tables_key == (base_key, n_sigs):
            return prev
        # signatures are append-only; when only new ones arrived (same group
        # vocab, node set, and buckets), fill just the new rows instead of
        # re-evaluating every prior spec — O(new) on the scheduling hot path
        if prev is not None and self._aff_tables_key[0] == base_key:
            start = self._aff_tables_key[1]
            # fresh dict object: TPUBackend.device_inputs re-uploads on
            # identity change, and the rows below mutate in place
            tables = dict(prev)
        else:
            start = 0
            tables = {
                "aff_match": np.ones((a, g), bool),
                "aff_pref": np.zeros((a, g), np.int32),
                "aff_allow": np.ones((a, planes.nb), bool),
                "aff_has_pref": np.zeros(a, bool),
            }
        group_labels = [dict(v.groups.key(gi)) for gi in range(len(v.groups))]
        for si in range(start, n_sigs):
            node_selector, terms, preferred, allowed_names = self._aff_specs[si]
            tables["aff_has_pref"][si] = bool(preferred)
            if allowed_names is not None:
                tables["aff_allow"][si, :] = False
                for nm in allowed_names:
                    i = planes.node_index.get(nm)
                    if i is not None:
                        tables["aff_allow"][si, i] = True
            for gi, labels in enumerate(group_labels):
                ok = all(labels.get(kk) == vv for kk, vv in node_selector.items())
                if ok and terms is not None:
                    ok = terms.matches(labels, {})
                tables["aff_match"][si, gi] = ok
                tables["aff_pref"][si, gi] = sum(
                    t.weight for t in preferred if t.preference.matches(labels, {})
                )
        self._aff_tables, self._aff_tables_key = tables, (base_key, n_sigs)
        return tables


def placement_masks(planes: Planes, node_name_lists: list[list[str]],
                    n_rows: int | None = None) -> np.ndarray:
    """[D, Nb] bool row-mask stack for the gang kernel, one row per
    placement's node-name list in HOST PLACEMENT ORDER (the gang winner
    tie-break is first-max over this order). Names missing from the plane
    index are skipped — the host dry run skips snapshot misses the same
    way. Rows beyond the given lists (shape padding up to `n_rows`) stay
    all-False: an empty valid set places nobody and can never win."""
    d = len(node_name_lists) if n_rows is None else max(n_rows, len(node_name_lists))
    masks = np.zeros((d, planes.nb), np.bool_)
    for row, names in enumerate(node_name_lists):
        for nm in names:
            i = planes.node_index.get(nm)
            if i is not None:
                masks[row, i] = True
    return masks


def stack_features(feats: list[dict[str, np.ndarray]]) -> dict[str, np.ndarray]:
    """Stack per-pod feature dicts into [P, ...] batched arrays."""
    if not feats:
        raise ValueError("no features to stack")
    return {k: np.stack([f[k] for f in feats]) for k in feats[0]}


def pad_features(stacked: dict[str, np.ndarray], pad_to: int) -> dict[str, np.ndarray]:
    """Pad a stacked feature batch to `pad_to` pod slots with inactive rows
    (active=False: the scan step discards their placements and draws no
    tie-break words). One static batch shape per configured wave size means
    ONE XLA compile — a fresh compile per odd tail size costs far more than
    scanning dead steps."""
    p = stacked["active"].shape[0]
    if p >= pad_to:
        return stacked
    out = {}
    for k, a in stacked.items():
        pad = np.zeros((pad_to - p,) + a.shape[1:], a.dtype)
        if k in ("ipa_aff_t", "ipa_anti_t", "ipa_pref_t"):
            pad -= 1  # -1 = inactive term slot
        out[k] = np.concatenate([a, pad])
    return out


# --------------------------------------------------------------------------
# feature packing: ONE host→device transfer per wave
# --------------------------------------------------------------------------

def pack_features(stacked: dict[str, np.ndarray]):
    """Pack a stacked feature batch into a single [P, F] int32 buffer plus
    a STATIC layout tuple. A wave's features are ~30 tiny arrays; over a
    tunneled device each array is its own host→device transfer paying full
    round-trip latency, so the batch ships as one buffer and the kernel
    unpacks it inside the trace (slices fuse away under XLA).

    bool columns ride as 0/1 int32, uint32 bitmask columns are bitcast
    (same bytes); values are reconstructed exactly — bit-identity holds.
    """
    cols = []
    layout = []
    off = 0
    for name in sorted(stacked):
        a = stacked[name]
        a2 = a[:, None] if a.ndim == 1 else a
        width = a2.shape[1]
        if a.dtype == np.uint32:
            tag = "uint32"
            cols.append(a2.view(np.int32))
        elif a.dtype == np.bool_:
            tag = "bool"
            cols.append(a2.astype(np.int32))
        else:
            tag = "int32"
            cols.append(a2.astype(np.int32, copy=False))
        layout.append((name, off, width, a.ndim, tag))
        off += width
    return np.ascontiguousarray(np.concatenate(cols, axis=1)), tuple(layout)


def unpack_features(buf, layout):
    """Inverse of pack_features INSIDE a jit trace (layout is static)."""
    import jax
    import jax.numpy as jnp

    out = {}
    for name, off, width, ndim, tag in layout:
        sl = buf[:, off:off + width]
        if tag == "bool":
            sl = sl.astype(bool)
        elif tag == "uint32":
            sl = jax.lax.bitcast_convert_type(sl, jnp.uint32)
        if ndim == 1:
            sl = sl[:, 0]
        out[name] = sl
    return out
