"""Cluster vocabularies: the string→column-id maps behind the device planes.

The dense kernels cannot consume strings, selectors, or taint structs; every
categorical dimension of cluster state is interned into a small append-only
vocabulary, and the planes carry integer ids into these vocabularies.

Reference points (what each vocab re-expresses TPU-natively):
- taints: pkg/scheduler/framework/plugins/tainttoleration — distinct
  (key, value, effect) triples; a pod's tolerations are pre-evaluated host-side
  into a per-vocab-entry boolean, so the device check is a gather.
- node groups: nodes sharing identical label maps (scheduler_perf clusters have
  a handful of label templates across 5k nodes); NodeAffinity/nodeSelector
  required matching (node_affinity.go:218) is evaluated once per (pod, group)
  host-side and gathered per node on device.
- selector signatures: (namespace, selector-canonical) pairs used by
  PodTopologySpread counting (podtopologyspread/filtering.go:97) — per-node
  matching-pod counts are maintained as a [nodes, S] plane so domain counts
  become segment-sums on device.
- ports: distinct (protocol, port) pairs → bit positions in the used-port
  bitset planes (node_ports.go:75).
- images: image name → column in the per-node image-size plane
  (image_locality.go:93-105).
"""

from __future__ import annotations

from typing import Hashable, Iterator


class Vocab:
    """Append-only intern table: hashable key → dense id."""

    __slots__ = ("_index", "_keys")

    def __init__(self) -> None:
        self._index: dict[Hashable, int] = {}
        self._keys: list[Hashable] = []

    def id(self, key: Hashable) -> int:
        i = self._index.get(key)
        if i is None:
            i = len(self._keys)
            self._index[key] = i
            self._keys.append(key)
        return i

    def get(self, key: Hashable) -> int | None:
        return self._index.get(key)

    def key(self, i: int) -> Hashable:
        return self._keys[i]

    def __len__(self) -> int:
        return len(self._keys)

    def __iter__(self) -> Iterator[Hashable]:
        return iter(self._keys)

    def __contains__(self, key: Hashable) -> bool:
        return key in self._index


def next_pow2(n: int, floor: int = 1) -> int:
    """Bucketed padding size: smallest power of two ≥ max(n, floor).

    Static shapes are an XLA requirement; bucketing bounds the number of
    distinct compiled programs to O(log n) per dimension.
    """
    n = max(n, floor)
    p = 1
    while p < n:
        p <<= 1
    return p


class ClusterVocabs:
    """All vocabularies for one cluster, shared by planes + feature extractor."""

    def __init__(self) -> None:
        # (key, value, effect) for NoSchedule/NoExecute taints
        self.taints = Vocab()
        # (key, value) for PreferNoSchedule taints (scored, not filtered)
        self.prefer_taints = Vocab()
        # canonical node-label tuple → node group id
        self.groups = Vocab()
        # topology key (e.g. topology.kubernetes.io/zone) → plane column
        self.topo_keys = Vocab()
        # per topology key: value → domain id
        self.topo_domains: dict[int, Vocab] = {}
        # (namespace, selector canonical) → selector-signature column.
        # matcher objects kept alongside for host-side pod matching.
        self.selectors = Vocab()
        self.selector_matchers: list[tuple[str, object]] = []  # (namespace, selector)
        # (protocol, port) → bit position
        self.ports = Vocab()
        # image name → column
        self.images = Vocab()
        # inter-pod affinity terms: (namespaces, selector canonical, topo key
        # idx) → term column (interpodaffinity/filtering.go:91 — the dense
        # analogue of topologyToMatchedTermCount keys its planes by term)
        self.ipa_terms = Vocab()
        self.ipa_term_matchers: list[tuple[frozenset, object, int]] = []

    def ipa_term_id(self, term) -> int:
        """Intern an AffinityTerm (nodeinfo.AffinityTerm shape: resolved
        namespaces frozenset + selector + topology_key)."""
        ki = self.topo_keys.id(term.topology_key)
        sel = term.selector
        key = (term.namespaces, sel.canonical() if sel is not None else None, ki)
        existing = self.ipa_terms.get(key)
        if existing is not None:
            return existing
        i = self.ipa_terms.id(key)
        self.ipa_term_matchers.append((term.namespaces, sel, ki))
        return i

    def ipa_term_lookup(self, term) -> int | None:
        """Existing id for an AffinityTerm, or None when not interned (the
        read-only counterpart of ipa_term_id — must mirror its key shape)."""
        ki = self.topo_keys.get(term.topology_key)
        if ki is None:
            return None
        sel = term.selector
        return self.ipa_terms.get(
            (term.namespaces, sel.canonical() if sel is not None else None, ki)
        )

    def domain_vocab(self, key_idx: int) -> Vocab:
        v = self.topo_domains.get(key_idx)
        if v is None:
            v = Vocab()
            self.topo_domains[key_idx] = v
        return v

    def group_of_labels(self, labels: dict[str, str]) -> int:
        return self.groups.id(tuple(sorted(labels.items())))

    def selector_id(self, namespace: str, selector) -> int:
        key = (namespace, selector.canonical())
        existing = self.selectors.get(key)
        if existing is not None:
            return existing
        i = self.selectors.id(key)
        self.selector_matchers.append((namespace, selector))
        return i
