"""kubernetes_tpu — a TPU-native control plane + batched TPU scheduler.

A from-scratch framework with the capabilities of Kubernetes (reference:
bart0sh/kubernetes @ ~v1.36-dev), re-designed TPU-first:

- ``api``       typed object core (Pod/Node/PodGroup/...), quantities, selectors
                (reference: staging/src/k8s.io/api + apimachinery)
- ``store``     versioned ordered KV + watch bus (reference: etcd + apiserver storage)
- ``client``    reflector/informer/workqueue equivalents (reference: client-go)
- ``scheduler`` cache/snapshot, 3-tier queue, framework runtime, plugins
                (reference: pkg/scheduler)
- ``ops``       dense pods x nodes feasibility/score kernels (JAX/Pallas) — the
                TPU-native replacement for framework/parallelize goroutine fan-out
- ``parallel``  device mesh + shard_map collectives (nodes axis over ICI)
- ``utils``     metrics, clock, logging, feature gates
"""

__version__ = "0.1.0"
