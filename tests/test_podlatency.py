"""Pod latency ledger: quantile golden vs numpy, ledger semantics, the
ledger-on/off bit-compat golden, trace-bench determinism, and the
regression gate's mechanics (including the synthetically-slowed-segment
failure the gate exists to catch)."""

from __future__ import annotations

import json
import random

import numpy as np
import pytest

from kubernetes_tpu.perf.regression_gate import (
    compare,
    load_rows,
    run_gate,
)
from kubernetes_tpu.scheduler import Profile, Scheduler
from kubernetes_tpu.scheduler.metrics import SchedulerMetrics
from kubernetes_tpu.scheduler.tpu import podlatency
from kubernetes_tpu.scheduler.tpu.podlatency import (
    EDGES,
    LEDGER_SERIES,
    SEGMENT_NAMES,
    PodLatencyLedger,
    StreamingQuantile,
)
from kubernetes_tpu.store import Store
from tests.wrappers import make_node, make_pod

# ------------------------------------------------------- streaming quantile


class TestStreamingQuantileGolden:
    """The ledger's estimator must agree with numpy's inverted-CDF
    percentile — the definition the README promises — on fixed seeds."""

    @pytest.mark.parametrize("seed", [0, 7, 1234])
    @pytest.mark.parametrize("n", [1, 5, 100, 1000])
    def test_matches_numpy_inverted_cdf(self, seed, n):
        rng = random.Random(seed)
        values = [rng.expovariate(3.0) for _ in range(n)]
        est = StreamingQuantile(window=max(n, 2))
        for v in values:
            est.add(v)
        for q in (0.5, 0.9, 0.99):
            expected = float(np.percentile(values, q * 100,
                                           method="inverted_cdf"))
            assert est.quantile(q) == expected

    def test_window_compression_is_deterministic(self):
        """Past the window, the oldest half is dropped — quantiles stay
        exact over the independently-simulated retained slice."""
        rng = random.Random(42)
        values = [rng.expovariate(1.0) for _ in range(1000)]
        est = StreamingQuantile(window=64)
        retained: list[float] = []
        for v in values:
            est.add(v)
            retained.append(v)
            if len(retained) > 64:
                del retained[:32]
        assert est.n() == len(retained)
        assert est.total_n == 1000
        for q in (0.5, 0.99):
            expected = float(np.percentile(retained, q * 100,
                                           method="inverted_cdf"))
            assert est.quantile(q) == expected

    def test_empty_returns_none(self):
        assert StreamingQuantile().quantile(0.5) is None


# ----------------------------------------------------------------- ledger


def stamp_all(ledger, key, t0=100.0, wave_id=None, clock=None):
    """Stamp every edge at exact binary-fraction offsets via a fake clock."""
    offsets = {  # edge -> perf_counter value (all exact in float64)
        "watch_arrival": t0,
        "queue_admission": t0 + 0.5,
        "wave_admission": t0 + 1.0,
        "kernel_verdict": t0 + 1.25,
        "bind_dispatch": t0 + 1.375,
        "bind_commit": t0 + 1.5,
    }
    for edge in EDGES[:-1]:
        if edge not in offsets:  # gang_wait_*: gang pods only
            continue
        clock.now = offsets[edge]
        ledger.stamp(key, edge, wave_id=wave_id)
    return offsets


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


@pytest.fixture
def clock(monkeypatch):
    c = FakeClock()
    monkeypatch.setattr(podlatency.time, "perf_counter", c)
    return c


class TestLedger:
    def test_exact_segment_decomposition(self, clock):
        ledger = PodLatencyLedger()
        stamp_all(ledger, "default/p0", wave_id=3, clock=clock)
        entry = ledger.complete("default/p0")
        assert entry.segments == {
            "informer": 0.5,
            "queue_wait": 0.5,
            "kernel": 0.25,
            "bind_dispatch": 0.125,
            "bind_commit": 0.125,
            "e2e": 1.5,
        }
        d = entry.to_dict()
        assert d["wave_id"] == 3
        assert d["span"] == "wave/3"  # exemplar link to the wave span

    def test_first_wins_and_last_wins_edges(self, clock):
        ledger = PodLatencyLedger()
        clock.now = 10.0
        ledger.stamp("default/p0", "watch_arrival")
        clock.now = 20.0
        ledger.stamp("default/p0", "watch_arrival")  # requeue: must not move
        ledger.stamp("default/p0", "wave_admission")
        clock.now = 30.0
        ledger.stamp("default/p0", "wave_admission")  # retry: must move
        entry = ledger._open["default/p0"]
        assert entry.stamps["watch_arrival"] == 10.0
        assert entry.stamps["wave_admission"] == 30.0

    def test_late_status_ack_lands_on_retained_entry(self, clock):
        metrics = SchedulerMetrics()
        ledger = PodLatencyLedger(metrics=metrics)
        stamp_all(ledger, "default/p0", clock=clock)
        ledger.complete("default/p0")
        clock.now = 102.0  # bind_commit was at 101.5
        ledger.stamp("default/p0", "status_ack")
        (entry,) = ledger._completed
        assert entry.segments["status_ack"] == 0.5
        hist = metrics.registry.get(LEDGER_SERIES[0])
        assert hist.count("status_ack") == 1

    def test_histogram_and_gauges_land(self, clock):
        metrics = SchedulerMetrics()
        ledger = PodLatencyLedger(metrics=metrics)
        stamp_all(ledger, "default/p0", clock=clock)
        ledger.complete("default/p0")
        hist = metrics.registry.get(LEDGER_SERIES[0])
        for seg in ("informer", "queue_wait", "kernel", "e2e"):
            assert hist.count(seg) == 1
        ledger.update_gauges()
        gauge = metrics.registry.get(LEDGER_SERIES[1])
        assert gauge.get("e2e", "p50") == 1.5
        assert gauge.get("kernel", "p99") == 0.25

    def test_forget_drops_open_entry(self, clock):
        ledger = PodLatencyLedger()
        clock.now = 1.0
        ledger.stamp("default/p0", "watch_arrival")
        ledger.forget("default/p0")
        assert ledger.complete("default/p0") is None

    def test_open_cap_sheds_oldest_first(self, clock):
        ledger = PodLatencyLedger(open_cap=4)
        for i in range(6):
            clock.now = float(i)
            ledger.stamp(f"default/p{i}", "watch_arrival")
        assert len(ledger._open) == 4
        assert ledger.dropped_open == 2
        assert "default/p0" not in ledger._open  # oldest shed first
        assert "default/p5" in ledger._open

    def test_disabled_ledger_is_inert(self, clock):
        ledger = PodLatencyLedger()
        ledger.enabled = False
        clock.now = 1.0
        ledger.stamp("default/p0", "watch_arrival")
        assert ledger.complete("default/p0") is None
        assert ledger.summary()["pods_completed"] == 0

    def test_snapshot_last_and_slowest(self, clock):
        ledger = PodLatencyLedger()
        for i, t0 in enumerate([100.0, 200.0, 300.0]):
            key = f"default/p{i}"
            stamp_all(ledger, key, t0=t0, clock=clock)
            if i == 1:  # make p1 the slowest e2e
                clock.now = t0 + 9.0
                ledger.stamp(key, "bind_commit")
            ledger.complete(key)
        snap = ledger.snapshot(last=2, slowest=1)
        assert [e["pod"] for e in snap["last"]] == ["default/p1",
                                                    "default/p2"]
        assert snap["slowest"][0]["pod"] == "default/p1"
        assert snap["summary"]["pods_completed"] == 3
        assert set(snap["summary"]["segments"]) <= set(SEGMENT_NAMES)

    def test_completed_ring_bounded(self, clock):
        ledger = PodLatencyLedger(capacity=2)
        for i in range(5):
            key = f"default/p{i}"
            stamp_all(ledger, key, t0=10.0 * i, clock=clock)
            ledger.complete(key)
        assert len(ledger._completed) == 2
        assert ledger.completed_total == 5


# -------------------------------------------------- ledger on/off golden


class TestLedgerBitCompat:
    def test_placements_identical_ledger_on_vs_off(self):
        """The ledger consumes no rng and influences no decision: the same
        seeded wave workload places identically with it on (production
        default) and off."""

        def run(ledger_on: bool) -> dict[str, str]:
            store = Store()
            for i in range(8):
                store.create(make_node(f"n{i}", cpu="4", mem="8Gi",
                                       zone=f"z{i % 2}"))
            sched = Scheduler(
                store,
                profiles=[Profile(backend="tpu", wave_size=16)],
                metrics=SchedulerMetrics(),
                seed=11,
            )
            sched.flight_recorder.pod_ledger.enabled = ledger_on
            sched.start()
            for i in range(24):
                kind = i % 3
                cpu, mem = [("1", "1Gi"), ("900m", "900Mi"),
                            ("800m", "800Mi")][kind]
                store.create(make_pod(f"g{i:02d}", cpu=cpu, mem=mem,
                                      labels={"app": "abc"[kind]}))
            sched.pump()
            sched.schedule_pending()
            return {p.meta.key: p.spec.node_name for p in store.pods()}

        on, off = run(True), run(False)
        assert on == off
        assert any(on.values())  # the workload actually scheduled

    def test_ledger_populated_under_wave_path(self):
        """With the ledger on (default), the wave pipeline completes an
        entry per bound pod, with every pipeline segment present."""
        store = Store()
        for i in range(4):
            store.create(make_node(f"n{i}", cpu="8", mem="16Gi"))
        sched = Scheduler(
            store,
            profiles=[Profile(backend="tpu", wave_size=8)],
            metrics=SchedulerMetrics(),
            seed=3,
        )
        sched.start()
        for i in range(10):
            store.create(make_pod(f"w{i}", cpu="500m", mem="256Mi"))
        sched.pump()
        sched.schedule_pending()
        ledger = sched.flight_recorder.pod_ledger
        bound = sum(1 for p in store.pods() if p.spec.node_name)
        assert bound == 10
        assert ledger.completed_total == bound
        segs = ledger.segment_quantiles()
        for name in ("informer", "queue_wait", "kernel", "bind_commit",
                     "e2e"):
            assert segs[name]["n"] == bound


# ----------------------------------------------- trace bench determinism


class TestTraceBenchDeterminism:
    def test_same_seed_same_sli_rows(self):
        """Two runs at the same seed produce identical deterministic rows
        (virtual-time SLI — satellite contract for `bench.py --trace`)."""
        from kubernetes_tpu.perf.trace_bench import (
            DETERMINISTIC_KEYS,
            run_trace_bench,
        )

        rows = [run_trace_bench(shape="poisson", seed=7, pods=120)
                for _ in range(2)]
        a, b = [{k: r[k] for k in DETERMINISTIC_KEYS} for r in rows]
        assert a == b
        assert rows[0]["scheduled"] == 120
        assert rows[0]["sli_p50_ok"] and rows[0]["sli_p99_ok"]
        # the ledger's wall-clock breakdown rides along as diagnostics
        assert rows[0]["segments"]["e2e"]["n"] == 120

    def test_different_shapes_are_different_traces(self):
        from kubernetes_tpu.testing.chaos import ArrivalTrace

        base = ArrivalTrace(seed=7, pods=50)
        assert base.arrivals() == ArrivalTrace(seed=7, pods=50,
                                               shape="burst").arrivals()
        poisson = ArrivalTrace(seed=7, pods=50, shape="poisson").arrivals()
        diurnal = ArrivalTrace(seed=7, pods=50, shape="diurnal").arrivals()
        assert poisson != base.arrivals()
        assert diurnal != poisson
        # replayable: same seed + shape -> same trace
        assert poisson == ArrivalTrace(seed=7, pods=50,
                                       shape="poisson").arrivals()


# -------------------------------------------------------- regression gate


BASE_ROW = {
    "metric": "trace_sli_poisson",
    "value": 0.15,
    "unit": "s (virtual p50)",
    "trace_p50_s": 0.15,
    "trace_p99_s": 0.55,
    "sli_p50_ok": True,
    "sli_p99_ok": True,
    "segments": {
        "kernel": {"p50": 0.010, "p99": 0.050, "n": 200},
        "queue_wait": {"p50": 0.001, "p99": 0.004, "n": 200},
    },
}

THROUGHPUT_ROW = {
    "metric": "scheduling_throughput_basic_5000",
    "value": 300.0,
    "unit": "pods/s",
    "sli_p99_s": 12.0,
}


def write_artifact(path, *rows):
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return str(path)


class TestRegressionGate:
    def test_self_diff_passes(self, tmp_path):
        art = write_artifact(tmp_path / "BENCH_a.json", BASE_ROW,
                             THROUGHPUT_ROW)
        assert run_gate(art, art) == 0

    def test_within_tolerance_passes(self, tmp_path):
        old = write_artifact(tmp_path / "BENCH_old.json", THROUGHPUT_ROW)
        new_row = dict(THROUGHPUT_ROW, value=280.0)  # -6.7%
        new = write_artifact(tmp_path / "BENCH_new.json", new_row)
        assert run_gate(old, new) == 0

    def test_throughput_regression_fails(self, tmp_path, capsys):
        old = write_artifact(tmp_path / "BENCH_old.json", THROUGHPUT_ROW)
        new_row = dict(THROUGHPUT_ROW, value=250.0)  # -16.7%
        new = write_artifact(tmp_path / "BENCH_new.json", new_row)
        assert run_gate(old, new) == 1
        assert "REGRESSION" in capsys.readouterr().out

    def test_slowed_segment_fails_and_is_named(self, tmp_path, capsys):
        """The acceptance demo: synthetically slow the kernel segment,
        inflating trace_p99_s — the gate fails AND names the segment."""
        old = write_artifact(tmp_path / "BENCH_old.json", BASE_ROW)
        slowed = json.loads(json.dumps(BASE_ROW))  # deep copy
        slowed["trace_p99_s"] = 1.2   # > 0.55 * 1.1
        slowed["segments"]["kernel"] = {"p50": 0.450, "p99": 0.900, "n": 200}
        new = write_artifact(tmp_path / "BENCH_new.json", slowed)
        assert run_gate(old, new) == 1
        out = capsys.readouterr().out
        assert "trace_p99_s" in out
        assert "segment 'kernel'" in out  # the delta explanation

    def test_blown_sli_flag_fails_outside_tolerance_band(self, tmp_path):
        old = write_artifact(tmp_path / "BENCH_old.json", BASE_ROW)
        blown = dict(BASE_ROW, sli_p99_ok=False)
        new = write_artifact(tmp_path / "BENCH_new.json", blown)
        assert run_gate(old, new) == 1

    def test_no_common_metrics_passes(self, tmp_path):
        old = write_artifact(tmp_path / "BENCH_old.json", THROUGHPUT_ROW)
        new = write_artifact(tmp_path / "BENCH_new.json", BASE_ROW)
        assert run_gate(old, new) == 0

    def test_loads_wrapper_artifact(self, tmp_path):
        """BENCH_r*.json shape: rows embedded as JSON lines in 'tail'."""
        tail = "noise\n" + json.dumps(THROUGHPUT_ROW) + "\nmore noise\n"
        wrapper = {"n": 5, "cmd": "python bench.py", "rc": 0, "tail": tail}
        p = tmp_path / "BENCH_r99.json"
        p.write_text(json.dumps(wrapper, indent=2))
        rows = load_rows(str(p))
        assert rows["scheduling_throughput_basic_5000"]["value"] == 300.0

    def test_loads_jsonl_artifact(self, tmp_path):
        art = write_artifact(tmp_path / "BENCH_SUITE.jsonl", BASE_ROW,
                             THROUGHPUT_ROW)
        rows = load_rows(art)
        assert set(rows) == {"trace_sli_poisson",
                             "scheduling_throughput_basic_5000"}

    def test_compare_improvement_never_fails(self):
        old = {"m": dict(THROUGHPUT_ROW, metric="m")}
        new = {"m": dict(THROUGHPUT_ROW, metric="m", value=400.0)}
        assert compare(old, new) == []


# ------------------------------------------------------------------ zpage


class TestPodLatencyZpage:
    def test_served_with_params(self):
        import urllib.error
        import urllib.request

        from kubernetes_tpu.cmd.scheduler import SchedulerServer
        from kubernetes_tpu.config.types import SchedulerConfiguration

        store = Store()
        store.create(make_node("n0", cpu="8", mem="16Gi"))
        for i in range(6):
            store.create(make_pod(f"z{i}", cpu="500m", mem="256Mi"))
        cfg = SchedulerConfiguration()
        cfg.profiles[0].backend = "tpu"
        cfg.profiles[0].wave_size = 4
        server = SchedulerServer(store, cfg)
        port = server.serve(0)
        try:
            server.scheduler.start()
            server.scheduler.pump()
            server.scheduler.schedule_pending()

            url = (f"http://127.0.0.1:{port}"
                   "/debug/podlatency?last=2&slowest=1")
            with urllib.request.urlopen(url) as r:
                assert r.status == 200
                assert r.headers.get("Content-Type") == "application/json"
                payload = json.loads(r.read())
            assert payload["summary"]["pods_completed"] == 6
            assert len(payload["last"]) == 2
            assert len(payload["slowest"]) == 1
            assert "segments" in payload["last"][0]

            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/podlatency?last=abc")
                raise AssertionError("expected 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            server.shutdown()
